"""Performance/resource monitoring: bounded metric history, threshold alerts,
trend analysis, health verdicts.

Parity with /root/reference/src/observability/monitoring.py:38-341: a
``PerformanceMonitor`` with deque-bounded per-metric history and alert
callbacks, system collection (psutil when present), and a
``ResourceMonitor`` layering default thresholds, linear-regression trend
analysis, and a health verdict with recommendations. Adds a TPU device
collector (HBM occupancy via jax memory_stats).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

try:
    import psutil

    PSUTIL_AVAILABLE = True
except ImportError:  # pragma: no cover
    PSUTIL_AVAILABLE = False


@dataclass
class Alert:
    metric: str
    value: float
    threshold: float
    severity: str
    at: float = field(default_factory=time.perf_counter)


class PerformanceMonitor:
    def __init__(self, history: int = 512) -> None:
        self._history: dict[str, deque] = {}
        self._history_len = history
        self._thresholds: dict[str, tuple[float, str]] = {}
        self._callbacks: list[Callable[[Alert], None]] = []
        self._alerts: deque = deque(maxlen=256)
        self._lock = threading.Lock()

    def set_threshold(self, metric: str, threshold: float, severity: str = "warning") -> None:
        self._thresholds[metric] = (threshold, severity)

    def on_alert(self, callback: Callable[[Alert], None]) -> None:
        self._callbacks.append(callback)

    def record(self, metric: str, value: float) -> None:
        with self._lock:
            series = self._history.setdefault(metric, deque(maxlen=self._history_len))
            series.append((time.perf_counter(), value))
        threshold = self._thresholds.get(metric)
        if threshold and value > threshold[0]:
            alert = Alert(metric, value, threshold[0], threshold[1])
            self._alerts.append(alert)
            for cb in self._callbacks:
                try:
                    cb(alert)
                except Exception:  # noqa: BLE001 — an alert callback must not break recording
                    pass

    def series(self, metric: str) -> list[tuple[float, float]]:
        with self._lock:
            return list(self._history.get(metric, ()))

    def summary(self, metric: str) -> dict[str, float]:
        values = [v for _, v in self.series(metric)]
        if not values:
            return {"count": 0}
        ordered = sorted(values)
        return {
            "count": len(values),
            "mean": sum(values) / len(values),
            "p50": ordered[len(ordered) // 2],
            "p95": ordered[min(int(len(ordered) * 0.95), len(ordered) - 1)],
            "max": ordered[-1],
        }

    def trend(self, metric: str) -> dict[str, Any]:
        """Least-squares slope over the history (reference's linear-regression
        trend, monitoring.py:259-287)."""
        points = self.series(metric)
        if len(points) < 3:
            return {"direction": "unknown", "slope": 0.0}
        t0 = points[0][0]
        xs = [t - t0 for t, _ in points]
        ys = [v for _, v in points]
        n = len(xs)
        mean_x, mean_y = sum(xs) / n, sum(ys) / n
        denom = sum((x - mean_x) ** 2 for x in xs) or 1e-9
        slope = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, ys)) / denom
        direction = "rising" if slope > 1e-9 else "falling" if slope < -1e-9 else "flat"
        return {"direction": direction, "slope": slope}

    def recent_alerts(self) -> list[Alert]:
        return list(self._alerts)

    def collect_system(self) -> dict[str, float]:
        out: dict[str, float] = {}
        if PSUTIL_AVAILABLE:
            out["cpu_percent"] = psutil.cpu_percent(interval=None)
            mem = psutil.virtual_memory()
            out["memory_percent"] = mem.percent
            out["memory_available_mb"] = mem.available / 1e6
        try:
            import jax

            for dev in jax.devices():
                stats = dev.memory_stats() or {}
                if "bytes_in_use" in stats and stats.get("bytes_limit"):
                    out[f"hbm_percent_dev{dev.id}"] = (
                        100.0 * stats["bytes_in_use"] / stats["bytes_limit"]
                    )
        except Exception:  # noqa: BLE001 — HBM scrape is best-effort telemetry
            pass
        for metric, value in out.items():
            self.record(metric, value)
        return out


class ResourceMonitor:
    """Default thresholds + health verdict + recommendations."""

    DEFAULT_THRESHOLDS = {
        "cpu_percent": (90.0, "warning"),
        "memory_percent": (90.0, "critical"),
        "request_latency_ms": (2000.0, "warning"),
    }

    def __init__(self, monitor: Optional[PerformanceMonitor] = None) -> None:
        self.monitor = monitor or PerformanceMonitor()
        for metric, (threshold, severity) in self.DEFAULT_THRESHOLDS.items():
            self.monitor.set_threshold(metric, threshold, severity)

    def health_verdict(self) -> dict[str, Any]:
        system = self.monitor.collect_system()
        alerts = self.monitor.recent_alerts()
        recent = [a for a in alerts if time.perf_counter() - a.at < 300]
        critical = [a for a in recent if a.severity == "critical"]
        status = "unhealthy" if critical else "degraded" if recent else "healthy"
        recommendations = []
        if system.get("memory_percent", 0) > 80:
            recommendations.append("host memory pressure: shrink caches or batch sizes")
        for key, value in system.items():
            if key.startswith("hbm_percent") and value > 85:
                recommendations.append(
                    f"{key}: HBM nearly full — reduce KV window, corpus shards, or batch"
                )
        latency_trend = self.monitor.trend("request_latency_ms")
        if latency_trend["direction"] == "rising":
            recommendations.append("request latency trending up")
        return {
            "status": status,
            "system": system,
            "recent_alerts": len(recent),
            "recommendations": recommendations,
        }


performance_monitor = PerformanceMonitor()
resource_monitor = ResourceMonitor(performance_monitor)
