"""Metrics: Prometheus counters/histograms/gauges with in-memory fallback,
plus TPU device gauges the reference never needed.

Parity with /root/reference/src/observability/metrics.py:46-514 — request/
embedding/retrieval/LLM/system/breaker dimensions, context-manager tracking
helpers, text-or-JSON export — extended with device telemetry: HBM bytes in
use, batch occupancy, generated tokens/s (SURVEY.md §2.10 build column).
"""

from __future__ import annotations

import ast
import time
from contextlib import contextmanager
from typing import Any, Optional

from sentio_tpu.analysis.sanitizer import guard_locksets, make_lock

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    PROMETHEUS_AVAILABLE = True
except ImportError:  # pragma: no cover - prometheus is in the image
    PROMETHEUS_AVAILABLE = False


# fleet telemetry (runtime/worker.py telemetry frames → merge_worker_series):
# distinct worker-originated series a single replica may mint on the router.
# The worker's own registry is already label-bounded (phase/family/reason
# sets are fixed tuples), so this cap only fires if a worker starts lying —
# overflow series are dropped and counted, never merged.
MAX_WORKER_SERIES_PER_REPLICA = 512


def _parse_series_key(key: str):
    """Split an :class:`InMemoryMetrics` storage key (``f"{name}{labels}"``
    with ``labels`` a tuple) back into ``(name, labels)``. Returns
    ``(None, ())`` for keys that do not round-trip — a malformed key from a
    byte-damaged frame must be dropped, not crash the merge."""
    cut = key.find("(")
    if cut < 0:
        return key, ()
    try:
        labels = ast.literal_eval(key[cut:])
    except (ValueError, SyntaxError):
        return None, ()
    if not isinstance(labels, tuple):
        labels = (labels,)
    return key[:cut], tuple(str(item) for item in labels)


@guard_locksets
class InMemoryMetrics:
    """Fallback store mirroring the counter/histogram API shape."""

    WINDOW = 1000  # retained observations per histogram key

    def __init__(self) -> None:
        self._lock = make_lock("InMemoryMetrics._lock")
        self.counters: dict[str, float] = {}  # guarded-by: _lock
        self.histograms: dict[str, list[float]] = {}  # guarded-by: _lock
        self._histo_total: dict[str, int] = {}  # guarded-by: _lock
        self._histo_sum: dict[str, float] = {}  # guarded-by: _lock
        self.gauges: dict[str, float] = {}  # guarded-by: _lock

    def inc(self, name: str, labels: tuple = (), value: float = 1.0) -> None:
        key = f"{name}{labels}"
        with self._lock:
            self.counters[key] = self.counters.get(key, 0.0) + value

    def observe(self, name: str, labels: tuple, value: float) -> None:
        key = f"{name}{labels}"
        with self._lock:
            self.histograms.setdefault(key, []).append(value)
            self._histo_total[key] = self._histo_total.get(key, 0) + 1
            self._histo_sum[key] = self._histo_sum.get(key, 0.0) + value
            if len(self.histograms[key]) > self.WINDOW:
                self.histograms[key] = self.histograms[key][-self.WINDOW:]

    def set_gauge(self, name: str, labels: tuple, value: float) -> None:
        with self._lock:
            self.gauges[f"{name}{labels}"] = value

    def snapshot(self) -> dict[str, Any]:
        """JSON-export aggregates. ``count`` and ``mean`` are TRUE lifetime
        statistics; quantiles come from the retained window (the last
        ``WINDOW`` observations) with ``dropped`` saying how many fell out,
        so exported numbers are never silently presented as full-run
        statistics (the old export reported a truncation-biased p50 under
        the full count)."""
        with self._lock:
            histos = {}
            for k, v in self.histograms.items():
                total = self._histo_total.get(k, len(v))
                s = sorted(v)
                histos[k] = {
                    "count": total,
                    "window": len(v),
                    "dropped": total - len(v),
                    "p50": s[len(s) // 2] if s else 0.0,
                    "p95": s[min(int(len(s) * 0.95), len(s) - 1)] if s else 0.0,
                    "mean": (self._histo_sum.get(k, 0.0) / total) if total else 0.0,
                }
            return {"counters": dict(self.counters), "histograms": histos, "gauges": dict(self.gauges)}


@guard_locksets
class MetricsCollector:
    """One instance per process. With prometheus_client present, metrics
    register in an isolated registry (no default-registry collisions in
    tests); the in-memory store is always maintained for JSON export."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self.memory = InMemoryMetrics()
        self.registry = None
        self._prom: dict[str, Any] = {}
        self._inflight = 0  # guarded-by: _inflight_lock
        self._inflight_lock = make_lock("MetricsCollector._inflight_lock")
        self._serving_last: dict[str, float] = {}
        # per-replica worker-telemetry merge state: cumulative baselines +
        # the (pid, epoch) fence. Lives on the COLLECTOR, not the replica
        # shim — a heal replaces the ProcessReplica object, and losing the
        # baselines there would double-count every series post-heal.
        self._worker_last: dict[int, dict] = {}  # guarded-by: _worker_lock
        self._worker_lock = make_lock("MetricsCollector._worker_lock")
        if PROMETHEUS_AVAILABLE and enabled:
            self.registry = CollectorRegistry()
            self._build_prom()

    def _build_prom(self) -> None:
        r = self.registry
        self._prom = {
            "requests": Counter(
                "sentio_requests_total", "HTTP requests", ["endpoint", "status"], registry=r
            ),
            "request_latency": Histogram(
                "sentio_request_latency_seconds", "request latency", ["endpoint"],
                buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10), registry=r,
            ),
            "embeddings": Counter(
                "sentio_embeddings_total", "texts embedded", ["provider"], registry=r
            ),
            "retrieval_latency": Histogram(
                "sentio_retrieval_latency_seconds", "retrieval latency", ["strategy"], registry=r
            ),
            "llm_tokens": Counter(
                "sentio_llm_tokens_total", "tokens generated", ["kind"], registry=r
            ),
            "llm_latency": Histogram(
                "sentio_llm_latency_seconds", "LLM call latency", ["op"], registry=r
            ),
            "breaker_state": Gauge(
                "sentio_circuit_breaker_state", "0 closed / 1 half-open / 2 open",
                ["name"], registry=r,
            ),
            # TPU device dimension
            "hbm_bytes": Gauge(
                "sentio_tpu_hbm_bytes_in_use", "device memory in use", ["device"], registry=r
            ),
            "batch_occupancy": Histogram(
                "sentio_tpu_batch_occupancy", "coalesced batch fill fraction", ["batcher"],
                buckets=(0.125, 0.25, 0.5, 0.75, 1.0), registry=r,
            ),
            "serving_stat": Gauge(
                "sentio_tpu_serving_stat",
                "decode service point-in-time stats (occupancy, queue depth, pages)",
                ["stat"], registry=r,
            ),
            "serving_total": Counter(
                "sentio_tpu_serving_events_total",
                "decode service lifetime totals", ["event"], registry=r,
            ),
            "tokens_per_s": Gauge(
                "sentio_tpu_decode_tokens_per_second", "decode throughput", [], registry=r
            ),
            # per-sequence serving latency, the two numbers an LLM-serving
            # SLO is actually written against (vLLM exposes the same pair):
            # TTFT = submit → first sampled token host-visible; TPOT = mean
            # seconds per output token after the first
            "ttft": Histogram(
                "sentio_tpu_ttft_seconds", "time to first token", ["path"],
                buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30),
                registry=r,
            ),
            "tpot": Histogram(
                "sentio_tpu_tpot_seconds", "time per output token", ["path"],
                buckets=(0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5),
                registry=r,
            ),
            # engine pump iteration telemetry (the flight recorder's tick
            # events, aggregated): wall time per fused decode dispatch
            "tick_duration": Histogram(
                "sentio_tpu_tick_duration_seconds", "engine pump tick wall time",
                [], buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 5),
                registry=r,
            ),
            # XLA compilations observed at registered jit families
            # (analysis/audit): after warmup this should flatline — any
            # increase is a recompile regression a latency SLO will feel
            "xla_compiles": Counter(
                "sentio_tpu_xla_compiles_total",
                "XLA compilations at registered jit families", ["family"],
                registry=r,
            ),
            # admission-control outcomes: requests dropped at or after the
            # decode-service door (queue_full / draining / deadline /
            # expired / cancelled) — the overload story's headline series;
            # a nonzero rate here is the signal to scale out or shed earlier
            "shed": Counter(
                "sentio_tpu_shed_total",
                "requests shed / expired / cancelled by the decode service",
                ["reason"], registry=r,
            ),
            # the HPA scaling signal (deploy/kubernetes/hpa.yaml): CPU% is
            # meaningless for a TPU pod, queue depth is what saturates a slice
            "inflight": Gauge(
                "sentio_inflight_requests", "requests currently being served", [], registry=r
            ),
            # multi-replica serving tier (runtime/replica.py): per-tenant
            # weighted-fair-queueing outcomes and per-replica occupancy /
            # queue / page-pool gauges — the labels that say WHICH tenant
            # was shed and WHICH replica is hot. Tenant label cardinality
            # is bounded by TenantFairQueue.MAX_TRACKED.
            "tenant_admitted": Counter(
                "sentio_tpu_tenant_admitted_total",
                "requests admitted through weighted fair queueing",
                ["tenant"], registry=r,
            ),
            "tenant_shed": Counter(
                "sentio_tpu_tenant_shed_total",
                "requests shed by weighted fair queueing",
                ["tenant", "reason"], registry=r,
            ),
            "replica_stat": Gauge(
                "sentio_tpu_replica_stat",
                "per-replica decode service point-in-time stats",
                ["replica", "stat"], registry=r,
            ),
            # replica failure domains (runtime/replica.py supervisor): 1 on
            # the replica's CURRENT health state, 0 on the other three —
            # monitoring.yaml alerts on any replica out of HEALTHY > 60s
            "replica_health": Gauge(
                "sentio_tpu_replica_health",
                "replica health state machine position (1 = current state)",
                ["replica", "state"], registry=r,
            ),
            # confidence-gated verification (ops/confidence.py + the graph
            # verify node): outcome per mode — skipped_confident is the
            # gate paying off, a skip-rate anomaly alert rides this series
            "verify_total": Counter(
                "sentio_tpu_verify_total",
                "answer verifications by mode and outcome",
                ["mode", "outcome"], registry=r,
            ),
            "verify_confidence": Histogram(
                "sentio_tpu_verify_confidence",
                "confidence-gate score per scored answer",
                [], buckets=(0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.75,
                             0.8, 0.85, 0.9, 0.95, 1.0),
                registry=r,
            ),
            # stall watchdog: seconds since a replica's decode pump last
            # completed a loop iteration WITH pending work (0 = idle or
            # freshly ticked). A tick wedged inside a device dispatch
            # raises nothing — this gauge climbing toward the stall budget
            # is the only early signal; monitoring.yaml alerts on it
            "pump_heartbeat_age": Gauge(
                "sentio_tpu_pump_heartbeat_age_seconds",
                "decode pump heartbeat age under pending work",
                ["replica"], registry=r,
            ),
            # tick-phase attribution (infra/phases.py): per-replica
            # host/device/idle wall-time split (fractions sum to 1) and
            # the per-tick phase latency distributions. Host fraction
            # near 1 under load = the pump is GIL/dispatch-bound, not
            # device-bound — monitoring.yaml's SentioTpuPumpHostBound
            # alert and ROADMAP item 1's multi-process argument both
            # read this series.
            "pump_duty_cycle": Gauge(
                "sentio_tpu_pump_duty_cycle",
                "fraction of wall time the decode pump spends per state "
                "(host / device / idle; sums to 1 per replica)",
                ["replica", "state"], registry=r,
            ),
            "tick_phase": Histogram(
                "sentio_tpu_tick_phase_seconds",
                "pump-iteration time per named phase",
                ["phase"],
                buckets=(1e-5, 1e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 0.01,
                         0.025, 0.05, 0.1, 0.25, 0.5, 1, 5),
                registry=r,
            ),
            # process-mode replica tier (runtime/worker.py): worker
            # process deaths observed by the router-side shim (SIGKILL,
            # OOM-kill, crash, broken RPC pipe). A steadily increasing
            # rate means the supervisor is respawn-looping a replica —
            # monitoring.yaml's SentioTpuReplicaWorkerDead alerts on it
            "worker_deaths": Counter(
                "sentio_tpu_replica_worker_deaths",
                "replica worker process deaths observed by the router",
                ["replica"], registry=r,
            ),
            # multi-host worker tier (runtime/transport.py + the worker
            # registry in runtime/replica.py): each (re)registration of a
            # socket worker bumps the slot's incarnation epoch — this
            # gauge IS the epoch, so a sawtooth means the slot is churning
            "worker_incarnation": Gauge(
                "sentio_tpu_worker_incarnation",
                "current incarnation epoch of each replica slot's worker "
                "(bumped at every socket (re)registration)",
                ["replica"], registry=r,
            ),
            # frames from a PREVIOUS incarnation dropped at dispatch — a
            # partition healing is the normal source (the old connection
            # drains its buffered pre-partition frames); nonzero during an
            # incident is the epoch fence doing its job, a sustained rate
            # outside incidents means a zombie connection never died
            "worker_stale_frames": Counter(
                "sentio_tpu_worker_stale_frames_total",
                "worker frames dropped for carrying a stale incarnation "
                "epoch",
                ["replica"], registry=r,
            ),
            # worker (re)connection outcomes: heal = a live partitioned
            # worker re-registered and kept its process; respawn = the
            # supervisor spawned a fresh process; reconnected = a dialed
            # remote worker accepted a fresh router connection; rejected_*
            # = the registry refused a registration. monitoring.yaml's
            # SentioTpuWorkerFlapping alerts on churn in this series.
            "worker_reconnects": Counter(
                "sentio_tpu_worker_reconnects_total",
                "socket worker reconnection outcomes",
                ["outcome"], registry=r,
            ),
            # resumable streams (runtime/replica.py): mid-flight failovers
            # of delivered-token streams. outcome=resumed is the healthy
            # path; a sustained resume RATE means a replica is flapping —
            # monitoring.yaml's SentioTpuStreamResumeStorm alerts on it
            "stream_resumes": Counter(
                "sentio_tpu_stream_resumes_total",
                "mid-flight stream resume outcomes (resumed = delivered "
                "prefix spliced onto a survivor; exhausted = resume budget "
                "spent, typed error surfaced; failed = no survivor could "
                "take the splice; opt_out = caller disabled resumption)",
                ["outcome"], registry=r,
            ),
            # fleet telemetry plane (runtime/worker.py telemetry frames):
            # worker-process metric registries shipped as monotonic deltas
            # and re-published here under {replica} — /metrics shows one
            # truthful fleet view in every replica mode. Counters (not
            # gauges): rate() stays correct across scrapes and worker
            # respawns (merge_worker_series resets baselines on pid change).
            "worker_tick_phase_seconds": Counter(
                "sentio_tpu_worker_tick_phase_seconds_total",
                "cumulative pump-iteration seconds per named phase, per "
                "worker replica (fleet-merged from telemetry frames)",
                ["replica", "phase"], registry=r,
            ),
            "worker_tick_phase_ticks": Counter(
                "sentio_tpu_worker_tick_phase_ticks_total",
                "pump iterations observed per named phase, per worker "
                "replica (fleet-merged from telemetry frames)",
                ["replica", "phase"], registry=r,
            ),
            "worker_verify": Counter(
                "sentio_tpu_worker_verify_total",
                "answer verifications landed inside a worker process, by "
                "mode and outcome (fleet-merged from telemetry frames)",
                ["replica", "mode", "outcome"], registry=r,
            ),
            "worker_compiles": Counter(
                "sentio_tpu_worker_compiles_total",
                "XLA compilations observed inside a worker process at "
                "registered jit families (fleet-merged)",
                ["replica", "family"], registry=r,
            ),
            "worker_events": Counter(
                "sentio_tpu_worker_events_total",
                "other worker-process counter series, flattened to one "
                "bounded series label (fleet-merged)",
                ["replica", "series"], registry=r,
            ),
            "worker_observed_sum": Counter(
                "sentio_tpu_worker_observed_sum",
                "worker-process histogram value sums per series "
                "(fleet-merged; pairs with ..._observed_count for means)",
                ["replica", "series"], registry=r,
            ),
            "worker_observed_count": Counter(
                "sentio_tpu_worker_observed_count",
                "worker-process histogram observation counts per series "
                "(fleet-merged)",
                ["replica", "series"], registry=r,
            ),
            # telemetry silence made observable: seconds since the last
            # ACCEPTED telemetry frame from each worker. Climbs ~1 s/s
            # through a partition, snaps back at the first post-heal frame —
            # monitoring.yaml's SentioTpuWorkerTelemetryStale alerts on it
            "worker_telemetry_age": Gauge(
                "sentio_tpu_worker_telemetry_age_seconds",
                "seconds since the router last merged a telemetry frame "
                "from this replica's worker",
                ["replica"], registry=r,
            ),
            # the telemetry epoch fence + cardinality guard, visible:
            # stale_epoch = a healed worker's pre-partition buffer hit the
            # fence (normal during incidents); cardinality = a worker tried
            # to mint more distinct series than the per-replica cap
            "worker_telemetry_dropped": Counter(
                "sentio_tpu_worker_telemetry_dropped_total",
                "worker telemetry frames/series dropped at merge",
                ["replica", "reason"], registry=r,
            ),
            # elastic fleet: membership is now a runtime variable, so the
            # live size is a gauge and every autoscaler decision a counter
            # (monitoring.yaml's SentioTpuAutoscaleFlapping alerts on
            # decision churn; ...FleetAtMaxSaturated on the gauge below)
            "fleet_size": Gauge(
                "sentio_tpu_fleet_live_replicas",
                "live (non-retired) replicas currently wired into the "
                "serving set",
                [], registry=r,
            ),
            "autoscale_decisions": Counter(
                "sentio_tpu_autoscale_decisions_total",
                "executed autoscaler decisions by direction and the "
                "signal that triggered them",
                ["direction", "reason"], registry=r,
            ),
            "fleet_saturated": Gauge(
                "sentio_tpu_fleet_at_max_saturated",
                "1 while the fleet sits at AUTOSCALE_MAX_REPLICAS with "
                "the windowed load still above the scale-out thresholds",
                [], registry=r,
            ),
        }

    # ------------------------------------------------------------- recording

    def record_request(self, endpoint: str, status: int, latency_s: float) -> None:
        if not self.enabled:
            return
        self.memory.inc("requests", (endpoint, str(status)))
        self.memory.observe("request_latency", (endpoint,), latency_s)
        if self._prom:
            self._prom["requests"].labels(endpoint, str(status)).inc()
            self._prom["request_latency"].labels(endpoint).observe(latency_s)

    def record_embeddings(self, provider: str, n_texts: int) -> None:
        if not self.enabled:
            return
        self.memory.inc("embeddings", (provider,), n_texts)
        if self._prom:
            self._prom["embeddings"].labels(provider).inc(n_texts)

    def record_retrieval(self, strategy: str, latency_s: float) -> None:
        if not self.enabled:
            return
        self.memory.observe("retrieval_latency", (strategy,), latency_s)
        if self._prom:
            self._prom["retrieval_latency"].labels(strategy).observe(latency_s)

    def record_llm(self, op: str, latency_s: float, tokens: int = 0) -> None:
        if not self.enabled:
            return
        self.memory.observe("llm_latency", (op,), latency_s)
        if tokens:
            self.memory.inc("llm_tokens", (op,), tokens)
            if latency_s > 0:
                self.memory.set_gauge("tokens_per_s", (), tokens / latency_s)
        if self._prom:
            self._prom["llm_latency"].labels(op).observe(latency_s)
            if tokens:
                self._prom["llm_tokens"].labels(op).inc(tokens)
                if latency_s > 0:
                    self._prom["tokens_per_s"].set(tokens / latency_s)

    def record_ttft(self, seconds: float, path: str = "paged") -> None:
        """Time-to-first-token for one sequence (``path``: paged | stream)."""
        if not self.enabled:
            return
        self.memory.observe("ttft", (path,), seconds)
        if self._prom:
            self._prom["ttft"].labels(path).observe(seconds)

    def record_tpot(self, seconds: float, path: str = "paged") -> None:
        """Mean time-per-output-token for one sequence (excludes the first
        token — that interval is TTFT's)."""
        if not self.enabled:
            return
        self.memory.observe("tpot", (path,), seconds)
        if self._prom:
            self._prom["tpot"].labels(path).observe(seconds)

    def record_tick(self, duration_s: float, active_slots: int,
                    queue_depth: int) -> None:
        """One engine pump tick: dispatch wall time plus the point-in-time
        occupancy/queue gauges operators watch between scrapes."""
        if not self.enabled:
            return
        self.memory.observe("tick_duration", (), duration_s)
        self.set_serving_stat("tick_active_slots", float(active_slots))
        self.set_serving_stat("tick_queue_depth", float(queue_depth))
        if self._prom:
            self._prom["tick_duration"].observe(duration_s)

    def record_tick_phases(self, phase_s: dict) -> None:
        """One pump iteration's phase split (seconds per phase, keys from
        :data:`sentio_tpu.infra.phases.TICK_PHASES`). Unknown keys are
        DROPPED — the ``phase`` label space is a fixed bounded set and a
        typo'd phase name must not mint a new metric series."""
        if not self.enabled:
            return
        from sentio_tpu.infra.phases import TICK_PHASES

        hist = self._prom.get("tick_phase")
        for key in TICK_PHASES:
            value = phase_s.get(key)
            if value is None:
                continue
            self.memory.observe("tick_phase", (key,), float(value))
            if hist is not None:
                hist.labels(phase=key).observe(float(value))

    def record_duty_cycle(self, replica: int, fractions: dict) -> None:
        """Publish one replica's host/device/idle duty-cycle fractions
        (:func:`sentio_tpu.infra.phases.duty_fractions` output — they sum
        to 1). Bounded: only the three known states are exported."""
        if not self.enabled:
            return
        gauge = self._prom.get("pump_duty_cycle")
        for state in ("host", "device", "idle"):
            value = float(fractions.get(state, 0.0))
            self.memory.set_gauge("pump_duty_cycle", (str(replica), state),
                                  value)
            if gauge is not None:
                gauge.labels(replica=str(replica), state=state).set(value)

    def record_compiles(self, family: str, n: int = 1) -> None:
        """``n`` XLA compilations at jit family ``family`` (fed by the audit
        registry's cache-miss accounting, analysis/audit/fence.py)."""
        if not self.enabled:
            return
        self.memory.inc("xla_compiles", (family,), n)
        if self._prom:
            self._prom["xla_compiles"].labels(family).inc(n)

    def record_shed(self, reason: str, n: int = 1) -> None:
        """One request dropped by admission control or deadline enforcement
        (``reason``: queue_full | draining | deadline | expired |
        cancelled | crash)."""
        if not self.enabled:
            return
        self.memory.inc("shed", (reason,), n)
        if self._prom:
            self._prom["shed"].labels(reason).inc(n)

    def record_verify(self, mode: str, outcome: str,
                      confidence: Optional[float] = None) -> None:
        """One answer-verification outcome (``mode``: sync | async | gated;
        ``outcome``: pass | warn | fail | skipped_confident |
        skipped_deadline), plus the gate's confidence score when one was
        computed."""
        if not self.enabled:
            return
        self.memory.inc("verify", (mode, outcome))
        if confidence is not None:
            self.memory.observe("verify_confidence", (), float(confidence))
        if self._prom:
            self._prom["verify_total"].labels(mode, outcome).inc()
            if confidence is not None:
                self._prom["verify_confidence"].observe(float(confidence))

    def record_tenant_admitted(self, tenant: str) -> None:
        """One request admitted through WFQ for ``tenant``."""
        if not self.enabled:
            return
        self.memory.inc("tenant_admitted", (tenant,))
        if self._prom:
            self._prom["tenant_admitted"].labels(tenant).inc()

    def record_tenant_shed(self, tenant: str, reason: str) -> None:
        """One request shed by WFQ (``reason``: tenant_quota |
        priority_batch | tenant_deficit)."""
        if not self.enabled:
            return
        self.memory.inc("tenant_shed", (tenant, reason))
        if self._prom:
            self._prom["tenant_shed"].labels(tenant, reason).inc()

    def set_replica_stat(self, replica: int, key: str, value: float) -> None:
        """Publish one point-in-time stat for one serving replica under the
        replica-labeled gauge and the JSON snapshot."""
        self.memory.set_gauge(f"replica_{replica}_{key}", (), value)
        gauge = self._prom.get("replica_stat")
        if gauge is not None:
            gauge.labels(replica=str(replica), stat=key).set(value)

    def record_heartbeat_age(self, replica: int, age_s: float) -> None:
        """Publish one replica's pump heartbeat age (0.0 = idle or fresh).
        Set each watchdog pass, so the gauge's scrape-to-scrape slope under
        a wedged pump is ~1 s/s — the stall signature dashboards alert
        on."""
        if not self.enabled:
            return
        self.memory.set_gauge("pump_heartbeat_age", (str(replica),), age_s)
        gauge = self._prom.get("pump_heartbeat_age")
        if gauge is not None:
            gauge.labels(replica=str(replica)).set(age_s)

    def record_worker_death(self, replica: int) -> None:
        """One replica worker PROCESS death (process-mode replica tier,
        runtime/worker.py) — observed via broken RPC pipe, a false
        ``proc.is_alive()``, or an explicit chaos SIGKILL. Counted once
        per corpse by the router-side shim's death latch."""
        if not self.enabled:
            return
        self.memory.inc("worker_deaths", (str(replica),))
        counter = self._prom.get("worker_deaths")
        if counter is not None:
            counter.labels(str(replica)).inc()

    def record_worker_incarnation(self, replica: int, epoch: int) -> None:
        """Publish one replica slot's CURRENT worker incarnation epoch
        (worker registry, runtime/replica.py) — set at every socket
        (re)registration."""
        if not self.enabled:
            return
        self.memory.set_gauge("worker_incarnation", (str(replica),),
                              float(epoch))
        gauge = self._prom.get("worker_incarnation")
        if gauge is not None:
            gauge.labels(str(replica)).set(float(epoch))

    def record_stale_frames(self, replica: int, n: int = 1) -> None:
        """Count worker frames dropped for carrying a stale incarnation
        epoch — a reconnected worker's pre-partition traffic hitting the
        epoch fence instead of resurrecting dead tickets."""
        if not self.enabled or n <= 0:
            return
        self.memory.inc("worker_stale_frames", (str(replica),), float(n))
        counter = self._prom.get("worker_stale_frames")
        if counter is not None:
            counter.labels(str(replica)).inc(n)

    def record_worker_reconnect(self, outcome: str) -> None:
        """One socket-worker reconnection outcome (``heal`` | ``respawn``
        | ``reconnected`` | ``rejected_auth`` | ``rejected_proto``) —
        the churn series behind SentioTpuWorkerFlapping."""
        if not self.enabled:
            return
        self.memory.inc("worker_reconnects", (outcome,))
        counter = self._prom.get("worker_reconnects")
        if counter is not None:
            counter.labels(outcome).inc()

    def record_fleet_size(self, live: int) -> None:
        """Publish the live (non-retired) replica count — re-derived by
        ``ReplicaSet`` whenever membership changes (join/retire), so the
        gauge steps exactly at the scale events."""
        if not self.enabled:
            return
        self.memory.set_gauge("fleet_size", (), float(live))
        gauge = self._prom.get("fleet_size")
        if gauge is not None:
            gauge.set(float(live))

    def record_autoscale_decision(self, direction: str, reason: str) -> None:
        """One EXECUTED autoscaler decision (``direction``: out | in;
        ``reason``: busy | backlog | idle) — the churn series behind
        SentioTpuAutoscaleFlapping."""
        if not self.enabled:
            return
        self.memory.inc("autoscale_decisions", (direction, reason))
        counter = self._prom.get("autoscale_decisions")
        if counter is not None:
            counter.labels(direction, reason).inc()

    def record_fleet_saturation(self, value: float) -> None:
        """1.0 while the fleet is pinned at max replicas AND the windowed
        load still clears the scale-out thresholds; 0.0 otherwise."""
        if not self.enabled:
            return
        self.memory.set_gauge("fleet_saturated", (), float(value))
        gauge = self._prom.get("fleet_saturated")
        if gauge is not None:
            gauge.set(float(value))

    def record_stream_resume(self, outcome: str) -> None:
        """One mid-flight stream resume outcome (``outcome``: resumed |
        exhausted | failed | opt_out) — the counter behind
        ``sentio_tpu_stream_resumes_total``."""
        if not self.enabled:
            return
        self.memory.inc("stream_resumes", (outcome,))
        counter = self._prom.get("stream_resumes")
        if counter is not None:
            counter.labels(outcome).inc()

    # ----------------------------------------------- fleet telemetry merge

    def export_worker_series(self) -> dict[str, Any]:
        """CUMULATIVE snapshot of this process's counter/histogram registry,
        the payload a worker's telemetry frame carries (runtime/worker.py).
        Cheap: three dict copies under the memory lock, no histogram windows
        (quantiles stay worker-local — only monotonic aggregates ship, so
        the router can difference them into deltas safely)."""
        memory = self.memory
        with memory._lock:
            return {
                "counters": dict(memory.counters),
                "histo_count": dict(memory._histo_total),
                "histo_sum": dict(memory._histo_sum),
            }

    def _publish_worker_delta(self, replica: str, name: str,
                                 labels: tuple, delta_sum: float,
                                 delta_count: float, is_histo: bool) -> None:
        """Route one accepted worker-series delta into the {replica}-labeled
        fleet families. Known bounded-label series keep their label
        structure (phase / mode+outcome / family); everything else flattens
        into one ``series`` label so an unknown worker series can never mint
        an unbounded label SET, only a new value under the guard's cap."""
        if is_histo:
            if name == "tick_phase" and len(labels) == 1:
                self.memory.inc("worker_tick_phase_seconds",
                                (replica, labels[0]), delta_sum)
                self.memory.inc("worker_tick_phase_ticks",
                                (replica, labels[0]), delta_count)
                sec = self._prom.get("worker_tick_phase_seconds")
                cnt = self._prom.get("worker_tick_phase_ticks")
                if sec is not None and delta_sum:
                    sec.labels(replica, labels[0]).inc(delta_sum)
                if cnt is not None and delta_count:
                    cnt.labels(replica, labels[0]).inc(delta_count)
                return
            series = "_".join((name,) + labels) if labels else name
            self.memory.inc("worker_observed_sum", (replica, series),
                            delta_sum)
            self.memory.inc("worker_observed_count", (replica, series),
                            delta_count)
            osum = self._prom.get("worker_observed_sum")
            ocnt = self._prom.get("worker_observed_count")
            if osum is not None and delta_sum > 0:
                osum.labels(replica, series).inc(delta_sum)
            if ocnt is not None and delta_count:
                ocnt.labels(replica, series).inc(delta_count)
            return
        if name == "verify" and len(labels) == 2:
            self.memory.inc("worker_verify", (replica,) + labels, delta_sum)
            counter = self._prom.get("worker_verify")
            if counter is not None:
                counter.labels(replica, labels[0], labels[1]).inc(delta_sum)
            return
        if name == "xla_compiles" and len(labels) == 1:
            self.memory.inc("worker_compiles", (replica, labels[0]),
                            delta_sum)
            counter = self._prom.get("worker_compiles")
            if counter is not None:
                counter.labels(replica, labels[0]).inc(delta_sum)
            return
        series = "_".join((name,) + labels) if labels else name
        self.memory.inc("worker_events", (replica, series), delta_sum)
        counter = self._prom.get("worker_events")
        if counter is not None:
            counter.labels(replica, series).inc(delta_sum)

    def merge_worker_series(self, replica: int, series: dict,
                            epoch: int = 0,
                            pid: Optional[int] = None) -> dict:
        """Fold one worker telemetry frame's CUMULATIVE series snapshot
        (:meth:`export_worker_series` shape) into the router's fleet
        families under ``{replica}`` labels, differencing against the last
        accepted snapshot.

        Fencing & continuity contract (ISSUE 16 leg 4):

        * ``epoch`` below the last accepted epoch → the whole frame is a
          healed worker's pre-partition buffer draining late; DROPPED and
          counted (``reason="stale_epoch"``) — merging it would double-count
          everything the current epoch already shipped.
        * same pid, same-or-higher epoch (a HEAL) → baselines are KEPT: the
          process never died, its cumulative registry kept growing, so the
          next delta is exactly the partition window's truth.
        * pid change (a RESPAWN) → baselines reset to zero: the fresh
          process's registry restarts from nothing and differencing against
          the corpse's totals would swallow the first interval.
        """
        if not self.enabled or not isinstance(series, dict):
            return {"accepted": False, "merged": 0}
        rep = str(replica)
        merged = 0
        with self._worker_lock:
            state = self._worker_last.get(replica)
            if state is None:
                state = {"pid": None, "epoch": int(epoch), "cum": {}}
                self._worker_last[replica] = state
            if int(epoch) < state["epoch"]:
                self.record_telemetry_dropped(replica, "stale_epoch")
                return {"accepted": False, "merged": 0}
            if pid is not None and state["pid"] not in (None, pid):
                state["cum"] = {}  # respawn: fresh process, fresh baselines
            state["epoch"] = int(epoch)
            if pid is not None:
                state["pid"] = pid
            cum = state["cum"]
            plan: list[tuple] = []
            for kind, is_histo in (("counters", False),
                                   ("histo_sum", True)):
                counts = series.get("histo_count", {}) if is_histo else {}
                for key, value in (series.get(kind) or {}).items():
                    name, labels = _parse_series_key(str(key))
                    if name is None:
                        self.record_telemetry_dropped(replica, "malformed")
                        continue
                    scoped = f"{kind}:{key}"
                    if (scoped not in cum and
                            len(cum) >= 2 * MAX_WORKER_SERIES_PER_REPLICA):
                        self.record_telemetry_dropped(replica, "cardinality")
                        continue
                    last_sum, last_count = cum.get(scoped, (0.0, 0.0))
                    delta_sum = max(float(value) - last_sum, 0.0)
                    new_count = float(counts.get(key, 0.0))
                    delta_count = max(new_count - last_count, 0.0)
                    cum[scoped] = (float(value), new_count)
                    if delta_sum <= 0.0 and delta_count <= 0.0:
                        continue
                    plan.append((name, labels, delta_sum, delta_count,
                                 is_histo))
        for name, labels, delta_sum, delta_count, is_histo in plan:
            self._publish_worker_delta(rep, name, labels, delta_sum,
                                          delta_count, is_histo)
            merged += 1
        return {"accepted": True, "merged": merged}

    def record_telemetry_age(self, replica: int, age_s: float) -> None:
        """Publish seconds since the last ACCEPTED telemetry frame from one
        replica's worker — set each supervisor pass, so the gauge climbs
        ~1 s/s through a partition and snaps back at the first post-heal
        frame (the SentioTpuWorkerTelemetryStale signal)."""
        if not self.enabled:
            return
        self.memory.set_gauge("worker_telemetry_age", (str(replica),),
                              float(age_s))
        gauge = self._prom.get("worker_telemetry_age")
        if gauge is not None:
            gauge.labels(str(replica)).set(float(age_s))

    def record_telemetry_dropped(self, replica: int, reason: str,
                                 n: int = 1) -> None:
        """Count telemetry frames/series refused at merge (``reason``:
        stale_epoch | cardinality | malformed)."""
        if not self.enabled or n <= 0:
            return
        self.memory.inc("worker_telemetry_dropped", (str(replica), reason),
                        float(n))
        counter = self._prom.get("worker_telemetry_dropped")
        if counter is not None:
            counter.labels(str(replica), reason).inc(n)

    def worker_telemetry_epoch(self, replica: int) -> Optional[int]:
        """The last accepted telemetry epoch for one replica (None before
        any frame merged) — the epoch-fence drill's assertion hook."""
        with self._worker_lock:
            state = self._worker_last.get(replica)
            return None if state is None else state["epoch"]

    def record_replica_health(self, replica: int, state: str) -> None:
        """Publish one replica's health-state transition: the new state's
        series goes to 1 and every other state's to 0, so
        ``sentio_tpu_replica_health{replica="K"}`` always sums to 1 and a
        dashboard can plot the machine's position directly."""
        from sentio_tpu.runtime.replica import HEALTH_STATES

        for name in HEALTH_STATES:
            value = 1.0 if name == state else 0.0
            self.memory.set_gauge("replica_health", (str(replica), name),
                                  value)
            gauge = self._prom.get("replica_health")
            if gauge is not None:
                gauge.labels(replica=str(replica), state=name).set(value)

    def record_breaker(self, name: str, state: str) -> None:
        value = {"closed": 0.0, "half_open": 1.0, "open": 2.0}.get(state, 0.0)
        self.memory.set_gauge("breaker_state", (name,), value)
        if self._prom:
            self._prom["breaker_state"].labels(name).set(value)

    def record_batch_occupancy(self, batcher: str, occupancy: float) -> None:
        self.memory.observe("batch_occupancy", (batcher,), occupancy)
        if self._prom:
            self._prom["batch_occupancy"].labels(batcher).observe(occupancy)

    def collect_device_memory(self) -> None:
        """Poll jax device memory stats into the HBM gauge (best effort)."""
        try:
            import jax

            for dev in jax.devices():
                stats = dev.memory_stats()
                if stats and "bytes_in_use" in stats:
                    self.memory.set_gauge("hbm_bytes", (str(dev.id),), stats["bytes_in_use"])
                    if self._prom:
                        self._prom["hbm_bytes"].labels(str(dev.id)).set(stats["bytes_in_use"])
        except Exception:  # noqa: BLE001 — device-memory scrape is best-effort telemetry
            pass

    # --------------------------------------------------------------- helpers

    def adjust_inflight(self, delta: int) -> None:
        # gauge writes stay INSIDE the lock: two concurrent finishes could
        # otherwise write counter values out of order and leave the HPA
        # scaling signal stuck at a phantom non-zero on an idle pod
        with self._inflight_lock:
            self._inflight = max(self._inflight + delta, 0)
            value = float(self._inflight)
            self.memory.set_gauge("inflight", (), value)
            if self._prom:
                self._prom["inflight"].set(value)

    @contextmanager
    def track_request(self, endpoint: str):
        t0 = time.perf_counter()
        status = 200
        self.adjust_inflight(+1)
        try:
            yield
        except Exception:
            status = 500
            raise
        finally:
            self.adjust_inflight(-1)
            self.record_request(endpoint, status, time.perf_counter() - t0)

    # ---------------------------------------------------------------- export

    def set_serving_stat(self, key: str, value: float) -> None:
        """Publish one point-in-time decode-service stat under both exports:
        the labeled ``sentio_tpu_serving_stat`` gauge and the JSON
        snapshot."""
        self.memory.set_gauge(f"serving_{key}", (), value)
        gauge = self._prom.get("serving_stat")
        if gauge is not None:
            gauge.labels(stat=key).set(value)

    def bump_serving_total(self, event: str, lifetime_total: float) -> None:
        """Publish a MONOTONIC decode-service total as a Counter (rate()
        stays correct across restarts — Gauge semantics would not). The
        engine reports lifetime totals, so this tracks deltas."""
        last = self._serving_last.get(event, 0.0)
        delta = max(lifetime_total - last, 0.0)
        self._serving_last[event] = lifetime_total
        self.memory.set_gauge(f"serving_{event}", (), lifetime_total)
        counter = self._prom.get("serving_total")
        if counter is not None and delta:
            counter.labels(event=event).inc(delta)

    def export_prometheus(self) -> bytes:
        if self.registry is not None:
            return generate_latest(self.registry)
        return b""

    def export_json(self) -> dict[str, Any]:
        return self.memory.snapshot()


_collector: Optional[MetricsCollector] = None


def get_metrics() -> MetricsCollector:
    global _collector
    if _collector is None:
        _collector = MetricsCollector()
    return _collector


def set_metrics(collector: Optional[MetricsCollector]) -> None:
    global _collector
    _collector = collector
