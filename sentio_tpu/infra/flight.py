"""Request flight recorder: per-request traces + per-tick serving telemetry.

Round 5's verdict was that every serving-performance claim "rests on prose"
— nothing committed records what the engine actually did per request or per
tick. This module is the evidence layer: a Dapper-style request trace
(Sigelman et al., 2010 — one id threaded HTTP → graph → engine) joined with
the per-iteration scheduler/KV telemetry that continuous-batching systems
like vLLM (Kwon et al., SOSP 2023) expose to explain batching behavior.

Two bounded, thread-safe stores:

* a **tick ring buffer** — one event per engine pump tick (wall time, batch
  occupancy, queue depth, prefill/decode token counts, speculative accepts,
  prefix-cache hits, page-pool free/used), appended by the decode pump and
  read by ``/debug/flight``, ``sentio trace``, and ``bench.py``. The same
  ring carries the replica-supervision vocabulary: ``replica_health``,
  ``pump_stall``, ``inbox_handoff``, ``tick_failure``, and
  ``stream_resumed`` (a delivered-token stream spliced onto a survivor —
  ``replica_from``/``replica_to``, ``replayed_tokens``, ``splice_index``);
* a **request table** — per-request records keyed by the serving layer's
  ``query_id`` (graph node timings, TTFT, TPOT, token counts, and the tick
  window the request's decode rode), LRU-evicted at ``max_requests``.

Writers never block on readers beyond one short mutex; the pump appends one
small dict per tick, so recording cost is noise next to a device dispatch.
Everything stored is plain JSON-serializable data — records go verbatim
into HTTP responses and bench artifacts.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Optional

from sentio_tpu.analysis.sanitizer import assert_held, guard_locksets, make_lock

__all__ = ["FlightRecorder", "get_flight_recorder", "set_flight_recorder"]

# tick events returned inline with one request's record — the full ring is
# available via timeline(); per-request responses stay bounded
MAX_TICKS_PER_RECORD = 256


@guard_locksets
class FlightRecorder:
    """Bounded, thread-safe flight store. All methods are cheap dict/deque
    operations under one lock; safe to call from the HTTP event loop, graph
    worker threads, and the engine pump thread concurrently."""

    def __init__(self, max_ticks: int = 4096, max_requests: int = 512) -> None:
        self._lock = make_lock("FlightRecorder._lock")
        self._ticks: deque = deque(maxlen=max_ticks)  # guarded-by: _lock
        self._tick_seq = 0  # guarded-by: _lock
        self._records: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock
        self.max_requests = max_requests
        self.dropped_requests = 0  # guarded-by: _lock
        self._t0 = time.perf_counter()  # timeline origin for tick timestamps

    # ------------------------------------------------------------- requests

    def _ensure_locked(self, request_id: str) -> dict:
        """Fetch-or-create a record (lock held). Any layer may be the first
        to see an id — HTTP handler, graph executor, CLI, or a direct
        service caller — so every writer creates on demand."""
        assert_held(self._lock)
        record = self._records.get(request_id)
        if record is None:
            record = {"request_id": request_id, "status": "active",
                      "t_start_s": round(self._now(), 6)}
            self._records[request_id] = record
            self._evict_locked()
        return record

    def start_request(self, request_id: str, **fields: Any) -> None:
        """Open a record. Extra fields merge in verbatim. A finished record
        under the same id (multi-turn conversations pin ``thread_id``, which
        doubles as the trace id) is replaced, not merged — otherwise turn 2's
        node timings would sum onto turn 1's; the latest turn wins."""
        if not request_id:
            return
        with self._lock:
            prior = self._records.get(request_id)
            if prior is not None and prior.get("status") != "active":
                del self._records[request_id]
            record = self._ensure_locked(request_id)
            record.update(fields)
            self._records.move_to_end(request_id)

    def annotate(self, request_id: str, **fields: Any) -> None:
        """Merge fields into an existing-or-new record."""
        if not request_id:
            return
        with self._lock:
            self._ensure_locked(request_id).update(fields)

    def add_node_timings(
        self, request_id: str, timings: dict, graph_path: Optional[list] = None
    ) -> None:
        """Attach the graph executor's per-node wall times (merged when a
        request invokes the graph more than once, e.g. verifier rewrites)."""
        if not request_id or not timings:
            return
        with self._lock:
            record = self._ensure_locked(request_id)
            merged = dict(record.get("node_timings_ms", {}))
            for node, ms in timings.items():
                merged[node] = round(merged.get(node, 0.0) + float(ms), 3)
            record["node_timings_ms"] = merged
            if graph_path:
                record["graph_path"] = list(graph_path)

    def note_engine_submit(self, request_id: str, **fields: Any) -> None:
        """Mark where this request enters the decode engine: its tick window
        starts at the NEXT tick the pump records. Extra fields (e.g. the
        ``replica_id`` that admission routed to) merge into the engine
        section; the first admission's values win — the verify node's later
        admission under the same trace id must not overwrite which replica
        served the user-facing generation."""
        if not request_id:
            return
        with self._lock:
            engine = self._ensure_locked(request_id).setdefault("engine", {})
            engine.setdefault("tick_first", self._tick_seq)
            # timeline-origin submit stamp: lets the Chrome-trace exporter
            # place the engine span / first-token mark on the same clock as
            # tick events (t_start_s is the HTTP-layer open, not submit)
            engine.setdefault("t_submit_s", round(self._now(), 6))
            for key, value in fields.items():
                engine.setdefault(key, value)

    def finish_engine(self, request_id: str, **fields: Any) -> None:
        """Close one engine admission for this request and pin the end of
        its tick window. A request may admit MORE than once under one trace
        id (the verify node reuses the generate node's id so both land on
        the same record): every admission appends to ``engine.admissions``
        verbatim, while the headline scalars (ttft_ms, tokens, …) keep the
        FIRST admission's values — the user-facing generation."""
        if not request_id:
            return
        with self._lock:
            record = self._ensure_locked(request_id)
            engine = record.setdefault("engine", {})
            engine.setdefault("admissions", []).append(
                dict(fields, tick_last=self._tick_seq)
            )
            for key, value in fields.items():
                engine.setdefault(key, value)
            engine["tick_last"] = self._tick_seq
            self._records.move_to_end(request_id)

    def note_verify(self, request_id: str, **fields: Any) -> None:
        """Merge fields into the request's ``verify`` section (mode,
        confidence score, verdict, verdict latency, skipped reason).
        Deliberately works on FINISHED records too: with VERIFY_MODE=async
        or gated, the answer's record closes before the detached audit
        lands its verdict — ``/debug/flight/{id}`` is where a caller holding
        ``verify_pending`` fetches the late verdict."""
        if not request_id:
            return
        with self._lock:
            record = self._ensure_locked(request_id)
            record.setdefault("verify", {}).update(fields)
            self._records.move_to_end(request_id)

    def finish_request(self, request_id: str, **fields: Any) -> None:
        if not request_id:
            return
        with self._lock:
            record = self._records.get(request_id)
            if record is None:
                return
            if record.get("status") == "active":
                record["status"] = "done"
            record.update(fields)
            record["latency_ms"] = fields.get(
                "latency_ms",
                round((self._now() - record.get("t_start_s", self._now())) * 1e3, 1),
            )
            self._records.move_to_end(request_id)

    # ---------------------------------------------------------------- ticks

    def record_tick(self, **fields: Any) -> int:
        """Append one engine-tick event; returns its sequence number. The
        pump owns tick cadence — one call per ``engine.step()``, made
        BEFORE result delivery so a request finishing this tick records a
        ``tick_last`` that still includes it (the window filter in
        :meth:`get` is ``first < tick <= last``)."""
        with self._lock:
            self._tick_seq += 1
            event = {"tick": self._tick_seq, "t_s": round(self._now(), 4)}
            event.update(fields)
            self._ticks.append(event)
            return self._tick_seq

    def amend_tick(self, tick: int, restamp: bool = True,
                   **fields: Any) -> int:
        """Merge late fields into an already-recorded tick event — the pump
        records the tick before delivering results (window semantics above)
        and amends the COMPLETED phase decomposition afterwards. ``restamp``
        moves ``t_s`` to now, keeping the convention that a tick's stamp
        marks the END of the span it covers (the Chrome exporter subtracts
        ``pump_ms`` to find the start). Returns 1 when the event was found
        (it is normally the ring's tail; a full ring may have evicted it)."""
        with self._lock:
            for event in reversed(self._ticks):
                if event["tick"] == tick:
                    event.update(fields)
                    if restamp:
                        event["t_s"] = round(self._now(), 4)
                    return 1
        return 0

    # ---------------------------------------------------------------- reads

    def get(self, request_id: str) -> Optional[dict]:
        """One request's full flight record, with the tick events that fall
        inside its engine window (those still in the ring)."""
        with self._lock:
            record = self._records.get(request_id)
            if record is None:
                return None
            out = dict(record)
            engine = record.get("engine")
            if engine:
                out["engine"] = dict(engine)
                first = engine.get("tick_first")
                last = engine.get("tick_last", self._tick_seq)
                if first is not None:
                    window = [dict(e) for e in self._ticks
                              if first < e["tick"] <= last]
                    if len(window) > MAX_TICKS_PER_RECORD:
                        out["ticks_truncated"] = len(window) - MAX_TICKS_PER_RECORD
                        window = window[-MAX_TICKS_PER_RECORD:]
                    out["ticks"] = window
            return out

    def timeline(self, last: Optional[int] = None) -> list[dict]:
        """The tick ring, oldest first (optionally only the last N)."""
        with self._lock:
            events = [dict(e) for e in self._ticks]
        return events[-last:] if last else events

    def records(self) -> list[dict]:
        """Shallow copies of every retained request record, insertion order
        (the Chrome-trace exporter's request-span source)."""
        with self._lock:
            return [
                dict(record, engine=dict(record["engine"]))
                if "engine" in record else dict(record)
                for record in self._records.values()
            ]

    def origin(self) -> float:
        """This recorder's timeline zero as a raw ``perf_counter`` value.
        Two recorders in one PROCESS (router + its thread-mode services)
        share a clock but not an origin; across processes the clock itself
        differs — fleet trace stitching needs both the origin (same-clock
        re-basing) and a ClockSync offset (cross-process re-basing)."""
        return self._t0

    def highwater(self) -> dict:
        """Ring/table occupancy counters only — the bounded stats a 1 Hz
        telemetry frame can afford (``snapshot()`` inlines every retained
        tick and is far too heavy to ship on a cadence)."""
        with self._lock:
            return {
                "ticks_recorded": self._tick_seq,
                "ticks_retained": len(self._ticks),
                "requests_retained": len(self._records),
                "requests_dropped": self.dropped_requests,
            }

    def snapshot(self) -> dict:
        """Aggregate view for bench artifacts / debugging."""
        with self._lock:
            ticks = [dict(e) for e in self._ticks]
            n_records = len(self._records)
            dropped = self.dropped_requests
            seq = self._tick_seq
        return {
            "ticks_recorded": seq,
            "ticks_retained": len(ticks),
            "requests_retained": n_records,
            "requests_dropped": dropped,
            "ticks": ticks,
        }

    def clear(self) -> None:
        with self._lock:
            self._ticks.clear()
            self._records.clear()
            self._tick_seq = 0
            self.dropped_requests = 0

    # -------------------------------------------------------------- private

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def _evict_locked(self) -> None:
        assert_held(self._lock)
        while len(self._records) > self.max_requests:
            self._records.popitem(last=False)
            self.dropped_requests += 1


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def set_flight_recorder(recorder: Optional[FlightRecorder]) -> None:
    global _recorder
    with _recorder_lock:
        _recorder = recorder
