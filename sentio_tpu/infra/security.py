"""Security utilities: log sanitization, input validation, headers, tokens,
per-IP rate windows, CSRF.

Parity with /root/reference/src/utils/security.py:23-594: a ``LogSanitizer``
regex filter installed on the root logger redacting keys/tokens globally, an
``InputValidator`` for query/content/metadata, standard security headers, a
``TokenGenerator``, an ``IPRateLimiter`` sliding window with an adaptive
load factor, and CSRF token issue/check.
"""

from __future__ import annotations

import hmac
import html
import logging
import re
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from sentio_tpu.infra.exceptions import RateLimitError, ValidationError

_REDACTION_PATTERNS = [
    # key=value / key: value forms for credential-ish keys
    re.compile(
        r"(?i)\b(api[_-]?key|authorization|secret|token|password|bearer)"
        r"([\"']?\s*[:=]\s*[\"']?)([^\s\"',;&]+)"
    ),
    re.compile(r"\bstk_[A-Za-z0-9_\-]{16,}\b"),  # our API keys
    re.compile(r"\beyJ[A-Za-z0-9_\-]+\.[A-Za-z0-9_\-]+\.[A-Za-z0-9_\-]+\b"),  # JWTs
]


def sanitize_text(text: str) -> str:
    out = text
    out = _REDACTION_PATTERNS[0].sub(lambda m: f"{m.group(1)}{m.group(2)}[REDACTED]", out)
    out = _REDACTION_PATTERNS[1].sub("[REDACTED_KEY]", out)
    out = _REDACTION_PATTERNS[2].sub("[REDACTED_JWT]", out)
    return out


class LogSanitizer(logging.Filter):
    """Root-logger filter redacting secrets from every record (reference
    security.py:23-124, installed globally at :583-594)."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            message = record.getMessage()
            sanitized = sanitize_text(message)
            if sanitized != message:
                record.msg = sanitized
                record.args = ()
        except Exception:  # noqa: BLE001 — log sanitizing must never break logging itself
            pass
        return True


_sanitizer_installed = False


def setup_log_sanitization() -> None:
    global _sanitizer_installed
    if _sanitizer_installed:
        return
    logging.getLogger().addFilter(LogSanitizer())
    for handler in logging.getLogger().handlers:
        handler.addFilter(LogSanitizer())
    _sanitizer_installed = True


_CONTROL_CHARS = re.compile(r"[\x00-\x08\x0b\x0c\x0e-\x1f\x7f]")
_SUSPICIOUS = re.compile(
    r"(?i)(<script\b|javascript:|on\w+\s*=|\bunion\s+select\b|\bdrop\s+table\b)"
)


class InputValidator:
    """Query/content/metadata validation (reference security.py:126-264)."""

    def __init__(self, max_query_chars: int = 2000, max_content_chars: int = 50_000) -> None:
        self.max_query_chars = max_query_chars
        self.max_content_chars = max_content_chars

    def validate_query(self, query: Any) -> str:
        if not isinstance(query, str):
            raise ValidationError("question must be a string")
        query = _CONTROL_CHARS.sub("", query).strip()
        if not query:
            raise ValidationError("question must be non-empty")
        if len(query) > self.max_query_chars:
            raise ValidationError(
                f"question exceeds {self.max_query_chars} characters"
            )
        if _SUSPICIOUS.search(query):
            raise ValidationError("question contains disallowed content")
        return query

    def validate_content(self, content: Any) -> str:
        if not isinstance(content, str):
            raise ValidationError("content must be a string")
        content = _CONTROL_CHARS.sub("", content)
        if not content.strip():
            raise ValidationError("content must be non-empty")
        if len(content) > self.max_content_chars:
            raise ValidationError(f"content exceeds {self.max_content_chars} characters")
        return content

    def validate_metadata(self, metadata: Any) -> dict[str, Any]:
        if metadata is None:
            return {}
        if not isinstance(metadata, dict):
            raise ValidationError("metadata must be an object")
        if len(metadata) > 64:
            raise ValidationError("metadata has too many keys")
        out: dict[str, Any] = {}
        for key, value in metadata.items():
            if not isinstance(key, str) or len(key) > 128:
                raise ValidationError("metadata keys must be short strings")
            if isinstance(value, str):
                if len(value) > 4096:
                    raise ValidationError(f"metadata value for {key!r} too long")
                out[key] = _CONTROL_CHARS.sub("", value)
            elif isinstance(value, (int, float, bool)) or value is None:
                out[key] = value
            else:
                raise ValidationError(f"metadata value for {key!r} must be scalar")
        return out

    @staticmethod
    def sanitize_html(text: str) -> str:
        return html.escape(text, quote=True)


SECURITY_HEADERS = {
    "X-Content-Type-Options": "nosniff",
    "X-Frame-Options": "DENY",
    "X-XSS-Protection": "1; mode=block",
    "Referrer-Policy": "strict-origin-when-cross-origin",
    "Cache-Control": "no-store",
    "Content-Security-Policy": "default-src 'none'",
}


class TokenGenerator:
    @staticmethod
    def token(n_bytes: int = 32) -> str:
        return secrets.token_urlsafe(n_bytes)

    @staticmethod
    def numeric_code(digits: int = 6) -> str:
        return "".join(secrets.choice("0123456789") for _ in range(digits))


@dataclass
class RateLimitConfig:
    per_minute: int = 100
    burst: int = 20


class IPRateLimiter:
    """Per-IP sliding window with an adaptive load factor: under global load,
    effective limits shrink (reference security.py:289-400, 401-560)."""

    def __init__(self, default: Optional[RateLimitConfig] = None) -> None:
        self.default = default or RateLimitConfig()
        self.per_endpoint: dict[str, RateLimitConfig] = {}
        self._events: dict[tuple[str, str], list[float]] = {}
        self._lock = threading.Lock()
        self._checks_since_sweep = 0
        self.load_factor = 1.0  # <1.0 tightens limits under pressure

    def _maybe_sweep(self, now: float) -> None:
        """Drop idle (ip, endpoint) keys so rotating/spoofed IPs can't grow
        the table without bound. Called under the lock."""
        self._checks_since_sweep += 1
        if self._checks_since_sweep < 1024 and len(self._events) < 16_384:
            return
        self._checks_since_sweep = 0
        doomed = [k for k, w in self._events.items() if not w or now - w[-1] >= 60.0]
        for k in doomed:
            del self._events[k]

    def configure(self, endpoint: str, per_minute: int, burst: Optional[int] = None) -> None:
        self.per_endpoint[endpoint] = RateLimitConfig(
            per_minute=per_minute, burst=burst or max(per_minute // 5, 1)
        )

    def check(self, ip: str, endpoint: str = "*") -> None:
        cfg = self.per_endpoint.get(endpoint, self.default)
        limit = max(int(cfg.per_minute * self.load_factor), 1)
        now = time.perf_counter()
        key = (ip, endpoint)
        with self._lock:
            self._maybe_sweep(now)
            window = [t for t in self._events.get(key, []) if now - t < 60.0]
            if len(window) >= limit:
                retry = 60.0 - (now - window[0])
                raise RateLimitError(
                    f"rate limit {limit}/min exceeded for {endpoint}",
                    retry_after_s=max(retry, 1.0),
                )
            window.append(now)
            self._events[key] = window

    def remaining(self, ip: str, endpoint: str = "*") -> int:
        cfg = self.per_endpoint.get(endpoint, self.default)
        limit = max(int(cfg.per_minute * self.load_factor), 1)
        now = time.perf_counter()
        with self._lock:
            window = [t for t in self._events.get((ip, endpoint), []) if now - t < 60.0]
        return max(limit - len(window), 0)


class CSRFProtection:
    def __init__(self, secret: Optional[str] = None) -> None:
        self._secret = (secret or secrets.token_urlsafe(32)).encode()

    def issue(self, session_id: str) -> str:
        ts = str(int(time.time()))  # wall-clock: CSRF token timestamp crosses workers
        mac = hmac.new(self._secret, f"{session_id}:{ts}".encode(), "sha256").hexdigest()
        return f"{ts}.{mac}"

    def verify(self, session_id: str, token: str, max_age_s: float = 3600.0) -> bool:
        try:
            ts, mac = token.split(".")
            if time.time() - float(ts) > max_age_s:  # wall-clock: CSRF token timestamp crosses workers
                return False
        except ValueError:
            return False
        expected = hmac.new(self._secret, f"{session_id}:{ts}".encode(), "sha256").hexdigest()
        return hmac.compare_digest(mac, expected)
