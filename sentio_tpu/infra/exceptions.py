"""Typed exception hierarchy with wire-format serialization.

Parity with /root/reference/src/utils/exceptions.py:21-419: an ``ErrorCode``
enum, a base exception carrying code/status/details with ``to_dict``, typed
subclasses per failure domain, and a central handler that turns any exception
into a consistent JSON error body (framework-agnostic here — the serve layer
maps it onto aiohttp responses).
"""

from __future__ import annotations

import logging
import time
import uuid
from enum import Enum
from typing import Any, Optional

logger = logging.getLogger(__name__)


class ErrorCode(str, Enum):
    # auth
    UNAUTHORIZED = "UNAUTHORIZED"
    FORBIDDEN = "FORBIDDEN"
    TOKEN_EXPIRED = "TOKEN_EXPIRED"
    ACCOUNT_LOCKED = "ACCOUNT_LOCKED"
    # validation
    VALIDATION_ERROR = "VALIDATION_ERROR"
    INVALID_INPUT = "INVALID_INPUT"
    PAYLOAD_TOO_LARGE = "PAYLOAD_TOO_LARGE"
    # rate limiting / load shedding
    RATE_LIMITED = "RATE_LIMITED"
    OVERLOADED = "OVERLOADED"
    DEADLINE_EXCEEDED = "DEADLINE_EXCEEDED"
    # resources
    NOT_FOUND = "NOT_FOUND"
    ALREADY_EXISTS = "ALREADY_EXISTS"
    # services
    SERVICE_UNAVAILABLE = "SERVICE_UNAVAILABLE"
    CIRCUIT_OPEN = "CIRCUIT_OPEN"
    TIMEOUT = "TIMEOUT"
    # processing
    RETRIEVAL_FAILED = "RETRIEVAL_FAILED"
    EMBEDDING_FAILED = "EMBEDDING_FAILED"
    RERANK_FAILED = "RERANK_FAILED"
    GENERATION_FAILED = "GENERATION_FAILED"
    INGEST_FAILED = "INGEST_FAILED"
    # device / runtime
    DEVICE_ERROR = "DEVICE_ERROR"
    DEVICE_OOM = "DEVICE_OOM"
    COMPILATION_FAILED = "COMPILATION_FAILED"
    # system
    INTERNAL_ERROR = "INTERNAL_ERROR"
    NOT_IMPLEMENTED = "NOT_IMPLEMENTED"


_DEFAULT_STATUS = {
    ErrorCode.UNAUTHORIZED: 401,
    ErrorCode.TOKEN_EXPIRED: 401,
    ErrorCode.FORBIDDEN: 403,
    ErrorCode.ACCOUNT_LOCKED: 423,
    ErrorCode.VALIDATION_ERROR: 422,
    ErrorCode.INVALID_INPUT: 400,
    ErrorCode.PAYLOAD_TOO_LARGE: 413,
    ErrorCode.RATE_LIMITED: 429,
    ErrorCode.OVERLOADED: 503,
    ErrorCode.DEADLINE_EXCEEDED: 504,
    ErrorCode.NOT_FOUND: 404,
    ErrorCode.ALREADY_EXISTS: 409,
    ErrorCode.SERVICE_UNAVAILABLE: 503,
    ErrorCode.CIRCUIT_OPEN: 503,
    ErrorCode.TIMEOUT: 504,
    ErrorCode.DEVICE_OOM: 503,
}


class SentioError(Exception):
    """Base error: code + http status + safe-to-serialize details."""

    code: ErrorCode = ErrorCode.INTERNAL_ERROR

    def __init__(
        self,
        message: str,
        code: Optional[ErrorCode] = None,
        status: Optional[int] = None,
        details: Optional[dict[str, Any]] = None,
        retryable: bool = False,
    ) -> None:
        super().__init__(message)
        self.message = message
        if code is not None:
            self.code = code
        self.status = status or _DEFAULT_STATUS.get(self.code, 500)
        self.details = details or {}
        self.retryable = retryable
        self.error_id = str(uuid.uuid4())
        self.timestamp = time.time()  # wall-clock: reported error timestamp

    def to_dict(self) -> dict[str, Any]:
        return {
            "error": {
                "code": self.code.value,
                "message": self.message,
                "error_id": self.error_id,
                "retryable": self.retryable,
                "details": self.details,
            }
        }


class AuthError(SentioError):
    code = ErrorCode.UNAUTHORIZED


class ForbiddenError(SentioError):
    code = ErrorCode.FORBIDDEN


class ValidationError(SentioError):
    code = ErrorCode.VALIDATION_ERROR


class RateLimitError(SentioError):
    code = ErrorCode.RATE_LIMITED

    def __init__(self, message: str = "rate limit exceeded", retry_after_s: float = 60.0, **kw):
        super().__init__(message, **kw)
        self.details.setdefault("retry_after_s", retry_after_s)


class NotFoundError(SentioError):
    code = ErrorCode.NOT_FOUND


class ServiceOverloaded(SentioError):
    """Load shed at admission: the serving queue is full, the service is
    draining, or the request's deadline cannot be met. Carries
    ``retry_after_s`` so handlers can answer 429/503 + ``Retry-After`` —
    shedding fast beats timing out slow (the caller retries elsewhere
    instead of holding a connection that will die anyway)."""

    code = ErrorCode.OVERLOADED
    # the degradation ladder must NOT swallow sheds into a 200 "apology":
    # the whole point is a fast, honest 429/503 the caller can act on
    soft_fail_exempt = True

    def __init__(self, message: str = "service overloaded",
                 retry_after_s: float = 1.0, **kw) -> None:
        kw.setdefault("retryable", True)
        super().__init__(message, **kw)
        self.details.setdefault("retry_after_s", retry_after_s)


class DeadlineExceededError(SentioError):
    """The caller-supplied deadline passed before (or while) the request
    was served; any in-flight decode work was cancelled."""

    code = ErrorCode.DEADLINE_EXCEEDED
    soft_fail_exempt = True  # an expired caller gets 504, not an apology


class ServiceUnavailableError(SentioError):
    code = ErrorCode.SERVICE_UNAVAILABLE

    def __init__(self, message: str, **kw):
        kw.setdefault("retryable", True)
        super().__init__(message, **kw)


class CircuitOpenError(ServiceUnavailableError):
    code = ErrorCode.CIRCUIT_OPEN


class ReplicaUnavailable(ServiceUnavailableError):
    """The decode replica (or every replica in the set) is out of rotation:
    the engine latched broken after a failed reset, the service was closed,
    or the supervisor has quarantined it for rebuild. Carries
    ``retry_after_s`` so handlers answer 503 + ``Retry-After`` — the
    supervisor rebuilds replicas in place, so coming back IS worthwhile
    (unlike an untyped 500, which tells the caller nothing)."""

    code = ErrorCode.SERVICE_UNAVAILABLE
    # a replica outage must surface as an honest 503 + Retry-After, not be
    # swallowed by the degradation ladder into a 200 apology (same rule as
    # ServiceOverloaded: the caller can act on a typed answer)
    soft_fail_exempt = True

    def __init__(self, message: str = "decode replica unavailable",
                 retry_after_s: float = 5.0, **kw) -> None:
        kw.setdefault("retryable", True)
        super().__init__(message, **kw)
        self.details.setdefault("retry_after_s", retry_after_s)


class TimeoutError_(SentioError):
    code = ErrorCode.TIMEOUT


class ProcessingError(SentioError):
    code = ErrorCode.GENERATION_FAILED


class DeviceError(SentioError):
    code = ErrorCode.DEVICE_ERROR


class GraphError(SentioError):
    """Structural graph failure (unknown node, no entry point, cycle past
    the step limit) — a server-side misconfiguration, never a node-level
    soft failure. Typed so a bad graph answers an honest, coded 500 and
    survives the RPC exception codec if it ever crosses a wire."""

    code = ErrorCode.INTERNAL_ERROR


class ErrorHandler:
    """Central exception → (status, json body) mapping; unknown exceptions
    become opaque 500s (internals never leak to clients)."""

    @staticmethod
    def handle(exc: Exception) -> tuple[int, dict[str, Any]]:
        if isinstance(exc, SentioError):
            if exc.status >= 500:
                logger.error("server error %s: %s", exc.code.value, exc.message)
            return exc.status, exc.to_dict()
        logger.exception("unhandled exception")
        wrapped = SentioError("internal server error")
        return 500, wrapped.to_dict()
