"""Chrome/Perfetto trace export for flight-recorder timelines.

Turns the flight recorder's tick ring + request table into the Chrome
Trace Event Format (the JSON ``ui.perfetto.dev`` and ``chrome://tracing``
open directly): one process row per serving replica, the pump's ticks as
slices with their named phases (infra/phases.py) nested inside, request
lifecycles as spans on per-request lanes (admit → engine decode →
first-token mark → finish), replica health transitions as instants, and
verify verdicts as trailing slices.

Everything here is a PURE function over plain dicts — the exact shapes
``FlightRecorder.timeline()``/``records()`` return — so the exporter is
golden-testable with hand-written fixtures and never touches a clock.

Layout conventions (Chrome trace event fields):

* ``pid`` = replica id (one process row per replica; metadata events name
  them ``replica N``);
* ``tid 0`` = the decode pump: one ``X`` (complete) slice per tick, its
  ``phase_ms`` laid out as child slices in canonical phase order from the
  tick's start — phases sum to the tick's ``pump_ms`` by construction
  (runtime/service.py), so children exactly tile the parent;
* ``tid 1..`` = request lanes: the request's wall span, the engine decode
  sub-span (flight ``t_submit_s`` → finish), a ``first_token`` instant at
  submit + TTFT, and the verify verdict (when recorded) as a slice
  trailing the answer — async/gated verdicts visibly overhang the span;
* health transitions ride ``tid 0`` as process-scoped instants.

Timestamps: flight records share one ``perf_counter`` origin
(``FlightRecorder._t0``); Chrome wants microseconds, so ``ts = t_s * 1e6``.
"""

from __future__ import annotations

from typing import Optional

from sentio_tpu.infra.phases import TICK_PHASES

__all__ = ["build_chrome_trace", "build_fleet_trace", "flight_to_chrome"]

# tick args copied onto the tick slice (bounded, plot-friendly)
_TICK_ARGS = (
    "active_slots", "queue_depth", "inbox_depth", "prefill_tokens",
    "decode_tokens", "free_pages", "xla_compiles",
)

_PUMP_TID = 0
_REQUEST_TID_BASE = 1

# fleet traces: worker lanes get synthetic pids well above any router
# replica id — one process row per worker INCARNATION, so a slot that
# healed or respawned mid-trace shows its epochs as separate lanes
_FLEET_PID_BASE = 1000


def _us(seconds: float) -> float:
    """Timeline seconds → Chrome microseconds (µs-rounded for stability)."""
    return round(float(seconds) * 1e6, 1)


def _tick_events(ticks: list[dict]) -> list[dict]:
    events: list[dict] = []
    for tick in ticks:
        pid = int(tick.get("replica", 0))
        if tick.get("event") == "replica_health":
            # health transition: process-scoped instant on the pump row
            events.append({
                "name": f"health:{tick.get('state', '?')}",
                "ph": "i", "s": "p",
                "pid": pid, "tid": _PUMP_TID,
                "ts": _us(tick["t_s"]),
                "args": {k: v for k, v in tick.items()
                         if k in ("state", "prior", "reason", "tick")},
            })
            continue
        phase_ms = tick.get("phase_ms")
        pump_ms = tick.get("pump_ms", tick.get("dur_ms"))
        if pump_ms is None:
            continue  # not a pump tick event (e.g. inbox_handoff markers)
        # the record is stamped at the END of the covered span
        t_end = tick["t_s"]
        t_start = t_end - pump_ms / 1e3
        events.append({
            "name": f"tick {tick.get('tick', '?')}",
            "ph": "X", "pid": pid, "tid": _PUMP_TID,
            "ts": _us(t_start), "dur": round(float(pump_ms) * 1e3, 1),
            "args": {k: tick[k] for k in _TICK_ARGS if k in tick},
        })
        if not phase_ms:
            continue
        # phases tile the tick in canonical order (sum == pump_ms by
        # construction, so the children nest exactly inside the parent)
        cursor = t_start
        for phase in TICK_PHASES:
            dur_ms = phase_ms.get(phase)
            if not dur_ms:
                continue
            events.append({
                "name": phase,
                "ph": "X", "pid": pid, "tid": _PUMP_TID,
                "ts": _us(cursor), "dur": round(float(dur_ms) * 1e3, 1),
                "args": {},
            })
            cursor += dur_ms / 1e3
    return events


def _request_events(records: list[dict]) -> tuple[list[dict], dict]:
    """Request spans, one lane per record per replica. Returns the events
    plus {pid: max_tid} so thread-name metadata can be emitted."""
    events: list[dict] = []
    lanes: dict[int, int] = {}
    for record in records:
        engine = record.get("engine") or {}
        pid = int(engine.get("replica_id", 0))
        tid = lanes.get(pid, _REQUEST_TID_BASE)
        lanes[pid] = tid + 1
        rid = record.get("request_id", "?")
        t_start = record.get("t_start_s")
        latency_ms = record.get("latency_ms")
        if latency_ms is None:
            # records opened outside the HTTP handler (sentio trace, direct
            # graph invokes) never get finish_request's latency; the graph
            # node timings are the honest span fallback
            timings = record.get("node_timings_ms")
            if timings:
                latency_ms = sum(timings.values())
        if t_start is not None and latency_ms is not None:
            events.append({
                "name": f"request {rid}",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": _us(t_start), "dur": round(float(latency_ms) * 1e3, 1),
                "args": {k: record[k] for k in
                         ("status", "mode", "endpoint", "question_chars")
                         if k in record},
            })
            t_finish = t_start + latency_ms / 1e3
        else:
            t_finish = t_start
        t_submit = engine.get("t_submit_s")
        ttft_ms = engine.get("ttft_ms")
        if t_submit is not None and t_finish is not None \
                and t_finish > t_submit:
            # engine-side sub-span: admit → retire
            events.append({
                "name": "engine",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": _us(t_submit),
                "dur": _us(t_finish - t_submit),
                "args": {k: engine[k] for k in
                         ("tokens", "prompt_tokens", "prefix_hit_tokens",
                          "finish_reason", "tpot_ms")
                         if k in engine},
            })
        if t_submit is not None and ttft_ms is not None:
            events.append({
                "name": "first_token",
                "ph": "i", "s": "t",
                "pid": pid, "tid": tid,
                "ts": _us(t_submit + ttft_ms / 1e3),
                "args": {"ttft_ms": ttft_ms},
            })
        verify = record.get("verify")
        if verify and t_finish is not None:
            # the audit trails the answer (async/gated: visibly AFTER the
            # request slice ends; sync: inside it — either is the truth)
            verdict_ms = verify.get("verdict_ms") or 0.0
            events.append({
                "name": f"verify:{verify.get('outcome', 'pending')}",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": _us(t_finish),
                "dur": round(float(verdict_ms) * 1e3, 1),
                "args": {k: verify[k] for k in
                         ("mode", "confidence", "skipped", "verdict")
                         if k in verify},
            })
    return events, lanes


def build_chrome_trace(ticks: list[dict], records: list[dict],
                       label: str = "sentio-tpu") -> dict:
    """Chrome Trace Event Format JSON (dict form) from flight tick events
    + request records. Pure and deterministic: same inputs, same output —
    the golden test pins this."""
    events: list[dict] = []
    pids: set[int] = set()
    tick_events = _tick_events(ticks)
    request_events, lanes = _request_events(records)
    for event in tick_events + request_events:
        pids.add(event["pid"])
    # metadata rows first: name each replica's process + its lanes
    for pid in sorted(pids):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"replica {pid}"}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": _PUMP_TID, "args": {"name": "pump"}})
        for tid in range(_REQUEST_TID_BASE, lanes.get(pid, _REQUEST_TID_BASE)):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": f"request lane {tid}"}})
    # stable order for byte-stable golden artifacts (Chrome doesn't care)
    events.extend(sorted(
        tick_events + request_events,
        key=lambda e: (e["pid"], e["tid"], e.get("ts", 0.0), e["name"]),
    ))
    return {
        "displayTimeUnit": "ms",
        "otherData": {"source": label},
        "traceEvents": events,
    }


def build_fleet_trace(workers: list[dict], router_ticks: Optional[list] = None,
                      router_records: Optional[list] = None,
                      label: str = "sentio-tpu-fleet") -> dict:
    """One coherent Chrome trace across the fleet: router request lanes on
    top (their native pids, 0..N), one synthetic process row per WORKER
    INCARNATION below, every worker timestamp re-based onto the router's
    timeline before layout.

    Each ``workers`` entry is plain data (pure function — the golden test
    hands fixtures): ``{"replica", "epoch", "shift_s", "uncertainty_s",
    "ticks", "records"}`` where ``shift_s`` is the caller-computed
    worker-timeline → router-timeline correction
    (``worker_origin − clock_offset − router_origin`` for cross-process
    clocks; see ProcessReplica.fetch_flight) and ``uncertainty_s`` is the
    ClockSync bound, stamped on the lane name — a reader can see exactly
    how far causality claims stretch.

    An entry may also carry ``"status": "retired" | "dead"`` — a worker
    incarnation that no longer answers but whose last cached telemetry
    frame the router still holds. Its lane renders from that cached data
    (or as an empty named lane when even that is gone) with the status
    suffixed to the lane name, so churn reads as history instead of a
    silently missing row."""
    all_ticks = [dict(t) for t in (router_ticks or [])]
    all_records = [dict(r) for r in (router_records or [])]
    names: dict[int, str] = {}
    for worker in workers:
        replica = int(worker.get("replica", 0))
        epoch = int(worker.get("epoch", 0))
        shift = float(worker.get("shift_s", 0.0))
        pid = _FLEET_PID_BASE * (replica + 1) + epoch
        bound = worker.get("uncertainty_s")
        status = str(worker.get("status") or "").strip().lower()
        names[pid] = (
            f"worker {replica} epoch {epoch}"
            + (f" (clock ±{float(bound) * 1e3:.1f}ms)"
               if bound is not None else " (clock unaligned)")
            + (f" ({status})" if status else "")
        )
        for tick in worker.get("ticks") or []:
            shifted = dict(tick, replica=pid)
            if "t_s" in shifted:
                shifted["t_s"] = round(float(shifted["t_s"]) + shift, 6)
            all_ticks.append(shifted)
        for record in worker.get("records") or []:
            shifted = dict(record)
            engine = dict(shifted.get("engine") or {})
            engine["replica_id"] = pid
            if engine.get("t_submit_s") is not None:
                engine["t_submit_s"] = round(
                    float(engine["t_submit_s"]) + shift, 6)
            shifted["engine"] = engine
            if shifted.get("t_start_s") is not None:
                shifted["t_start_s"] = round(
                    float(shifted["t_start_s"]) + shift, 6)
            all_records.append(shifted)
    trace = build_chrome_trace(all_ticks, all_records, label=label)
    named: set[int] = set()
    for event in trace["traceEvents"]:
        if (event.get("ph") == "M" and event.get("name") == "process_name"
                and event["pid"] in names):
            event["args"]["name"] = names[event["pid"]]
            named.add(event["pid"])
    # dead/retired incarnations whose cached frame carried no ticks or
    # records produce no events, so build_chrome_trace never names their
    # pid — force the metadata row so the lane still appears in the trace
    for pid in sorted(set(names) - named):
        trace["traceEvents"].insert(0, {
            "name": "process_name", "ph": "M", "pid": pid,
            "tid": 0, "args": {"name": names[pid]},
        })
    return trace


def flight_to_chrome(recorder=None, request_id: Optional[str] = None,
                     label: str = "sentio-tpu") -> Optional[dict]:
    """Export a live flight recorder: the WHOLE timeline (``sentio trace
    --chrome``), or one request's record + its tick window
    (``/debug/flight/{id}?format=chrome``). Returns None when the request
    id has no record."""
    if recorder is None:
        from sentio_tpu.infra.flight import get_flight_recorder

        recorder = get_flight_recorder()
    if request_id is not None:
        record = recorder.get(request_id)
        if record is None:
            return None
        return build_chrome_trace(record.pop("ticks", []), [record],
                                  label=label)
    return build_chrome_trace(recorder.timeline(), recorder.records(),
                              label=label)
