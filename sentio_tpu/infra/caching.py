"""Cache tiers: LRU+TTL memory cache, pluggable L2, typed manager, strategies.

Parity with /root/reference/src/core/caching/ (memory_cache.py:36-360,
cache_manager.py:25-381, strategies.py:16-343): an in-process LRU+TTL cache
with pattern clear and stats, a manager with MEMORY / MULTI_TIER backends
(L2 is a pluggable async interface — redis isn't in this image, so the slot
ships with a null implementation and degrades to memory exactly like the
reference degrades when redis is down), typed embedding/query helpers, and
pluggable should-cache/TTL strategies including the adaptive hit-rate one.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Optional, Protocol

from sentio_tpu.config import CacheConfig, get_settings


class MemoryCache:
    """Thread-safe LRU with per-entry TTL."""

    def __init__(self, max_entries: int = 10_000, default_ttl_s: float = 3600.0) -> None:
        self.max_entries = max_entries
        self.default_ttl_s = default_ttl_s
        self._store: OrderedDict[str, tuple[Any, float, float]] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            entry = self._store.get(key)
            if entry is None:
                self.misses += 1
                return None
            value, stored_at, ttl = entry
            if ttl > 0 and time.perf_counter() - stored_at > ttl:
                del self._store[key]
                self.misses += 1
                return None
            self._store.move_to_end(key)
            self.hits += 1
            return value

    def set(self, key: str, value: Any, ttl_s: Optional[float] = None) -> None:
        with self._lock:
            ttl = self.default_ttl_s if ttl_s is None else ttl_s
            if key in self._store:
                self._store.move_to_end(key)
            self._store[key] = (value, time.perf_counter(), ttl)
            while len(self._store) > self.max_entries:
                self._store.popitem(last=False)
                self.evictions += 1

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._store.pop(key, None) is not None

    def clear(self, pattern: str = "*") -> int:
        with self._lock:
            if pattern == "*":
                n = len(self._store)
                self._store.clear()
                return n
            doomed = [k for k in self._store if fnmatch.fnmatch(k, pattern)]
            for k in doomed:
                del self._store[k]
            return len(doomed)

    def cleanup_expired(self) -> int:
        now = time.perf_counter()
        with self._lock:
            doomed = [
                k for k, (_, at, ttl) in self._store.items() if ttl > 0 and now - at > ttl
            ]
            for k in doomed:
                del self._store[k]
            return len(doomed)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._store),
                "max_entries": self.max_entries,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
            }


class L2Cache(Protocol):
    """Async second tier (redis-shaped). Implementations must never raise
    into the caller — the manager treats any exception as a miss."""

    async def get(self, key: str) -> Optional[Any]: ...
    async def set(self, key: str, value: Any, ttl_s: float) -> None: ...
    async def delete(self, key: str) -> None: ...
    async def ping(self) -> bool: ...


class NullL2Cache:
    """The no-redis placeholder: always a miss, always healthy=False."""

    async def get(self, key: str) -> Optional[Any]:
        return None

    async def set(self, key: str, value: Any, ttl_s: float) -> None:
        return None

    async def delete(self, key: str) -> None:
        return None

    async def ping(self) -> bool:
        return False


# --------------------------------------------------------------------- strategies


class CacheStrategy(Protocol):
    def should_cache(self, key: str, value: Any) -> bool: ...
    def ttl_for(self, key: str, value: Any) -> float: ...


@dataclass
class TTLStrategy:
    ttl_s: float = 3600.0

    def should_cache(self, key: str, value: Any) -> bool:
        return value is not None

    def ttl_for(self, key: str, value: Any) -> float:
        return self.ttl_s


@dataclass
class SizeAwareStrategy:
    """Skip caching oversized values (size estimated via repr length)."""

    max_bytes: int = 256 * 1024
    ttl_s: float = 3600.0

    def should_cache(self, key: str, value: Any) -> bool:
        if value is None:
            return False
        try:
            return len(repr(value)) <= self.max_bytes
        except Exception:  # noqa: BLE001 — unreprable value just fails the size gate
            return False

    def ttl_for(self, key: str, value: Any) -> float:
        return self.ttl_s


class AdaptiveStrategy:
    """Learns per-prefix hit rates and extends TTL for hot prefixes,
    shrinks it for cold ones (reference strategies.py adaptive variant)."""

    def __init__(self, base_ttl_s: float = 3600.0) -> None:
        self.base_ttl_s = base_ttl_s
        self._hits: dict[str, int] = {}
        self._misses: dict[str, int] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _prefix(key: str) -> str:
        return key.split(":", 1)[0]

    def record(self, key: str, hit: bool) -> None:
        p = self._prefix(key)
        with self._lock:
            table = self._hits if hit else self._misses
            table[p] = table.get(p, 0) + 1

    def hit_rate(self, key: str) -> float:
        p = self._prefix(key)
        with self._lock:
            h, m = self._hits.get(p, 0), self._misses.get(p, 0)
        return h / (h + m) if h + m else 0.5

    def should_cache(self, key: str, value: Any) -> bool:
        return value is not None

    def ttl_for(self, key: str, value: Any) -> float:
        rate = self.hit_rate(key)
        return self.base_ttl_s * (0.25 + 1.5 * rate)


# ----------------------------------------------------------------------- manager


class CacheManager:
    """MEMORY or MULTI_TIER (L1 memory + async L2 with promotion on L2 hit).
    L2 failure degrades silently to memory-only, like the reference's
    redis-down path (cache_manager.py:77-84 there)."""

    def __init__(
        self,
        config: Optional[CacheConfig] = None,
        l2: Optional[L2Cache] = None,
        strategy: Optional[CacheStrategy] = None,
    ) -> None:
        self.config = config or get_settings().cache
        self.l1 = MemoryCache(self.config.max_entries, self.config.default_ttl_s)
        if l2 is None and self.config.backend == "multi_tier":
            # redis L2 via the in-tree RESP client; errors degrade to misses
            from sentio_tpu.infra.redis_cache import RedisL2Cache

            l2 = RedisL2Cache(
                url=self.config.redis_url, key_prefix=self.config.redis_key_prefix
            )
        self.l2: L2Cache = l2 or NullL2Cache()
        self.strategy: CacheStrategy = strategy or TTLStrategy(self.config.default_ttl_s)
        self.enabled = self.config.backend != "off"
        self.multi_tier = self.config.backend == "multi_tier"

    # sync L1 surface
    def get(self, key: str) -> Optional[Any]:
        if not self.enabled:
            return None
        value = self.l1.get(key)
        if isinstance(self.strategy, AdaptiveStrategy):
            self.strategy.record(key, hit=value is not None)
        return value

    def set(self, key: str, value: Any, ttl_s: Optional[float] = None) -> None:
        if not self.enabled or not self.strategy.should_cache(key, value):
            return
        self.l1.set(key, value, ttl_s if ttl_s is not None else self.strategy.ttl_for(key, value))

    # async surface adds the L2 tier
    async def aget(self, key: str) -> Optional[Any]:
        value = self.get(key)
        if value is not None or not self.multi_tier:
            return value
        try:
            value = await self.l2.get(key)
        except Exception:  # noqa: BLE001 — L2 outage degrades to L1-only, miss path
            return None
        if value is not None:  # promote
            self.l1.set(key, value)
        return value

    async def aset(self, key: str, value: Any, ttl_s: Optional[float] = None) -> None:
        self.set(key, value, ttl_s)
        if self.multi_tier and self.strategy.should_cache(key, value):
            try:
                await self.l2.set(
                    key, value, ttl_s if ttl_s is not None else self.strategy.ttl_for(key, value)
                )
            except Exception:  # noqa: BLE001 — L2 write-through is best-effort
                pass

    # typed helpers (reference cache_manager.py:296-341)
    def get_query_response(self, query: str) -> Optional[dict]:
        return self.get(f"query:{query.strip().lower()}")

    def set_query_response(self, query: str, response: dict) -> None:
        self.set(f"query:{query.strip().lower()}", response, self.config.query_cache_ttl_s)

    def get_embedding(self, text_hash: str) -> Optional[Any]:
        return self.get(f"emb:{text_hash}")

    def set_embedding(self, text_hash: str, vec: Any) -> None:
        self.set(f"emb:{text_hash}", vec)

    def stats(self) -> dict[str, Any]:
        return {
            "backend": self.config.backend,
            "l1": self.l1.stats(),
            "multi_tier": self.multi_tier,
        }


_manager: Optional[CacheManager] = None


def get_cache_manager() -> CacheManager:
    global _manager
    if _manager is None:
        _manager = CacheManager()
    return _manager


def set_cache_manager(manager: Optional[CacheManager]) -> None:
    global _manager
    _manager = manager
