"""Resilience primitives: circuit breakers, retries, timeouts, fallbacks.

Parity with /root/reference/src/core/resilience/ (patterns.py:30-462,
fallbacks.py:18-265, decorators.py:18-103): CLOSED/OPEN/HALF_OPEN breakers
(sync + async) with stats, jittered exponential retry, a ResilientCall
combinator (breaker + retry + timeout), periodic HealthChecker, and the
3-tier degradation ladder's building blocks — disk-persisted response cache,
deterministic hash embedding fallback, template LLM fallback. On TPU the
breakers additionally guard device dispatch (OOM / compile / timeout), not
just remote HTTP.
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import json
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from pathlib import Path
from typing import Any, Awaitable, Callable, Optional, TypeVar

from sentio_tpu.infra.exceptions import CircuitOpenError, TimeoutError_

logger = logging.getLogger(__name__)

T = TypeVar("T")


class CircuitState(str, Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


_breakers: dict[str, "CircuitBreaker"] = {}
_registry_lock = threading.Lock()


def registered_breakers() -> dict[str, "CircuitBreaker"]:
    """Live breaker registry for health reporting (the reference exposes
    breaker states via get_health_status, jina_reranker.py:324-340 there)."""
    with _registry_lock:
        return dict(_breakers)


@dataclass
class BreakerStats:
    calls: int = 0
    failures: int = 0
    successes: int = 0
    rejected: int = 0
    state_changes: int = 0
    consecutive_failures: int = 0


class CircuitBreaker:
    """Thread-safe breaker: OPEN after ``failure_threshold`` consecutive
    failures, HALF_OPEN probe after ``recovery_timeout_s``, re-CLOSED after
    ``success_threshold`` probe successes."""

    def __init__(
        self,
        name: str = "breaker",
        failure_threshold: int = 5,
        recovery_timeout_s: float = 30.0,
        success_threshold: int = 2,
    ) -> None:
        self.name = name
        self.failure_threshold = failure_threshold
        self.recovery_timeout_s = recovery_timeout_s
        self.success_threshold = success_threshold
        self.state = CircuitState.CLOSED
        self.stats = BreakerStats()
        self._opened_at = 0.0
        self._half_open_successes = 0
        self._lock = threading.Lock()
        with _registry_lock:
            _breakers[name] = self

    def _transition(self, new_state: CircuitState) -> None:
        if new_state != self.state:
            logger.info("breaker %s: %s -> %s", self.name, self.state.value, new_state.value)
            self.state = new_state
            self.stats.state_changes += 1

    def allow(self) -> bool:
        with self._lock:
            if self.state == CircuitState.CLOSED:
                return True
            if self.state == CircuitState.OPEN:
                if time.monotonic() - self._opened_at >= self.recovery_timeout_s:
                    self._transition(CircuitState.HALF_OPEN)
                    self._half_open_successes = 0
                    return True
                self.stats.rejected += 1
                return False
            return True  # HALF_OPEN probes flow

    def record_success(self) -> None:
        with self._lock:
            self.stats.calls += 1
            self.stats.successes += 1
            self.stats.consecutive_failures = 0
            if self.state == CircuitState.HALF_OPEN:
                self._half_open_successes += 1
                if self._half_open_successes >= self.success_threshold:
                    self._transition(CircuitState.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self.stats.calls += 1
            self.stats.failures += 1
            self.stats.consecutive_failures += 1
            if self.state == CircuitState.HALF_OPEN or (
                self.state == CircuitState.CLOSED
                and self.stats.consecutive_failures >= self.failure_threshold
            ):
                self._transition(CircuitState.OPEN)
                self._opened_at = time.monotonic()

    def call(self, fn: Callable[..., T], *args, **kwargs) -> T:
        if not self.allow():
            raise CircuitOpenError(f"circuit {self.name} is open")
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result

    async def acall(self, fn: Callable[..., Awaitable[T]], *args, **kwargs) -> T:
        if not self.allow():
            raise CircuitOpenError(f"circuit {self.name} is open")
        try:
            result = await fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result

    def health(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "state": self.state.value,
            "failures": self.stats.failures,
            "successes": self.stats.successes,
            "rejected": self.stats.rejected,
            "consecutive_failures": self.stats.consecutive_failures,
        }


@dataclass
class RetryPolicy:
    """Jittered exponential backoff (reference AsyncRetry, patterns.py:403-462).

    ``rng`` injects a seeded ``random.Random`` so backoff jitter is
    deterministic in tests; None uses the module-level generator."""

    max_attempts: int = 3
    base_delay_s: float = 0.2
    max_delay_s: float = 10.0
    jitter: float = 0.25
    retry_on: tuple[type[Exception], ...] = (Exception,)
    rng: Optional[random.Random] = None

    def _check_attempts(self) -> None:
        # max_attempts <= 0 used to fall through the loop and `raise None`
        # (a TypeError masking the config error) — fail with the real cause
        if self.max_attempts < 1:
            raise ValueError(
                f"RetryPolicy.max_attempts must be >= 1, got {self.max_attempts}"
            )

    def delay(self, attempt: int) -> float:
        d = min(self.base_delay_s * (2**attempt), self.max_delay_s)
        jitter = (self.rng or random).uniform(-self.jitter, self.jitter)
        return d * (1.0 + jitter)

    def run(self, fn: Callable[..., T], *args, **kwargs) -> T:
        self._check_attempts()
        last: Optional[Exception] = None
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as exc:
                last = exc
                if attempt < self.max_attempts - 1:
                    time.sleep(self.delay(attempt))
        raise last  # type: ignore[misc]

    async def arun(self, fn: Callable[..., Awaitable[T]], *args, **kwargs) -> T:
        self._check_attempts()
        last: Optional[Exception] = None
        for attempt in range(self.max_attempts):
            try:
                return await fn(*args, **kwargs)
            except self.retry_on as exc:
                last = exc
                if attempt < self.max_attempts - 1:
                    await asyncio.sleep(self.delay(attempt))
        raise last  # type: ignore[misc]


class ResilientCall:
    """Breaker + retry + timeout combinator (reference ResilientClient,
    patterns.py:145-249) for async callables."""

    def __init__(
        self,
        name: str,
        breaker: Optional[CircuitBreaker] = None,
        retry: Optional[RetryPolicy] = None,
        timeout_s: float = 30.0,
    ) -> None:
        self.name = name
        self.breaker = breaker or CircuitBreaker(name=name)
        self.retry = retry or RetryPolicy()
        self.timeout_s = timeout_s

    async def execute(self, fn: Callable[..., Awaitable[T]], *args, **kwargs) -> T:
        async def bounded() -> T:
            try:
                return await asyncio.wait_for(fn(*args, **kwargs), timeout=self.timeout_s)
            except asyncio.TimeoutError as exc:
                raise TimeoutError_(f"{self.name} timed out after {self.timeout_s}s") from exc

        async def guarded() -> T:
            return await self.breaker.acall(bounded)

        return await self.retry.arun(guarded)


def with_circuit_breaker(breaker: CircuitBreaker):
    def deco(fn):
        if asyncio.iscoroutinefunction(fn):

            @functools.wraps(fn)
            async def awrapper(*args, **kwargs):
                return await breaker.acall(fn, *args, **kwargs)

            return awrapper

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return breaker.call(fn, *args, **kwargs)

        return wrapper

    return deco


def with_retry(policy: Optional[RetryPolicy] = None):
    policy = policy or RetryPolicy()

    def deco(fn):
        if asyncio.iscoroutinefunction(fn):

            @functools.wraps(fn)
            async def awrapper(*args, **kwargs):
                return await policy.arun(fn, *args, **kwargs)

            return awrapper

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return policy.run(fn, *args, **kwargs)

        return wrapper

    return deco


class HealthChecker:
    """Periodic breaker/callback probe loop (reference patterns.py:252-306)."""

    def __init__(self, interval_s: float = 30.0) -> None:
        self.interval_s = interval_s
        self._probes: dict[str, Callable[[], bool]] = {}
        self._results: dict[str, dict[str, Any]] = {}
        self._task: Optional[asyncio.Task] = None

    def register(self, name: str, probe: Callable[[], bool]) -> None:
        self._probes[name] = probe

    async def _loop(self) -> None:
        while True:
            for name, probe in list(self._probes.items()):
                try:
                    ok = bool(probe())
                except Exception as exc:  # noqa: BLE001
                    ok = False
                    self._results[name] = {"ok": False, "error": str(exc), "at": time.time()}  # wall-clock: reported probe time
                    continue
                self._results[name] = {"ok": ok, "at": time.time()}  # wall-clock: reported probe time
            await asyncio.sleep(self.interval_s)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def results(self) -> dict[str, dict[str, Any]]:
        return dict(self._results)


# ---------------------------------------------------------------------------
# fallback ladder components


class FallbackResponseCache:
    """Disk-persisted query→response cache, sha256 keys + TTL (reference
    FallbackManager, fallbacks.py:18-159). Tier 1 of the degradation ladder:
    a failing pipeline first replays the last good answer.

    Bounded: at most ``max_entries`` responses are kept (oldest-written
    evicted first), and every mutation — including expired-entry deletion,
    which previously lived only in memory and resurrected on restart —
    persists to disk."""

    def __init__(self, cache_dir: Optional[str] = None, ttl_s: float = 24 * 3600.0,
                 max_entries: int = 512) -> None:
        self.dir = Path(cache_dir or Path.home() / ".cache" / "sentio_tpu_fallback")
        self.ttl_s = ttl_s
        self.max_entries = max(int(max_entries), 1)
        self._path = self.dir / "responses.json"
        self._store: dict[str, dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._load()

    @staticmethod
    def _key(query: str) -> str:
        return hashlib.sha256(query.strip().lower().encode()).hexdigest()

    def _load(self) -> None:
        try:
            self._store = json.loads(self._path.read_text())
        except (OSError, json.JSONDecodeError):
            self._store = {}

    def _persist(self) -> None:
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            self._path.write_text(json.dumps(self._store))
        except OSError:
            logger.warning("fallback cache persist failed", exc_info=True)

    def _evict_locked(self) -> None:
        """Drop least-recently-USED entries past the cap — an unbounded disk
        cache grows one JSON blob per distinct query forever. Recency falls
        back to the write stamp for entries never read (or loaded from a
        pre-LRU disk file)."""
        while len(self._store) > self.max_entries:
            oldest = min(
                self._store,
                key=lambda k: self._store[k].get(
                    "last_used", self._store[k].get("at", 0.0)),
            )
            del self._store[oldest]

    def put(self, query: str, response: str) -> None:
        with self._lock:
            self._store[self._key(query)] = {"response": response, "at": time.time()}  # wall-clock: TTL persists across restarts
            self._evict_locked()
            self._persist()

    def get(self, query: str) -> Optional[str]:
        with self._lock:
            entry = self._store.get(self._key(query))
            if entry is None:
                return None
            if self.ttl_s > 0 and time.time() - entry["at"] > self.ttl_s:  # wall-clock: TTL persists across restarts
                del self._store[self._key(query)]
                # persist the deletion: an expired entry that only dies in
                # memory comes back from disk on the next restart
                self._persist()
                return None
            # recency for LRU eviction; deliberately NOT persisted per get
            # (a disk write per cache hit on the degraded path would be
            # worse than losing recency hints across restarts)
            entry["last_used"] = time.time()  # wall-clock: stored beside the TTL stamp
            return entry["response"]


class LLMFallback:
    """Tier 2: template answers from prompts/fallback_*.md (reference
    fallbacks.py:205-259); tier 3 is the apology template."""

    def __init__(self, prompts_dir: Optional[str] = None) -> None:
        from sentio_tpu.ops.prompts import PromptBuilder

        self._prompts = PromptBuilder(prompts_dir)

    def no_retrieval(self, query: str) -> str:
        return self._prompts.build("fallback_no_retrieval", query=query)

    def no_llm(self, context: str) -> str:
        return self._prompts.build("fallback_no_llm", context=context)

    def apology(self) -> str:
        return self._prompts.build("fallback_apology")


def embedding_fallback(text: str, dim: int) -> "list[float]":
    """Deterministic unit pseudo-embedding (reference EmbeddingFallback,
    fallbacks.py:162-202) — retrieval stays alive when the device path dies."""
    import numpy as np

    seed = int.from_bytes(hashlib.md5(text.lower().encode()).digest()[:8], "little")
    rng = np.random.default_rng(seed)
    vec = rng.standard_normal(dim).astype(np.float32)
    vec /= max(float(np.linalg.norm(vec)), 1e-9)
    return vec.tolist()
