"""Tick-phase time attribution: where a pump-loop millisecond goes.

The flight recorder (infra/flight.py) times ticks as opaque wholes; this
module gives every pump iteration a named-phase decomposition so host work
is separable from device compute — the measurement ROADMAP item 1's
multi-process argument needs (host-fraction x N replicas is the direct GIL
ceiling). Phases are plain ``perf_counter`` deltas: no spans, no context
objects on the hot path beyond one tiny ``_PhaseSpan``, nothing when a
section simply stamps two clocks.

The phase set is FIXED and BOUNDED (``TICK_PHASES``): per-tick ``phase_ms``
dicts on flight tick records and the ``sentio_tpu_tick_phase_seconds``
histogram label space can never grow by a typo'd key (metrics cardinality
guard — unknown keys are dropped at the recording seam).

Phase glossary (one pump iteration, in canonical order):

``inbox_drain``
    Service-side mutex section at the loop top: heartbeat stamp, cancelled/
    expired sweeps, engine ``submit`` for every inbox ticket.
``admission_build``
    Host-side admission work inside ``engine.step()``: tokenization, radix
    matching, page allocation, padded numpy array assembly — everything in
    ``_admit``/``_advance_prefill`` EXCEPT the jit dispatch calls.
``prefill_dispatch``
    Host call time of the prefill/scatter jit dispatches (async on device;
    this is what the dispatch costs the PUMP THREAD — the GIL-held part).
``decode_dispatch``
    Host call time of the fused decode dispatch (``step_n``/spec tick) plus
    its merge/budget prep — again host-side cost of an async dispatch.
``device_wait``
    Time blocked on device results: the harvest's packed-token fetch
    (``np.asarray`` on a not-yet-ready array) and any blocking first-token
    fold. With ``pipeline_depth=2`` the dispatch overlaps the previous
    fetch, so the wait measured in iteration N is for the tick dispatched
    at N-1 — it is charged to the iteration that HARVESTS it, which is
    where the wall clock actually went (per-iteration conservation holds).
``deliver``
    Service-side mutex section after the tick: TTFT stamping, stream-queue
    pushes, result/event completion.
``other``
    Everything else measured inside the iteration (sanitizer invariant
    walks, telemetry recording) — kept explicit so per-tick conservation
    (``sum(phase_ms) == pump_ms``) holds by construction, not by tolerance.

``idle`` is not a tick phase: it is the duty-cycle complement (wall time
with no pump iteration running — pump down, or gaps between bursts).
"""

from __future__ import annotations

import time

__all__ = [
    "TICK_PHASES",
    "ENGINE_PHASES",
    "HOST_PHASES",
    "DUTY_STATES",
    "PhaseTimer",
    "duty_fractions",
    "phases_to_ms",
    "sum_phase_totals",
]

# the one bounded key set — flight `phase_ms`, the tick-phase histogram's
# `phase` label, and the conservation test all pin against this tuple
TICK_PHASES = (
    "inbox_drain",
    "admission_build",
    "prefill_dispatch",
    "decode_dispatch",
    "device_wait",
    "deliver",
    "other",
)

# the subset engine.step() itself attributes (the service adds the rest)
ENGINE_PHASES = (
    "admission_build",
    "prefill_dispatch",
    "decode_dispatch",
    "device_wait",
    "other",
)

# duty-cycle rollup: every phase that burns the host thread (and, with N
# replicas in one process, contends for the one GIL) vs. blocked-on-device
HOST_PHASES = tuple(p for p in TICK_PHASES if p != "device_wait")

DUTY_STATES = ("host", "device", "idle")


class _PhaseSpan:
    """Tiny enter/exit timer — two perf_counter calls and a dict add."""

    __slots__ = ("_timer", "_key", "_t0")

    def __init__(self, timer: "PhaseTimer", key: str) -> None:
        self._timer = timer
        self._key = key

    def __enter__(self) -> "_PhaseSpan":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._timer.add(self._key, time.perf_counter() - self._t0)
        return False


class PhaseTimer:
    """Per-iteration phase accumulator. NOT thread-safe by design — one
    timer belongs to one pump/engine thread; cross-thread aggregation
    happens on snapshots. A region may be entered many times per tick
    (every prefill dispatch adds to ``prefill_dispatch``); keys outside
    the constructor's set are rejected so the bounded-set guarantee is
    enforced at the writer, not just the exporter."""

    __slots__ = ("acc",)

    def __init__(self, keys: tuple = TICK_PHASES) -> None:
        self.acc: dict[str, float] = dict.fromkeys(keys, 0.0)

    def reset(self) -> None:
        for key in self.acc:
            self.acc[key] = 0.0

    def add(self, key: str, seconds: float) -> None:
        # KeyError on an unknown phase is deliberate: a typo'd phase name
        # must fail the tick that introduced it, not mint a metric series
        self.acc[key] += seconds

    def phase(self, key: str) -> _PhaseSpan:
        """Context manager timing one region into ``key``."""
        if key not in self.acc:
            raise KeyError(f"unknown phase {key!r} (bounded set: {tuple(self.acc)})")
        return _PhaseSpan(self, key)

    def total(self) -> float:
        return sum(self.acc.values())

    def snapshot_ms(self) -> dict[str, float]:
        """Bounded ``phase_ms`` dict for a flight tick record (zero phases
        included — a fixed shape diffs and plots cleanly)."""
        return phases_to_ms(self.acc)


def phases_to_ms(phase_s: dict) -> dict:
    """Seconds-per-phase → the ``phase_ms`` wire shape (ms, 3 decimals).
    ONE definition — the pump's flight records and PhaseTimer.snapshot_ms
    must never drift (the chrome-trace golden fixture pins the format)."""
    return {k: round(v * 1e3, 3) for k, v in phase_s.items()}


def sum_phase_totals(rows) -> tuple:
    """Fold per-replica stats rows (each carrying cumulative
    ``phase_seconds`` + ``duty_elapsed_s``) into fleet totals:
    ``(phase_totals, duty_elapsed_s)``. ONE definition shared by
    ``ReplicaSet.stats()`` and the telemetry merge path — the fleet's
    phase arithmetic must not drift between replica modes. Rows without
    phase data (a dead worker's fallback stats) contribute nothing."""
    totals: dict[str, float] = {}
    elapsed = 0.0
    for row in rows:
        for key, val in (row.get("phase_seconds") or {}).items():
            totals[key] = totals.get(key, 0.0) + float(val)
        elapsed += float(row.get("duty_elapsed_s", 0.0))
    return totals, elapsed


def duty_fractions(phase_totals: dict, elapsed_s: float) -> dict:
    """Fold cumulative phase seconds into host/device/idle fractions of
    ``elapsed_s`` wall time, summing to exactly 1.0 (the gauge contract:
    ``sentio_tpu_pump_duty_cycle{state}`` over one replica sums to 1).
    Measurement skew (busy marginally exceeding elapsed on a coarse clock)
    clamps idle at 0 and renormalizes."""
    if elapsed_s <= 0:
        return {"host": 0.0, "device": 0.0, "idle": 1.0}
    host = sum(phase_totals.get(k, 0.0) for k in HOST_PHASES)
    device = phase_totals.get("device_wait", 0.0)
    idle = max(elapsed_s - host - device, 0.0)
    total = host + device + idle
    return {
        "host": round(host / total, 6),
        "device": round(device / total, 6),
        "idle": round(idle / total, 6),
    }
