"""Redis L2 cache over a minimal in-tree RESP2 client (asyncio sockets).

Parity with the reference's optional redis tier (src/core/caching/
redis_cache.py there: key prefix, JSON serialization, TTL, health check,
silent degradation when redis is down) WITHOUT the redis-py dependency —
the image doesn't ship it, and the cache needs only five commands (AUTH,
PING, GET, SET PX, DEL), which is a few dozen lines of RESP2 framing.

Values are JSON (never pickle — an attacker with redis access must not get
code execution in the server). Every public method satisfies the
:class:`sentio_tpu.infra.caching.L2Cache` contract: errors surface as
misses/None, never as exceptions into the cache manager; the connection
re-establishes on next use after a failure.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Optional
from urllib.parse import urlparse

logger = logging.getLogger(__name__)

_CRLF = b"\r\n"


class RESPError(Exception):
    pass


def _encode_command(*args: str | bytes) -> bytes:
    """RESP2 array-of-bulk-strings encoding."""
    out = [b"*%d" % len(args), _CRLF]
    for arg in args:
        data = arg if isinstance(arg, bytes) else str(arg).encode()
        out += [b"$%d" % len(data), _CRLF, data, _CRLF]
    return b"".join(out)


async def _read_reply(reader: asyncio.StreamReader) -> Any:
    line = (await reader.readuntil(_CRLF))[:-2]
    kind, rest = line[:1], line[1:]
    if kind == b"+":  # simple string
        return rest.decode()
    if kind == b"-":  # error
        raise RESPError(rest.decode())
    if kind == b":":  # integer
        return int(rest)
    if kind == b"$":  # bulk string
        n = int(rest)
        if n == -1:
            return None
        data = await reader.readexactly(n + 2)
        return data[:-2]
    if kind == b"*":  # array
        n = int(rest)
        if n == -1:
            return None
        return [await _read_reply(reader) for _ in range(n)]
    raise RESPError(f"unknown RESP type byte {kind!r}")


class RedisL2Cache:
    """L2Cache implementation speaking RESP2 to a redis-compatible server.

    One connection, serialized by an asyncio lock (the cache manager issues
    low-rate single-key ops; pipelining is not worth the complexity here).
    """

    def __init__(
        self,
        url: str = "redis://localhost:6379/0",
        key_prefix: str = "sentio:",
        timeout_s: float = 2.0,
    ) -> None:
        parsed = urlparse(url)
        if parsed.scheme not in ("redis", ""):
            raise ValueError(f"unsupported redis url scheme {parsed.scheme!r}")
        self.host = parsed.hostname or "localhost"
        self.port = parsed.port or 6379
        self.password = parsed.password or ""
        try:
            self.db = int((parsed.path or "/0").lstrip("/") or 0)
        except ValueError:
            self.db = 0
        self.key_prefix = key_prefix
        self.timeout_s = timeout_s
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    # ------------------------------------------------------------ connection

    async def _connect(self) -> None:
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.timeout_s
        )
        try:
            if self.password:
                await self._command_locked("AUTH", self.password)
            if self.db:
                await self._command_locked("SELECT", str(self.db))
        except BaseException:
            # a failed handshake (wrong password, bad db, timeout) must not
            # leave a half-initialized connection installed — later commands
            # would run unauthenticated / on the wrong db forever
            self._drop_connection()
            raise

    def _drop_connection(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # noqa: BLE001 — closing an already-dead socket
                pass
        self._reader = self._writer = None

    async def _command_locked(self, *args: str | bytes) -> Any:
        assert self._writer is not None and self._reader is not None
        self._writer.write(_encode_command(*args))
        await asyncio.wait_for(self._writer.drain(), self.timeout_s)
        return await asyncio.wait_for(_read_reply(self._reader), self.timeout_s)

    async def _command(self, *args: str | bytes) -> Any:
        async with self._lock:
            if self._writer is None:
                await self._connect()
            try:
                return await self._command_locked(*args)
            except (OSError, asyncio.TimeoutError, asyncio.IncompleteReadError):
                # dead connection: drop it so the next call redials
                self._drop_connection()
                raise
            except RESPError as exc:
                # auth/protocol desync (NOAUTH, WRONGPASS, LOADING) means the
                # session state is wrong, not just this command — redial
                msg = str(exc).upper()
                if msg.startswith(("NOAUTH", "WRONGPASS", "LOADING", "MASTERDOWN")):
                    self._drop_connection()
                raise

    # ----------------------------------------------------------- L2 surface

    def _k(self, key: str) -> str:
        return self.key_prefix + key

    async def get(self, key: str) -> Optional[Any]:
        try:
            raw = await self._command("GET", self._k(key))
        except Exception as exc:  # noqa: BLE001 — contract: errors are misses
            logger.debug("redis get failed: %s", exc)
            return None
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except (ValueError, TypeError):
            return None

    async def set(self, key: str, value: Any, ttl_s: float) -> None:
        try:
            payload = json.dumps(value, default=str)
        except (TypeError, ValueError):
            return
        px = max(int(ttl_s * 1000), 1)
        try:
            await self._command("SET", self._k(key), payload, "PX", str(px))
        except Exception as exc:  # noqa: BLE001
            logger.debug("redis set failed: %s", exc)

    async def delete(self, key: str) -> None:
        try:
            await self._command("DEL", self._k(key))
        except Exception as exc:  # noqa: BLE001
            logger.debug("redis del failed: %s", exc)

    async def ping(self) -> bool:
        try:
            return await self._command("PING") == "PONG"
        except Exception:  # noqa: BLE001 — any failure means "not reachable"
            return False

    async def close(self) -> None:
        async with self._lock:
            self._drop_connection()
