"""Fault injection — deterministic failure simulation for resilience tests.

SURVEY.md §5 notes the reference has NO fault-injection framework (failures
are simulated ad hoc with mocks in its tests) and prescribes adding "a
fault-injection hook (drop/deadline a batch) for tests" to the build. This
module is that hook: named injection points are planted at the framework's
failure-relevant seams (device dispatch in the generator engine, retriever
legs, reranker batches, ``worker.stream_chunk`` between a process-mode
worker's delivered stream chunks — the mid-stream death the resumable-
stream drills arm ``kill_process``/``stall_s`` at), default to no-ops with
near-zero overhead, and
tests (or chaos drills) arm them with rules — fail N times, fail with a
given exception, add latency, fail with probability p under a seeded RNG,
or **stall**: block inside the injection point for a duration (or until the
test releases an event), simulating the wedged device dispatch that raises
nothing but never returns — the hang class of fault the watchdog layer
(runtime/replica.py) exists to detect.

Usage:

    with inject("engine.generate", error=TimeoutError("deadline"), times=2):
        ...  # first two generate dispatches raise, third proceeds

    release = threading.Event()
    with inject("paged.step", stall_event=release, stall_s=60.0, times=1):
        ...  # the next decode tick wedges until release.set() (60s cap)

Planting a point in framework code:

    faults.hit("engine.generate")   # raises if an armed rule says so

Points are process-global and thread-safe; ``reset()`` disarms everything
(autouse-able in fixtures). Arming is cheap; an unarmed ``hit`` is a dict
lookup on a usually-empty dict.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

__all__ = ["FaultRule", "arm", "disarm", "reset", "hit", "hit_frame",
           "inject", "active_rules"]


@dataclass
class FaultRule:
    """What happens when an armed point is hit.

    * ``error`` — exception instance to raise (a fresh copy each hit via
      type(error)(*error.args), so tracebacks don't chain weirdly).
    * ``times`` — fire for the first N hits, then disarm (None = forever).
    * ``skip`` — ignore the first N hits entirely (fire from hit N+1 on):
      "the K+1th dispatch dies" armed deterministically BEFORE the work
      starts — e.g. a mid-stream kill that must land only after at least
      one decode tick's tokens were delivered.
    * ``probability`` — fire with this probability (seeded ``rng`` makes it
      deterministic in tests).
    * ``delay_s`` — sleep before (optionally) failing: deadline simulation.
    * ``stall_s`` / ``stall_event`` — the **hang** fault: block inside the
      injection point for ``stall_s`` seconds, or until the test sets
      ``stall_event`` (whichever comes first; ``stall_s=None`` with an
      event means wait for the release alone). The stall happens on the
      CALLING thread — arming it at ``paged.step`` wedges that replica's
      pump exactly like a hung device dispatch. Composes with ``error``:
      stall first, then raise (a dispatch that hangs and THEN dies).
    * ``kill_process`` — the **crash** fault: ``SIGKILL`` the CALLING
      process at the injection point. No handlers run, no frames unwind —
      the strongest possible replica death, meaningful only against
      process-mode replica workers (runtime/worker.py), whose supervisor
      must detect the corpse from the outside. Arming it in the test
      process itself kills the test runner; the worker RPC surface
      (``ProcessReplica.inject_fault``) arms it in the right process.
      Composes with ``stall_s`` (wedge, then die) but not ``error`` —
      the process is gone before any raise.
    * ``drop`` — the **network** fault, meaningful only at the transport
      frame points (``transport.recv[...]`` / ``transport.send[...]``,
      checked via :func:`hit_frame`): the frame is silently discarded —
      unsent, or received-and-ignored. ``drop=True, times=N`` is "lose the
      next N frames"; ``delay_s`` alone is a slow link; ``stall_s`` /
      ``stall_event`` at a recv point is the **half-open partition**
      (reads stall while the peer's writes — and this side's sends — keep
      succeeding), the fault the worker registry's incarnation epochs
      exist to make safe.
    """

    error: Optional[BaseException] = None
    times: Optional[int] = None
    probability: float = 1.0
    delay_s: float = 0.0
    stall_s: Optional[float] = None
    stall_event: Optional[threading.Event] = None
    kill_process: bool = False
    drop: bool = False
    skip: int = 0
    rng: random.Random = field(default_factory=lambda: random.Random(0))
    hits: int = 0
    fired: int = 0
    stalled: int = 0

    def should_fire(self) -> bool:
        # hits is incremented BEFORE this check: skip=N passes hits 1..N
        if self.hits <= self.skip:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        return self.probability >= 1.0 or self.rng.random() < self.probability


_rules: dict[str, FaultRule] = {}
_lock = threading.Lock()


def arm(point: str, rule: FaultRule) -> None:
    with _lock:
        _rules[point] = rule


def disarm(point: str) -> None:
    with _lock:
        _rules.pop(point, None)


def reset() -> None:
    with _lock:
        _rules.clear()


def active_rules() -> dict[str, FaultRule]:
    with _lock:
        return dict(_rules)


def _hit_impl(point: str) -> bool:
    """Shared body of :func:`hit` / :func:`hit_frame`: apply an armed
    rule's stall/kill/delay/error effects; return whether a fired rule
    asks for the frame to be DROPPED (transport points only)."""
    if not _rules:  # fast path: nothing armed anywhere
        return False
    with _lock:
        rule = _rules.get(point)
        if rule is None:
            return False
        rule.hits += 1
        fire = rule.should_fire()
        if fire:
            rule.fired += 1
        delay = rule.delay_s if fire else 0.0
        error = rule.error if fire else None
        stall_s = rule.stall_s if fire else None
        stall_event = rule.stall_event if fire else None
        if fire and (stall_s is not None or stall_event is not None):
            rule.stalled += 1
    # stall OUTSIDE the registry lock: a wedged injection point must not
    # block every other point's (unarmed, fast-path-missed) hit
    if stall_event is not None:
        stall_event.wait(stall_s)
    elif stall_s is not None and stall_s > 0:
        time.sleep(stall_s)
    if fire and rule.kill_process:
        # the crash fault: this process is gone NOW — no cleanup, no
        # flushing, exactly what a kernel OOM-kill or node loss looks like
        import os
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    if delay > 0:
        time.sleep(delay)
    if error is not None:
        raise type(error)(*error.args)
    return bool(fire and rule.drop)


def hit(point: str) -> None:
    """Framework code calls this at an injection point. No-op unless armed."""
    _hit_impl(point)


def hit_frame(point: str) -> bool:
    """Frame-granular transport variant of :func:`hit`: same stall / delay
    / error / kill semantics, plus a return value — True means an armed
    ``drop`` rule fired and the caller must discard this frame (unsent on
    the send path, read-and-ignored on the recv path)."""
    return _hit_impl(point)


@contextmanager
def inject(
    point: str,
    error: Optional[BaseException] = None,
    times: Optional[int] = None,
    probability: float = 1.0,
    delay_s: float = 0.0,
    stall_s: Optional[float] = None,
    stall_event: Optional[threading.Event] = None,
    drop: bool = False,
    skip: int = 0,
    seed: int = 0,
) -> Iterator[FaultRule]:
    """Arm ``point`` for the duration of the block; yields the rule so the
    test can assert on ``hits``/``fired``/``stalled``. NB: exiting the block
    disarms the point but does NOT release threads already wedged inside a
    stall — set the ``stall_event`` (or bound ``stall_s``) to free them."""
    rule = FaultRule(
        error=error, times=times, probability=probability,
        delay_s=delay_s, stall_s=stall_s, stall_event=stall_event,
        drop=drop, skip=skip, rng=random.Random(seed),
    )
    arm(point, rule)
    try:
        yield rule
    finally:
        disarm(point)
