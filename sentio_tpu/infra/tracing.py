"""Tracing: OpenTelemetry spans with OTLP/console exporters, a no-op mock
fallback, and JAX profiler correlation.

Parity with /root/reference/src/observability/tracing.py:34-347 — a
TracingManager with graceful degradation when OTel is absent, span context
managers and decorators for sync+async code — plus the TPU addition from
SURVEY.md §2.10: ``profile_step`` wraps a device batch step in a
``jax.profiler.StepTraceAnnotation`` (and optionally a trace session dumping
to ``observability.profiler_dir``) so request spans line up with XLA traces.
"""

from __future__ import annotations

import asyncio
import functools
import logging
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Optional

from sentio_tpu.config import ObservabilityConfig, get_settings

logger = logging.getLogger(__name__)


class MockSpan:
    def set_attribute(self, key: str, value: Any) -> "MockSpan":
        return self

    def record_exception(self, exc: BaseException) -> None:
        pass

    def set_status(self, *a, **k) -> None:
        pass

    def __enter__(self) -> "MockSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


class TracingManager:
    def __init__(self, config: Optional[ObservabilityConfig] = None) -> None:
        self.config = config or get_settings().observability
        self._tracer = None
        self._provider = None
        # THE hot-path guard: serving code (graph executor, serve
        # middleware, decode pump) tests this single bool before touching
        # span()/profile_step(). False when tracing is configured off OR
        # when OTel is absent — the mock-span fallback exists for direct
        # span() callers, but the hot path must stay a true no-op rather
        # than paying context-manager overhead to feed a mock.
        self.enabled = False
        if self.config.tracing_enabled:
            self._setup()

    def _setup(self) -> None:
        try:
            from opentelemetry import trace
            from opentelemetry.sdk.resources import Resource
            from opentelemetry.sdk.trace import TracerProvider
            from opentelemetry.sdk.trace.export import (
                BatchSpanProcessor,
                ConsoleSpanExporter,
                SimpleSpanProcessor,
            )

            resource = Resource.create({"service.name": self.config.service_name})
            provider = TracerProvider(resource=resource)
            if self.config.otlp_endpoint:
                try:
                    from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
                        OTLPSpanExporter,
                    )

                    provider.add_span_processor(
                        BatchSpanProcessor(OTLPSpanExporter(endpoint=self.config.otlp_endpoint))
                    )
                except ImportError:
                    logger.warning("OTLP exporter unavailable; skipping")
            if self.config.console_exporter:
                provider.add_span_processor(SimpleSpanProcessor(ConsoleSpanExporter()))
            trace.set_tracer_provider(provider)
            self._provider = provider
            self._tracer = trace.get_tracer(self.config.service_name)
            self.enabled = True
            logger.info("tracing enabled for %s", self.config.service_name)
        except ImportError:
            logger.info("opentelemetry not installed; tracing is a no-op")
            self._tracer = None
            self.enabled = False

    @contextmanager
    def span(self, name: str, **attributes: Any):
        if self._tracer is None:
            span = MockSpan()
            for k, v in attributes.items():
                span.set_attribute(k, v)
            yield span
            return
        with self._tracer.start_as_current_span(name) as span:
            for k, v in attributes.items():
                span.set_attribute(k, v)
            yield span

    @contextmanager
    def profile_step(self, name: str, step: int = 0):
        """Correlate a device dispatch with the XLA profiler timeline.
        ONLY the annotation setup is guarded: an exception raised by the
        traced body must propagate unmangled — the decode pump's crash
        containment and the chaos drills key off the original exception
        type (a broad except around the yield would re-enter the generator
        after a throw and replace a device fault with contextlib's
        \"generator didn't stop after throw()\")."""
        annotation = None
        try:
            import jax

            annotation = jax.profiler.StepTraceAnnotation(name, step_num=step)
            annotation.__enter__()
        except Exception:  # noqa: BLE001 — profiler unavailable: span-only fallback below
            annotation = None  # profiler unavailable: span-only fallback
        try:
            with self.span(f"tpu.{name}", step=step):
                yield
        finally:
            if annotation is not None:
                try:
                    annotation.__exit__(*sys.exc_info())
                except Exception:
                    logger.debug("StepTraceAnnotation exit failed",
                                 exc_info=True)

    def start_profiler(self, log_dir: Optional[str] = None) -> bool:
        target = log_dir or self.config.profiler_dir
        if not target:
            return False
        try:
            import jax

            jax.profiler.start_trace(target)
            return True
        except Exception:
            logger.warning("jax profiler start failed", exc_info=True)
            return False

    def stop_profiler(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — profiler may not be running
            pass

    def shutdown(self) -> None:
        if self._provider is not None:
            try:
                self._provider.shutdown()
            except Exception:  # noqa: BLE001 — provider shutdown is best-effort
                pass


def trace_function(name: Optional[str] = None, manager: Optional[TracingManager] = None):
    """Decorator for sync and async functions (reference tracing.py:181-265)."""

    def deco(fn):
        span_name = name or f"{fn.__module__}.{fn.__qualname__}"

        if asyncio.iscoroutinefunction(fn):

            @functools.wraps(fn)
            async def awrapper(*args, **kwargs):
                mgr = manager or get_tracing()
                with mgr.span(span_name):
                    return await fn(*args, **kwargs)

            return awrapper

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            mgr = manager or get_tracing()
            with mgr.span(span_name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


_tracing: Optional[TracingManager] = None


def get_tracing() -> TracingManager:
    global _tracing
    if _tracing is None:
        _tracing = TracingManager()
    return _tracing


def set_tracing(manager: Optional[TracingManager]) -> None:
    global _tracing
    _tracing = manager


# ------------------------------------------------------- windowed profiler

_profile_lock = threading.Lock()
_profile_active = False  # guarded-by: _profile_lock


def profile_window(seconds: float, log_dir: str) -> dict:
    """Arm ``jax.profiler`` for a bounded window and stop it — the
    ``/debug/profile?seconds=N`` implementation. Single-flight: the jax
    profiler is process-global, so a second concurrent window is refused
    rather than corrupting the first's trace. Blocking (sleeps for the
    window) — callers run it on a worker thread. Returns what happened;
    never raises (an unprofileable backend is an operator answer, not a
    500)."""
    global _profile_active
    with _profile_lock:
        if _profile_active:
            return {"started": False,
                    "error": "a profile window is already active"}
        _profile_active = True
    try:
        import jax

        try:
            jax.profiler.start_trace(log_dir)
        except Exception as exc:  # noqa: BLE001 — surface, don't crash
            return {"started": False, "error": f"start_trace failed: {exc}"}
        try:
            time.sleep(max(float(seconds), 0.0))
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                logger.warning("jax profiler stop failed", exc_info=True)
        return {"started": True, "seconds": float(seconds),
                "log_dir": log_dir}
    finally:
        with _profile_lock:
            _profile_active = False
