"""Speculative decoding INSIDE the paged continuous-batching engine.

Round 4 shipped draft-and-verify speculation for the contiguous engine only
(runtime/speculative.py) — which meant the documented LLM_DRAFT_CHECKPOINT
knob was dead in the default deployment (USE_PAGED_KV=1; flagged by the
round-4 advisor). This module fuses the same exact-by-construction
draft/verify/accept math into the paged engine's tick protocol, so
continuous batching and speculation compose: every live slot drafts and
verifies in the same fused dispatch, page tables stay the source of truth,
and requests still join/leave without recompilation.

Design (one compiled ``spec_tick`` per (k, out_w) pair):

1. **Densify** — each row's page table gathers into a contiguous
   [L, S, W, Hkv, D] cache (int8 pages dequantize on the way in). Decode
   attention reads the whole past KV anyway, so the extra densification
   traffic is second-order next to the target's weight stream — the thing
   speculation amortizes.
2. **Rounds** — a ``lax.while_loop`` of draft(k)+verify(k+1)+accept rounds,
   identical math to runtime/speculative.py (greedy rows: longest
   agree-prefix, bit-exact vs plain decode; sampled rows: rejection
   sampling via :func:`runtime.speculative.accept_and_correct`, marginally
   exact). Both rules are computed and selected PER ROW by temperature, so
   mixed batches serve correctly. Per-row tick budgets bound emissions;
   EOS halts rows (unless ignore_eos).
3. **Scatter back** — the dense cache writes back through the same
   ``scatter_prefill`` every other admission path uses (re-quantization is
   idempotent: absmax scales reproduce exactly), and the tick returns the
   engine's standard device-carried decode state (tok/lens/halted).

Window-limit nuance: a verify block writes KV for up to spec_k+1 positions
past the accepted length, so that headroom is reserved inside each
request's page window. Admission over-allocates pages to cover it, but a
request already at ``max_pages_per_seq`` cannot get extra pages — such
window-limited requests finish (reason "length") up to spec_k+1 tokens
earlier than the plain engine. Greedy bit-parity therefore holds for
requests at least spec_k+1 tokens clear of the window, i.e. everything the
window was sized for.

int8 nuance: within a tick the verify attends the current rounds' KV at
FULL precision (it lives in the dense cache before the tick-end
re-quantization), while the plain int8 engine reads every decode step
through int8. Spec output under ``kv_quant="int8"`` therefore differs from
the plain int8 engine within quantization noise — and is at least as close
to the unquantized model. Greedy bit-parity holds for the unquantized pool.

The host fetches ONE packed buffer per tick — ``[S, out_w + 3]`` rows of
``[echo, emitted_count, verify_count, tokens...]`` — preserving the
engine's one-fetch-per-tick cost model.

Cache discipline is inherited from speculative.py: both models write k/v at
absolute positions; entries beyond a row's accepted length are stale but
never attended (position-based causal masks) and are overwritten by later
rounds/ticks at the same offsets.
"""

from __future__ import annotations

from sentio_tpu.analysis.audit.registry import jit_family
from sentio_tpu.runtime.speculative import accept_and_correct


def build_spec_tick(target_fwd, cfg, draft_fwd, dcfg, eos_id: int,
                    ignore_eos: bool, page_size: int):
    """→ jitted ``spec_tick(params_t, params_d, tok, lens, halted,
    page_table, k_pages, v_pages, d_k, d_v, rng, temps, budgets, k=…,
    out_w=…)``; returns the 9-tuple ``(packed, tok', lens', halted',
    k_pages', v_pages', d_k', d_v', rng')`` where ``packed`` is
    ``[S, out_w + 3]``: column 0 echoes the input token, column 1 the
    emitted count, column 2 the verify (round) count, columns 3.. the
    emitted tokens."""
    import jax
    import jax.numpy as jnp

    from sentio_tpu.runtime.paged import dequantize_kv, scatter_prefill

    def densify(pages, table, dtype):
        if isinstance(pages, dict):
            dense = dequantize_kv(
                pages["q"][:, table], pages["s"][:, table], dtype
            )
        else:
            dense = pages[:, table]  # [L, S, NB, page, Hkv, Hd]
        lcount, s, nb, pg, hk, hd = dense.shape
        return dense.reshape(lcount, s, nb * pg, hk, hd)

    @jit_family("paged_spec.spec_tick", static_argnames=("k", "out_w"),
                donate_argnums=(6, 7, 8, 9))
    def spec_tick(params_t, params_d, tok, lens, halted, page_table,
                  k_pages, v_pages, d_k, d_v, rng, temps, budgets,
                  k, out_w):
        s_rows = tok.shape[0]
        tcache = {
            "k": densify(k_pages, page_table, cfg.jdtype),
            "v": densify(v_pages, page_table, cfg.jdtype),
        }
        dcache = {"k": d_k, "v": d_v}
        sampled_row = temps > 0.0
        inv_t = (1.0 / jnp.maximum(temps, 1e-6))[:, None]

        out0 = jnp.full((s_rows, out_w), eos_id, jnp.int32)
        emitted0 = jnp.zeros((s_rows,), jnp.int32)
        done0 = halted | (budgets <= 0)

        def round_body(state):
            (cur, lens, emitted, done, halted, tcache, dcache, out, rounds,
             rng_in) = state
            entry_done = done
            live = ~done[:, None]

            # ---- draft k+1 autoregressive steps (the last one only for its
            # k/v write — see speculative.py's draft_step rationale)
            def draft_step(carry, key):
                dtok, dlens, dcache = carry
                logits, dcache = draft_fwd(
                    params_d, dcfg, dtok[:, None], positions=dlens[:, None],
                    cache=dcache, cache_index=dlens, pad_mask=live,
                )
                last = logits[:, -1]
                qdist = jax.nn.softmax(
                    last.astype(jnp.float32) * inv_t, axis=-1
                )
                nxt = jnp.where(
                    sampled_row,
                    jax.random.categorical(key, last * inv_t, axis=-1),
                    jnp.argmax(last, axis=-1),
                ).astype(jnp.int32)
                return (nxt, dlens + 1, dcache), (nxt, qdist)

            rng_in, draft_rng, acc_rng = jax.random.split(rng_in, 3)
            (_, _, dcache), (drafts, qdists) = jax.lax.scan(
                draft_step, (cur, lens, dcache),
                jax.random.split(draft_rng, k + 1),
            )
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :k]   # [S, k]
            qdists = jnp.moveaxis(qdists, 0, 1)[:, :k]   # [S, k, V]

            # ---- one T=k+1 target verify over [cur, d1..dk]
            block = jnp.concatenate([cur[:, None], drafts], axis=1)
            pos = lens[:, None] + jnp.arange(k + 1)[None, :]
            t_logits, tcache = target_fwd(
                params_t, cfg, block, positions=pos, cache=tcache,
                cache_index=lens,
                pad_mask=jnp.broadcast_to(live, (s_rows, k + 1)),
            )

            j = jnp.arange(k + 1)[None, :]
            # greedy rule (bit-exact vs plain decode for temp-0 rows)
            targets = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            agree = drafts == targets[:, :k]
            n_acc_g = jnp.cumprod(agree.astype(jnp.int32), axis=1).sum(axis=1)
            corr_g = jnp.take_along_axis(targets, n_acc_g[:, None], axis=1)[:, 0]
            # rejection-sampling rule (marginally exact for sampled rows)
            tprobs = jax.nn.softmax(
                t_logits.astype(jnp.float32) * inv_t[..., None], axis=-1
            )
            n_acc_s, corr_s = accept_and_correct(acc_rng, drafts, qdists, tprobs)
            n_accept = jnp.where(sampled_row, n_acc_s, n_acc_g)
            correction = jnp.where(sampled_row, corr_s, corr_g)

            emit_n = n_accept + 1
            round_toks = jnp.where(
                j < n_accept[:, None], jnp.pad(drafts, ((0, 0), (0, 1))),
                jnp.where(j == n_accept[:, None], correction[:, None], eos_id),
            )
            # per-row tick budget FIRST: surplus verified tokens are
            # discarded (re-decoded next tick) — only a tick-boundary
            # effect. EOS is evaluated strictly INSIDE the capped window:
            # an EOS beyond the cap was never emitted, so it must neither
            # halt the row (it would hang forever un-folded) nor truncate.
            emit_n = jnp.minimum(emit_n, budgets - emitted)
            emit_n = jnp.where(done, 0, jnp.maximum(emit_n, 0))
            if not ignore_eos:
                eos_in = (round_toks == eos_id) & (j < emit_n[:, None])
                # positions up to and INCLUDING the first in-window EOS
                thru_eos = jnp.cumsum(jnp.cumsum(eos_in, axis=1), axis=1) <= 1
                emit_n = jnp.minimum(
                    emit_n, (thru_eos & (j < emit_n[:, None])).sum(axis=1)
                )
                halted = halted | (~done & eos_in.any(axis=1))

            def write_row(out_row, toks_row, off, n):
                upd = jax.lax.dynamic_update_slice(out_row, toks_row, (off,))
                keep = jnp.arange(out_row.shape[0])
                return jnp.where((keep >= off) & (keep < off + n), upd, out_row)

            out = jax.vmap(write_row)(out, round_toks, emitted, emit_n)
            new_cur = jnp.take_along_axis(
                round_toks, jnp.maximum(emit_n - 1, 0)[:, None], axis=1
            )[:, 0]
            cur = jnp.where(emit_n > 0, new_cur, cur)
            lens = lens + emit_n
            emitted = emitted + emit_n
            done = done | halted | (emitted >= budgets)
            # per-row verify count (rows live at round entry ran a verify) —
            # emitted/verifies is the tokens-per-verify ratio operators
            # tune the draft against
            rounds = rounds + (~entry_done).astype(jnp.int32)
            return (cur, lens, emitted, done, halted, tcache, dcache, out,
                    rounds, rng_in)

        def cond(state):
            return jnp.any(~state[3])

        rounds0 = jnp.zeros((s_rows,), jnp.int32)
        state = (tok, lens, emitted0, done0, halted, tcache, dcache, out0,
                 rounds0, rng)
        cur, lens, emitted, _, halted, tcache, dcache, out, rounds, rng = \
            jax.lax.while_loop(cond, round_body, state)

        k_pages, v_pages = scatter_prefill(
            k_pages, v_pages, tcache["k"], tcache["v"], page_table
        )
        # ONE host-fetchable buffer per tick: col 0 echoes the input token
        # (freshly admitted rows' deferred first tokens reach the host in
        # the same fetch, like the plain tick's packed row 0), col 1 is the
        # emitted count, col 2 the verify count, cols 3.. the emitted tokens
        packed = jnp.concatenate(
            [tok[:, None], emitted[:, None], rounds[:, None], out], axis=1
        )
        return (packed, cur, lens, halted,
                k_pages, v_pages, dcache["k"], dcache["v"], rng)

    return spec_tick
