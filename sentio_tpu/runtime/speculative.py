"""Speculative decoding: draft-and-verify generation, exact by construction.

Decode is bandwidth-bound — every step streams the full target weights for
one token per row. A small draft model proposes ``k`` tokens autoregressively
(cheap: draft weights are a fraction of the target's), then the target
scores all of them in ONE forward of T = k+1 (amortizing its weight stream
over up to k+1 emitted tokens). Two acceptance rules:

* **Greedy (temperature 0)** — keep the longest prefix where the target's
  own argmax agrees with the draft, then emit the target's correction
  token: bit-identical to target-only greedy decoding (asserted by tests).
* **Sampled (temperature > 0)** — rejection-sampling acceptance
  (:func:`accept_and_correct`): each emitted token's marginal equals
  sampling the target alone at that temperature (checked empirically).

Either way the draft only changes HOW FAST tokens appear, never the
output's law.

TPU-shaped implementation: the whole generate loop is one
``lax.while_loop`` on device — per round, an inner ``lax.scan`` drafts k
tokens, one batched target forward verifies, and ragged per-row acceptance
advances each row independently. The host dispatches once and fetches one
token buffer; no per-round round trips.

Cache discipline: both models write k/v at absolute positions; rejected
positions hold stale entries BEYOND each row's accepted length, which are
never attended (causal masks are position-based) and are overwritten by the
next round's writes at the same offsets. Rollback is therefore free — no
cache copying.

Reference seam: the reference's generator is a remote chat API
(/root/reference/src/core/llm/providers/openai.py:117) with no control over
decoding; speculative execution is only possible because the models live
in-process here.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sentio_tpu.analysis.audit.registry import jit_family


class SpeculativeError(Exception):
    pass


def accept_and_correct(rng, drafts, qdists, tprobs):
    """Rejection-sampling acceptance for sampled speculation.

    drafts [B, k] proposed tokens; qdists [B, k, V] the draft's sampling
    distributions; tprobs [B, k+1, V] the target's distributions at the
    verified positions. Accept d_j with probability min(1, p_t(d_j)/q(d_j))
    while the prefix holds; at the first rejection sample the correction
    from the residual ``norm(relu(p_t - q))``, and after a full accept
    sample the bonus token from the target's (k+1)-th distribution. The
    emitted marginal equals sampling from the target alone — the standard
    speculative-sampling guarantee (tested empirically in
    tests/test_speculative.py).

    Returns (n_accept [B], correction [B]).
    """
    import jax
    import jax.numpy as jnp

    b, k = drafts.shape
    rng_u, rng_c = jax.random.split(rng)
    u = jax.random.uniform(rng_u, (b, k))
    p_chosen = jnp.take_along_axis(tprobs[:, :k], drafts[..., None], axis=2)[..., 0]
    q_chosen = jnp.take_along_axis(qdists, drafts[..., None], axis=2)[..., 0]
    ratio = p_chosen / jnp.maximum(q_chosen, 1e-20)
    acc = u < jnp.minimum(ratio, 1.0)
    n_accept = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)

    # correction distribution at position j* = n_accept
    resid = jnp.maximum(tprobs[:, :k] - qdists, 0.0)          # [B, k, V]
    resid_full = jnp.concatenate([resid, tprobs[:, k:]], axis=1)
    sel = jnp.take_along_axis(
        resid_full, n_accept[:, None, None], axis=1
    )[:, 0]                                                    # [B, V]
    norm = sel.sum(-1, keepdims=True)
    tsel = jnp.take_along_axis(tprobs, n_accept[:, None, None], axis=1)[:, 0]
    # identical target/draft distributions → zero residual → target dist
    dist = jnp.where(norm > 1e-9, sel / jnp.maximum(norm, 1e-9), tsel)
    correction = jax.random.categorical(rng_c, jnp.log(dist + 1e-20), axis=-1)
    return n_accept, correction.astype(jnp.int32)


def build_spec_generate(target_fwd, target_cfg, draft_fwd, draft_cfg, eos_id: int,
                        attn_fn=None):
    """Compile the fused speculative generate: (params_t, params_d, ids,
    positions, lens, tcache, dcache, steps, k) → (out [B, steps+k+1],
    n_rounds) — all device side.

    ``steps`` bounds emitted tokens per row; each while-loop round emits
    between 1 and k+1 tokens per live row.
    """
    import jax
    import jax.numpy as jnp

    @jit_family("speculative.spec_generate",
                static_argnames=("steps", "k", "sampled"))
    def spec_generate(params_t, params_d, ids, positions, lens, tcache, dcache,
                      steps, k, pad_mask, rng, temperature, sampled=False):
        b, width = ids.shape
        row_valid = pad_mask.any(axis=1, keepdims=True)  # junk bucket rows
        inv_t = 1.0 / jnp.maximum(temperature, 1e-6)

        # prefill both models over the prompt (one dispatch each, fused
        # here); pad_mask keeps padding out of routed-expert capacity and
        # attn_fn keeps prefill numerics identical to the engine's own
        # prefill (kernel-vs-XLA float differences can flip argmax ties)
        t_logits, tcache = target_fwd(
            params_t, target_cfg, ids, positions=positions, cache=tcache,
            cache_index=0, pad_mask=pad_mask, attn_fn=attn_fn,
        )
        _, dcache = draft_fwd(
            params_d, draft_cfg, ids, positions=positions, cache=dcache,
            cache_index=0, pad_mask=pad_mask, attn_fn=attn_fn,
        )
        last = jnp.take_along_axis(t_logits, (lens - 1)[:, None, None], axis=1)[:, 0]
        if sampled:
            rng, sub = jax.random.split(rng)
            cur = jax.random.categorical(sub, last * inv_t, axis=-1).astype(jnp.int32)
        else:
            cur = jnp.argmax(last, axis=-1).astype(jnp.int32)  # target greedy

        out_w = steps + k + 1
        out0 = jnp.full((b, out_w), eos_id, jnp.int32)
        # emitted[b] counts tokens written for row b; cur sits at cache
        # position lens[b] and is already "emitted" conceptually at offset 0
        out0 = out0.at[:, 0].set(cur)
        emitted0 = jnp.ones((b,), jnp.int32)
        # junk bucket rows start done — otherwise the loop keeps burning
        # full draft+verify rounds on padding until it exhausts the budget
        done0 = (cur == eos_id) | ~row_valid[:, 0]

        def round_body(state):
            cur, lens, emitted, done, tcache, dcache, out, rounds, rng_in = state
            live = row_valid & ~done[:, None]

            # ---- draft autoregressively (T=1 scan over the draft). k+1
            # steps, not k: the last step's PROPOSAL is discarded, but its
            # input is d_k, whose k/v write at slot lens+k is needed when a
            # fully-accepted round advances lens past it — without it the
            # draft cache keeps a permanently-unwritten, attended slot and
            # acceptance decays exactly when the draft is good.
            def draft_step(carry, key):
                tok, dlens, dcache = carry
                logits, dcache = draft_fwd(
                    params_d, draft_cfg, tok[:, None], positions=dlens[:, None],
                    cache=dcache, cache_index=dlens, pad_mask=live,
                )
                if sampled:
                    qdist = jax.nn.softmax(
                        logits[:, -1].astype(jnp.float32) * inv_t, axis=-1
                    )
                    nxt = jax.random.categorical(
                        key, logits[:, -1] * inv_t, axis=-1
                    ).astype(jnp.int32)
                else:
                    qdist = jnp.zeros((b, 1), jnp.float32)  # unused
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (nxt, dlens + 1, dcache), (nxt, qdist)

            rng, draft_rng = jax.random.split(rng_in)
            (_, _, dcache), (drafts, qdists) = jax.lax.scan(
                draft_step, (cur, lens, dcache),
                jax.random.split(draft_rng, k + 1),
            )
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :k]   # [B, k]
            qdists = jnp.moveaxis(qdists, 0, 1)[:, :k]   # [B, k, V]

            # ---- target verifies cur + drafts in one T=k+1 forward
            block = jnp.concatenate([cur[:, None], drafts], axis=1)  # [B, k+1]
            pos = lens[:, None] + jnp.arange(k + 1)[None, :]
            t_logits, tcache = target_fwd(
                params_t, target_cfg, block, positions=pos, cache=tcache,
                cache_index=lens,
                pad_mask=jnp.broadcast_to(live, (b, k + 1)),
            )

            j = jnp.arange(k + 1)[None, :]
            if sampled:
                # ---- rejection-sampling acceptance: emitted marginal equals
                # sampling the target alone (accept_and_correct docstring)
                tprobs = jax.nn.softmax(
                    t_logits.astype(jnp.float32) * inv_t, axis=-1
                )
                rng, acc_rng = jax.random.split(rng)
                n_accept, corr_tok = accept_and_correct(
                    acc_rng, drafts, qdists, tprobs
                )
                correction = corr_tok[:, None]
            else:
                # ---- greedy: longest prefix where the draft equals the
                # target's own argmax (the choice AFTER cur, d1..dj-1)
                targets = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
                agree = drafts == targets[:, :k]                   # [B, k]
                n_accept = jnp.cumprod(agree.astype(jnp.int32), axis=1).sum(axis=1)
                correction = jnp.take_along_axis(targets, n_accept[:, None], axis=1)
            # tokens emitted this round per live row: accepted drafts plus
            # the correction/bonus token
            emit_n = n_accept + 1                                   # [B] in 1..k+1

            # round tokens [B, k+1]: d1..dm, correction, padding after
            round_toks = jnp.where(
                j < n_accept[:, None], jnp.pad(drafts, ((0, 0), (0, 1))),
                jnp.where(j == n_accept[:, None], correction, eos_id),
            )

            # EOS inside the accepted run truncates emission for that row
            is_eos = round_toks == eos_id
            before_eos = jnp.cumsum(jnp.cumsum(is_eos, axis=1), axis=1) <= 1
            emit_n = jnp.minimum(emit_n, before_eos.sum(axis=1))
            hit_eos = (jnp.cumsum(is_eos, axis=1) > 0) & (j < emit_n[:, None])
            row_done = done | hit_eos.any(axis=1)

            emit_n = jnp.where(done, 0, emit_n)

            # ---- scatter this round's tokens at each row's offset
            def write_row(out_row, toks_row, off, n):
                upd = jax.lax.dynamic_update_slice(out_row, toks_row, (off,))
                keep = jnp.arange(out_row.shape[0])
                return jnp.where(
                    (keep >= off) & (keep < off + n), upd, out_row
                )

            out = jax.vmap(write_row)(out, round_toks, emitted, emit_n)

            cur = jnp.where(done, cur, correction[:, 0])
            lens = lens + emit_n
            emitted = emitted + emit_n
            # a row retires when it hits EOS or exhausts its own budget —
            # otherwise fast rows would keep speculating garbage (and
            # growing lens) while slow rows finish
            row_done = row_done | (emitted >= steps)
            return (cur, lens, emitted, row_done, tcache, dcache, out, rounds + 1, rng)

        def cond(state):
            done = state[3]
            return jnp.any(~done)

        state = (cur, lens, emitted0, done0, tcache, dcache, out0,
                 jnp.zeros((), jnp.int32), rng)
        _, _, emitted, _, _, _, out, rounds, _ = jax.lax.while_loop(
            cond, round_body, state
        )
        return out, emitted, rounds

    return spec_generate


class SpeculativeDecoder:
    """Draft-model wrapper for a GeneratorEngine-style target.

    Temperature 0: greedy-exact — ``generate`` emits the same tokens as the
    target engine's plain greedy decode. Temperature > 0: distribution-
    exact — rejection-sampling acceptance makes each emitted token's
    marginal equal to sampling the target alone. Either way the ``k``
    drafted tokens per round only reduce the number of target weight
    streams per token. Exposes acceptance stats so operators can judge
    whether their draft earns its keep.
    """

    def __init__(self, engine, draft_params, draft_config, k: int = 4,
                 draft_fwd=None) -> None:
        if draft_config.vocab_size != engine.model_config.vocab_size:
            raise SpeculativeError(
                f"draft vocab {draft_config.vocab_size} != target "
                f"{engine.model_config.vocab_size} — same tokenizer required"
            )
        if k < 1:
            raise SpeculativeError(f"k must be >= 1, got {k}")
        if engine.mesh is not None:
            # the spec caches would need the engine's mesh shardings and the
            # verify forward its shard_map attention — not wired yet; fail
            # loudly instead of silently decoding off-mesh
            raise SpeculativeError("mesh-backed engines are not supported yet")
        from sentio_tpu.models.llama import llama_forward
        from sentio_tpu.models.moe import MoeConfig, moe_serving_forward

        if isinstance(engine.model_config, MoeConfig):
            # exactness needs routing to be batch-size-independent: the
            # verify forward routes B*(k+1) tokens where plain decode routes
            # B, so ANY capacity drop can differ between the paths. cf >=
            # E/k_experts guarantees no token ever drops (worst case all
            # tokens pick one expert).
            cfg = engine.model_config
            no_drop_cf = cfg.n_experts / cfg.experts_per_token
            if cfg.capacity_factor < no_drop_cf:
                raise SpeculativeError(
                    f"MoE target needs capacity_factor >= {no_drop_cf:.1f} "
                    f"(n_experts/experts_per_token) for greedy-exact "
                    f"speculation; got {cfg.capacity_factor}"
                )
        if draft_fwd is None:
            draft_fwd = (
                moe_serving_forward
                if isinstance(draft_config, MoeConfig) else llama_forward
            )

        self.engine = engine
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.k = int(k)
        self.stats = {"rounds": 0, "tokens": 0}
        self._fn = build_spec_generate(
            engine.forward_fn, engine.model_config,
            draft_fwd, draft_config,
            engine.tokenizer.eos_id,
            attn_fn=engine._attn_fn,
        )

    def generate(self, prompts, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0):
        """Batched generation through the speculative loop.

        ``temperature == 0``: greedy acceptance — bit-identical to
        ``engine.generate(temperature=0)``. ``temperature > 0``: rejection-
        sampling acceptance — each emitted token is distributed exactly as
        sampling the target alone at that temperature (the draft changes
        speed, not the distribution)."""
        import time as _time

        import jax.numpy as jnp

        from sentio_tpu.models.llama import init_cache
        from sentio_tpu.runtime.engine import GenerationResult

        eng = self.engine
        t0 = _time.perf_counter()
        requested = max_new_tokens or eng.config.max_new_tokens
        max_new = requested
        ids, positions, lens, tcache, n, window, pad_mask = eng._encode_batch(
            prompts, max_new + self.k + 1
        )
        headroom = window - int(lens.max())
        plain_steps = eng._stable_steps(max_new, headroom)
        spec_steps = eng._stable_steps(max_new, max(headroom - self.k - 1, 1))
        if spec_steps < plain_steps:
            # near-window prompts: the verify block's k+1 spill would force
            # a shorter budget than the plain path — fall back so the spec
            # seam never returns fewer tokens than engine.generate would
            return eng.generate(
                prompts, max_new_tokens=max_new, temperature=temperature
            )
        max_new = spec_steps
        dcache = init_cache(self.draft_config, ids.shape[0], window)

        import jax

        if temperature > 0.0:
            eng._rng, sub = jax.random.split(eng._rng)
        else:
            # greedy never samples — keep the engine's RNG stream untouched
            sub = jax.random.PRNGKey(0)
        out, emitted, rounds = self._fn(
            eng.params, self.draft_params, ids, positions, jnp.asarray(lens),
            tcache, dcache, max_new, self.k, jnp.asarray(pad_mask),
            sub, jnp.asarray(temperature, jnp.float32),
            sampled=temperature > 0.0,
        )
        out = np.asarray(out)
        emitted = np.asarray(emitted)
        self.stats["rounds"] += int(rounds)
        self.stats["tokens"] += int(emitted[:n].sum())

        results = []
        eos = eng.tokenizer.eos_id
        for i in range(n):
            # max_new rounds UP to a step bucket (_stable_steps); the tail
            # past the caller's budget is dropped, same as engine.generate
            row = out[i, : min(int(emitted[i]), max_new, requested)].tolist()
            if eos in row:
                row, reason = row[: row.index(eos)], "stop"
            else:
                reason = "length"
            results.append(
                GenerationResult(
                    text=eng.tokenizer.decode(row), tokens=row,
                    prompt_tokens=int(lens[i]), finish_reason=reason,
                    latency_ms=(_time.perf_counter() - t0) * 1000.0,
                )
            )
        return results

    @property
    def tokens_per_round(self) -> float:
        """Mean emitted tokens per target verify — 1.0 means the draft never
        helps; k+1 is the ceiling."""
        return self.stats["tokens"] / max(self.stats["rounds"], 1)
