"""Speculative decoding: draft-and-verify generation, exact under greedy.

Decode is bandwidth-bound — every step streams the full target weights for
one token per row. A small draft model proposes ``k`` tokens autoregressively
(cheap: draft weights are a fraction of the target's), then the target
scores all of them in ONE forward of T = k+1 (amortizing its weight stream
over up to k+1 emitted tokens). Greedy acceptance keeps the longest prefix
where the target's own argmax agrees with the draft, then emits the
target's correction token — so the emitted sequence is bit-identical to
target-only greedy decoding; the draft only changes HOW FAST tokens appear,
never WHICH tokens (asserted by tests).

TPU-shaped implementation: the whole generate loop is one
``lax.while_loop`` on device — per round, an inner ``lax.scan`` drafts k
tokens, one batched target forward verifies, and ragged per-row acceptance
advances each row independently. The host dispatches once and fetches one
token buffer; no per-round round trips.

Cache discipline: both models write k/v at absolute positions; rejected
positions hold stale entries BEYOND each row's accepted length, which are
never attended (causal masks are position-based) and are overwritten by the
next round's writes at the same offsets. Rollback is therefore free — no
cache copying.

Reference seam: the reference's generator is a remote chat API
(/root/reference/src/core/llm/providers/openai.py:117) with no control over
decoding; speculative execution is only possible because the models live
in-process here.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np


class SpeculativeError(Exception):
    pass


def build_spec_generate(target_fwd, target_cfg, draft_fwd, draft_cfg, eos_id: int,
                        attn_fn=None):
    """Compile the fused speculative generate: (params_t, params_d, ids,
    positions, lens, tcache, dcache, steps, k) → (out [B, steps+k+1],
    n_rounds) — all device side.

    ``steps`` bounds emitted tokens per row; each while-loop round emits
    between 1 and k+1 tokens per live row.
    """
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnames=("steps", "k"))
    def spec_generate(params_t, params_d, ids, positions, lens, tcache, dcache,
                      steps, k, pad_mask):
        b, width = ids.shape
        row_valid = pad_mask.any(axis=1, keepdims=True)  # junk bucket rows

        # prefill both models over the prompt (one dispatch each, fused
        # here); pad_mask keeps padding out of routed-expert capacity and
        # attn_fn keeps prefill numerics identical to the engine's own
        # prefill (kernel-vs-XLA float differences can flip argmax ties)
        t_logits, tcache = target_fwd(
            params_t, target_cfg, ids, positions=positions, cache=tcache,
            cache_index=0, pad_mask=pad_mask, attn_fn=attn_fn,
        )
        _, dcache = draft_fwd(
            params_d, draft_cfg, ids, positions=positions, cache=dcache,
            cache_index=0, pad_mask=pad_mask, attn_fn=attn_fn,
        )
        last = jnp.take_along_axis(t_logits, (lens - 1)[:, None, None], axis=1)[:, 0]
        cur = jnp.argmax(last, axis=-1).astype(jnp.int32)  # first token, target greedy

        out_w = steps + k + 1
        out0 = jnp.full((b, out_w), eos_id, jnp.int32)
        # emitted[b] counts tokens written for row b; cur sits at cache
        # position lens[b] and is already "emitted" conceptually at offset 0
        out0 = out0.at[:, 0].set(cur)
        emitted0 = jnp.ones((b,), jnp.int32)
        # junk bucket rows start done — otherwise the loop keeps burning
        # full draft+verify rounds on padding until it exhausts the budget
        done0 = (cur == eos_id) | ~row_valid[:, 0]

        def round_body(state):
            cur, lens, emitted, done, tcache, dcache, out, rounds = state
            live = row_valid & ~done[:, None]

            # ---- draft autoregressively (T=1 scan over the draft). k+1
            # steps, not k: the last step's PROPOSAL is discarded, but its
            # input is d_k, whose k/v write at slot lens+k is needed when a
            # fully-accepted round advances lens past it — without it the
            # draft cache keeps a permanently-unwritten, attended slot and
            # acceptance decays exactly when the draft is good.
            def draft_step(carry, _):
                tok, dlens, dcache = carry
                logits, dcache = draft_fwd(
                    params_d, draft_cfg, tok[:, None], positions=dlens[:, None],
                    cache=dcache, cache_index=dlens, pad_mask=live,
                )
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (nxt, dlens + 1, dcache), nxt

            (_, _, dcache), drafts = jax.lax.scan(
                draft_step, (cur, lens, dcache), None, length=k + 1
            )
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :k]  # [B, k]

            # ---- target verifies cur + drafts in one T=k+1 forward
            block = jnp.concatenate([cur[:, None], drafts], axis=1)  # [B, k+1]
            pos = lens[:, None] + jnp.arange(k + 1)[None, :]
            t_logits, tcache = target_fwd(
                params_t, target_cfg, block, positions=pos, cache=tcache,
                cache_index=lens,
                pad_mask=jnp.broadcast_to(live, (b, k + 1)),
            )
            targets = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [B, k+1]

            # ---- longest agreeing prefix: accept drafts[j] while it equals
            # targets[j] (the target's choice AFTER cur, d1..dj-1)
            agree = drafts == targets[:, :k]                       # [B, k]
            n_accept = jnp.cumprod(agree.astype(jnp.int32), axis=1).sum(axis=1)
            # tokens emitted this round per live row: accepted drafts plus
            # the target's correction/bonus token
            emit_n = n_accept + 1                                   # [B] in 1..k+1

            # round tokens [B, k+1]: d1..dm, t_{m+1}, padding after
            j = jnp.arange(k + 1)[None, :]
            correction = jnp.take_along_axis(targets, n_accept[:, None], axis=1)
            round_toks = jnp.where(
                j < n_accept[:, None], jnp.pad(drafts, ((0, 0), (0, 1))),
                jnp.where(j == n_accept[:, None], correction, eos_id),
            )

            # EOS inside the accepted run truncates emission for that row
            is_eos = round_toks == eos_id
            before_eos = jnp.cumsum(jnp.cumsum(is_eos, axis=1), axis=1) <= 1
            emit_n = jnp.minimum(emit_n, before_eos.sum(axis=1))
            hit_eos = (jnp.cumsum(is_eos, axis=1) > 0) & (j < emit_n[:, None])
            row_done = done | hit_eos.any(axis=1)

            emit_n = jnp.where(done, 0, emit_n)

            # ---- scatter this round's tokens at each row's offset
            def write_row(out_row, toks_row, off, n):
                upd = jax.lax.dynamic_update_slice(out_row, toks_row, (off,))
                keep = jnp.arange(out_row.shape[0])
                return jnp.where(
                    (keep >= off) & (keep < off + n), upd, out_row
                )

            out = jax.vmap(write_row)(out, round_toks, emitted, emit_n)

            cur = jnp.where(done, cur, correction[:, 0])
            lens = lens + emit_n
            emitted = emitted + emit_n
            # a row retires when it hits EOS or exhausts its own budget —
            # otherwise fast rows would keep speculating garbage (and
            # growing lens) while slow rows finish
            row_done = row_done | (emitted >= steps)
            return (cur, lens, emitted, row_done, tcache, dcache, out, rounds + 1)

        def cond(state):
            _, _, _, done, _, _, _, _ = state
            return jnp.any(~done)

        state = (cur, lens, emitted0, done0, tcache, dcache, out0, jnp.zeros((), jnp.int32))
        _, _, emitted, _, _, _, out, rounds = jax.lax.while_loop(
            cond, round_body, state
        )
        return out, emitted, rounds

    return spec_generate


class SpeculativeDecoder:
    """Draft-model wrapper for a GeneratorEngine-style target.

    Greedy-exact: ``generate`` emits the same tokens as the target engine's
    plain greedy decode; the ``k`` drafted tokens per round only reduce the
    number of target weight streams per token. Exposes acceptance stats so
    operators can judge whether their draft earns its keep.
    """

    def __init__(self, engine, draft_params, draft_config, k: int = 4,
                 draft_fwd=None) -> None:
        if draft_config.vocab_size != engine.model_config.vocab_size:
            raise SpeculativeError(
                f"draft vocab {draft_config.vocab_size} != target "
                f"{engine.model_config.vocab_size} — same tokenizer required"
            )
        if k < 1:
            raise SpeculativeError(f"k must be >= 1, got {k}")
        if engine.mesh is not None:
            # the spec caches would need the engine's mesh shardings and the
            # verify forward its shard_map attention — not wired yet; fail
            # loudly instead of silently decoding off-mesh
            raise SpeculativeError("mesh-backed engines are not supported yet")
        from sentio_tpu.models.llama import llama_forward
        from sentio_tpu.models.moe import MoeConfig, moe_serving_forward

        if isinstance(engine.model_config, MoeConfig):
            # exactness needs routing to be batch-size-independent: the
            # verify forward routes B*(k+1) tokens where plain decode routes
            # B, so ANY capacity drop can differ between the paths. cf >=
            # E/k_experts guarantees no token ever drops (worst case all
            # tokens pick one expert).
            cfg = engine.model_config
            no_drop_cf = cfg.n_experts / cfg.experts_per_token
            if cfg.capacity_factor < no_drop_cf:
                raise SpeculativeError(
                    f"MoE target needs capacity_factor >= {no_drop_cf:.1f} "
                    f"(n_experts/experts_per_token) for greedy-exact "
                    f"speculation; got {cfg.capacity_factor}"
                )
        if draft_fwd is None:
            draft_fwd = (
                moe_serving_forward
                if isinstance(draft_config, MoeConfig) else llama_forward
            )

        self.engine = engine
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.k = int(k)
        self.stats = {"rounds": 0, "tokens": 0}
        self._fn = build_spec_generate(
            engine.forward_fn, engine.model_config,
            draft_fwd, draft_config,
            engine.tokenizer.eos_id,
            attn_fn=engine._attn_fn,
        )

    def generate(self, prompts, max_new_tokens: Optional[int] = None):
        """Batched greedy generation through the speculative loop. Returns
        the same GenerationResult list as ``engine.generate(temperature=0)``."""
        import time as _time

        import jax.numpy as jnp

        from sentio_tpu.models.llama import init_cache
        from sentio_tpu.runtime.engine import GenerationResult

        eng = self.engine
        t0 = _time.perf_counter()
        max_new = max_new_tokens or eng.config.max_new_tokens
        ids, positions, lens, tcache, n, window, pad_mask = eng._encode_batch(
            prompts, max_new + self.k + 1
        )
        headroom = window - int(lens.max())
        plain_steps = eng._stable_steps(max_new, headroom)
        spec_steps = eng._stable_steps(max_new, max(headroom - self.k - 1, 1))
        if spec_steps < plain_steps:
            # near-window prompts: the verify block's k+1 spill would force
            # a shorter budget than the plain path — fall back so the spec
            # seam never returns fewer tokens than engine.generate would
            return eng.generate(prompts, max_new_tokens=max_new, temperature=0.0)
        max_new = spec_steps
        dcache = init_cache(self.draft_config, ids.shape[0], window)

        out, emitted, rounds = self._fn(
            eng.params, self.draft_params, ids, positions, jnp.asarray(lens),
            tcache, dcache, max_new, self.k, jnp.asarray(pad_mask),
        )
        out = np.asarray(out)
        emitted = np.asarray(emitted)
        self.stats["rounds"] += int(rounds)
        self.stats["tokens"] += int(emitted[:n].sum())

        results = []
        eos = eng.tokenizer.eos_id
        for i in range(n):
            row = out[i, : min(int(emitted[i]), max_new)].tolist()
            if eos in row:
                row, reason = row[: row.index(eos)], "stop"
            else:
                reason = "length"
            results.append(
                GenerationResult(
                    text=eng.tokenizer.decode(row), tokens=row,
                    prompt_tokens=int(lens[i]), finish_reason=reason,
                    latency_ms=(_time.perf_counter() - t0) * 1000.0,
                )
            )
        return results

    @property
    def tokens_per_round(self) -> float:
        """Mean emitted tokens per target verify — 1.0 means the draft never
        helps; k+1 is the ceiling."""
        return self.stats["tokens"] / max(self.stats["rounds"], 1)
