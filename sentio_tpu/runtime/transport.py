"""Worker RPC transports: the framing layer under the replica worker tier.

PR 13's worker protocol (runtime/worker.py) serialized every frame over the
``multiprocessing.Pipe`` the spawn start method hands out — correct, but
single-host by construction, and blind to the fault class that dominates
real multi-host serving: the network. This module extracts that framing
into a transport seam with two implementations:

* :class:`PipeTransport` — the existing spawn-pipe path, behavior-identical
  (``REPLICA_MODE=process`` keeps using it). Liveness is the OS's problem:
  a dead peer is a broken pipe / EOF, immediately.
* :class:`SocketTransport` — length-prefixed pickle frames over TCP
  (``REPLICA_MODE=socket`` and ``REPLICA_WORKERS=host:port,...``). The
  network adds the failure modes the pipe never had — partitions where
  neither side errors, half-open links where one direction still works,
  slow links, peers that stop reading — so every frame carries a validated
  header (magic, protocol version, **incarnation epoch**, length) and every
  blocking step carries a deadline:

  - a *partial* frame must complete within ``frame_timeout_s`` — a reader
    can never hang mid-frame on a stalled link (it raises
    :class:`TransportClosed` instead);
  - a send that cannot make progress within ``frame_timeout_s`` (the peer
    stopped reading and the kernel buffer filled — bounded buffering)
    raises :class:`TransportClosed`: the **broken-write** liveness signal;
  - an oversized frame raises :class:`FrameTooLarge` on BOTH sides (the
    sender refuses to emit it; the receiver refuses to buffer it);
  - a corrupt header, wrong protocol version, or undecodable payload
    raises :class:`FrameProtocolError` — the connection is dropped rather
    than resynchronized (a byte stream that lied once cannot be trusted
    about frame boundaries again).

The **epoch** in the header is the worker-registry incarnation stamp
(runtime/replica.py ``WorkerRegistry``): the router assigns a
monotonically-increasing epoch per replica slot at every (re)registration,
and the receive path surfaces each frame's epoch so the dispatcher can drop
frames from a previous incarnation — a worker that vanished behind a
partition and later reconnected can never resurrect dead tickets or
double-deliver stream chunks, because everything it sent before the
partition carries a stale epoch.

Handshake (versioned, authenticated): the connecting side's FIRST frame is
``(0, "hello", {token, slot, proto, pid})``; the accepting side validates
the shared token (constant-time compare) and protocol version, answers
``(0, "hello_ack", {epoch})`` — or ``(0, "hello_reject", {reason})`` and
drops the connection. Workers dial the router's registry listener
(self-registration / reconnection); the router dials advertised
``REPLICA_WORKERS`` listeners (``worker_serve`` in runtime/worker.py), in
which case the hello direction reverses but the frame shapes are the same.

Fault surface: the socket paths check ``infra.faults`` frame points —
``transport.recv`` / ``transport.send`` plus the per-peer scoped variants
``transport.recv.<scope>`` / ``transport.send.<scope>`` (router side:
``r<slot>``; worker side: ``worker``) — via :func:`faults.hit_frame`, so
chaos drills can drop the next N frames, delay frames, or arm the
half-open partition (reads stall while writes succeed) on either side.
"""

from __future__ import annotations

import hmac
import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Optional

from sentio_tpu.infra import faults

__all__ = [
    "PROTOCOL_VERSION",
    "TransportError",
    "TransportClosed",
    "FrameTooLarge",
    "FrameProtocolError",
    "PipeTransport",
    "SocketTransport",
    "ClockSync",
    "send_hello",
    "expect_hello",
    "dial",
    "DEFAULT_MAX_FRAME_BYTES",
    "DEFAULT_FRAME_TIMEOUT_S",
]

PROTOCOL_VERSION = 1

# frame header: magic | version | incarnation epoch | payload length
_MAGIC = b"SNTP"
_HEADER = struct.Struct("!4sBII")

DEFAULT_MAX_FRAME_BYTES = 32 * 1024 * 1024
DEFAULT_FRAME_TIMEOUT_S = 30.0

# fixed socket timeout: every blocking socket op wakes at this cadence to
# re-check its own deadline (set ONCE at construction — mutating the shared
# socket timeout from concurrent send/recv threads would race)
_POLL_S = 0.2


class TransportError(RuntimeError):
    """Base for transport-layer failures. Deliberately NOT a SentioError:
    these never cross the wire — the worker shim (runtime/worker.py) maps
    them to the typed ReplicaUnavailable surface callers already handle."""


class TransportClosed(TransportError):
    """The peer is gone or the link is unusable: EOF, broken pipe, reset,
    a mid-frame read that starved past its deadline, or a write the peer
    stopped draining. Terminal for the connection."""


class FrameTooLarge(TransportError):
    """A frame exceeded ``max_frame_bytes`` — refused on the sending side
    before any byte is written, and on the receiving side before any
    payload is buffered (a hostile or broken peer cannot balloon router
    memory). Terminal for the connection on the receive side (the bytes
    are already in flight and cannot be skipped trustworthily)."""


class FrameProtocolError(TransportError):
    """Bad magic, unsupported protocol version, or an undecodable payload.
    The connection is dropped: framing integrity is gone."""


class PipeTransport:
    """The spawn-pipe framing PR 13 shipped, behind the transport seam.
    Pickle round-trips are the Connection's own; epochs are fixed (no
    registry churn can happen on a pipe — the pipe IS the process)."""

    def __init__(self, conn, epoch: int = 0) -> None:
        self._conn = conn
        self.epoch = epoch
        # Connection.send is not thread-safe (a >16KB frame goes out as
        # separate header+body writes, and partial writes loop): concurrent
        # sender threads would interleave bytes and desync the pipe, making
        # a healthy peer look dead
        self._send_lock = threading.Lock()

    def send(self, frame: tuple) -> None:
        try:
            with self._send_lock:
                self._conn.send(frame)
        except (BrokenPipeError, EOFError, OSError) as exc:
            raise TransportClosed(f"pipe send failed: {exc}") from exc

    def recv(self, timeout_s: Optional[float] = None):
        """→ ``(frame, epoch)``, or ``None`` when ``timeout_s`` elapses
        with no frame available (the caller's poll tick)."""
        try:
            if timeout_s is not None and not self._conn.poll(timeout_s):
                return None
            frame = self._conn.recv()
        except (EOFError, BrokenPipeError, OSError) as exc:
            raise TransportClosed(f"pipe closed: {exc}") from exc
        except pickle.UnpicklingError as exc:
            raise FrameProtocolError(f"undecodable pipe frame: {exc}") from exc
        return frame, self.epoch

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass

    def fileno(self) -> int:
        return self._conn.fileno()


class SocketTransport:
    """Length-prefixed pickle frames over one TCP connection.

    Threading: many senders (``_send_lock`` serializes writes — a frame
    interleaved with another's bytes desyncs the stream), ONE receiver
    (the dispatcher thread; the recv path keeps partial-frame state and is
    not reentrant)."""

    def __init__(
        self,
        sock: socket.socket,
        epoch: int = 0,
        max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
        frame_timeout_s: float = DEFAULT_FRAME_TIMEOUT_S,
        fault_scope: str = "",
    ) -> None:
        self._sock = sock
        self.epoch = epoch
        self.max_frame_bytes = int(max_frame_bytes)
        self.frame_timeout_s = float(frame_timeout_s)
        self.fault_scope = fault_scope
        self._send_lock = threading.Lock()
        self._closed = threading.Event()
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # e.g. a unix socketpair in tests
        sock.settimeout(_POLL_S)

    # ------------------------------------------------------------- internals

    def _fault_points(self, op: str) -> tuple:
        if self.fault_scope:
            return (f"transport.{op}", f"transport.{op}.{self.fault_scope}")
        return (f"transport.{op}",)

    def _hit(self, op: str) -> bool:
        """True when an armed network-fault rule says to DROP this frame;
        stalls/delays/errors fire inside (half-open partitions arm a stall
        at the recv point — reads wedge while the send path stays live)."""
        drop = False
        for point in self._fault_points(op):
            drop = faults.hit_frame(point) or drop
        return drop

    def _send_bytes(self, data: bytes) -> None:
        """Write all of ``data``, bounded by PROGRESS: the deadline resets
        every time bytes move, so a slow-but-draining peer is fine and
        only a peer that stopped reading entirely (kernel buffer full, no
        progress for a whole frame timeout) breaks the write typed."""
        view = memoryview(data)
        deadline = time.perf_counter() + self.frame_timeout_s
        while view:
            if self._closed.is_set():
                raise TransportClosed("transport closed during send")
            try:
                n = self._sock.send(view)
            except socket.timeout:
                if time.perf_counter() > deadline:
                    # bounded buffering: the peer stopped reading and the
                    # kernel buffer filled — the broken-write death signal
                    raise TransportClosed(
                        f"send made no progress for {self.frame_timeout_s:.0f}s "
                        "(peer not reading)"
                    ) from None
                continue
            except OSError as exc:
                raise TransportClosed(f"send failed: {exc}") from exc
            if n == 0:
                raise TransportClosed("send returned 0 bytes")
            view = view[n:]
            deadline = time.perf_counter() + self.frame_timeout_s

    def _recv_exact(self, n: int, deadline: Optional[float],
                    idle_timeout_s: Optional[float]):
        """Read exactly ``n`` bytes. With ``deadline=None`` the FIRST byte
        may wait up to ``idle_timeout_s`` (None = forever) and returns
        ``None`` on idle expiry; once any byte has arrived, the remainder
        must land before the (started) frame deadline."""
        chunks: list[bytes] = []
        got = 0
        idle_start = time.perf_counter()
        while got < n:
            if self._closed.is_set():
                raise TransportClosed("transport closed during recv")
            try:
                chunk = self._sock.recv(n - got)  # lint: allow(socket-no-timeout) — vetted: fixed settimeout(_POLL_S) at construction + explicit frame deadlines here
            except socket.timeout:
                now = time.perf_counter()
                if got == 0 and deadline is None:
                    if (idle_timeout_s is not None
                            and now - idle_start >= idle_timeout_s):
                        return None
                    continue
                if deadline is None:
                    deadline = idle_start + self.frame_timeout_s
                if now > deadline:
                    raise TransportClosed(
                        f"partial frame stalled past {self.frame_timeout_s:.0f}s"
                    ) from None
                continue
            except OSError as exc:
                raise TransportClosed(f"recv failed: {exc}") from exc
            if not chunk:
                raise TransportClosed("peer closed the connection")
            if got == 0 and deadline is None:
                # first byte of a frame: the rest must complete in time
                deadline = time.perf_counter() + self.frame_timeout_s
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    # --------------------------------------------------------------- surface

    def send(self, frame: tuple) -> None:
        payload = pickle.dumps(frame, protocol=pickle.HIGHEST_PROTOCOL)
        if len(payload) > self.max_frame_bytes:
            raise FrameTooLarge(
                f"frame of {len(payload)} bytes exceeds the "
                f"{self.max_frame_bytes}-byte cap"
            )
        if self._hit("send"):
            return  # injected network fault: this frame is dropped on the wire
        header = _HEADER.pack(_MAGIC, PROTOCOL_VERSION,
                              self.epoch & 0xFFFFFFFF, len(payload))
        # the progress deadline starts INSIDE the lock: time spent queued
        # behind another sender must not count against this frame
        with self._send_lock:
            self._send_bytes(header + payload)

    def recv(self, timeout_s: Optional[float] = None):
        """→ ``(frame, epoch)``; ``None`` when ``timeout_s`` elapses before
        any frame STARTS (a started frame always completes or raises)."""
        while True:
            header = self._recv_exact(_HEADER.size, None, timeout_s)
            if header is None:
                return None
            magic, version, epoch, length = _HEADER.unpack(header)
            if magic != _MAGIC:
                raise FrameProtocolError(
                    f"bad frame magic {magic!r} — peer is not speaking this "
                    "protocol"
                )
            if version != PROTOCOL_VERSION:
                raise FrameProtocolError(
                    f"peer speaks protocol v{version}, this side v"
                    f"{PROTOCOL_VERSION}"
                )
            if length > self.max_frame_bytes:
                raise FrameTooLarge(
                    f"incoming frame of {length} bytes exceeds the "
                    f"{self.max_frame_bytes}-byte cap"
                )
            body_deadline = time.perf_counter() + self.frame_timeout_s
            payload = self._recv_exact(length, body_deadline, None)
            try:
                frame = pickle.loads(payload)
            except Exception as exc:  # noqa: BLE001 — any decode failure is protocol death
                raise FrameProtocolError(
                    f"undecodable frame payload: {exc}") from exc
            if self._hit("recv"):
                continue  # injected network fault: frame dropped before dispatch
            return frame, epoch

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def fileno(self) -> int:
        return self._sock.fileno()


class ClockSync:
    """NTP-style clock-offset estimator over the worker ping loop.

    Router and worker each run their own ``perf_counter`` — unrelated
    origins, so a worker's flight timestamps are meaningless on the
    router's timeline until an offset is known. Each ping/pong exchange
    yields one sample (NTP's four-timestamp exchange collapsed to three:
    the worker turns the pong around immediately, so its receive and
    transmit stamps coincide):

    * ``t_tx``  — router clock when the ping left
    * ``t_peer`` — worker clock when the pong was stamped
    * ``t_rx``  — router clock when the pong landed

    ``offset = t_peer − (t_tx + rtt/2)`` under the symmetric-path
    assumption; the error is bounded by ``rtt/2`` regardless of asymmetry,
    so :meth:`estimate` returns the MINIMUM-RTT sample over a sliding
    window (Cristian's algorithm / NTP clock-filter shape: the fastest
    exchange had the least queueing and the tightest bound) and reports
    ``uncertainty_s = rtt/2`` alongside it. Fleet Chrome traces re-base
    worker timestamps by the offset and stamp the bound on the lane name —
    causality within ±uncertainty is readable, beyond it is not claimed.

    Thread-safe: the ping thread adds samples, trace exporters read."""

    def __init__(self, window: int = 64) -> None:
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=window)  # guarded-by: _lock
        self._total = 0  # guarded-by: _lock

    def add_sample(self, t_tx: float, t_rx: float, t_peer: float) -> None:
        """Record one ping/pong exchange (router clocks ``t_tx``/``t_rx``,
        worker clock ``t_peer``). A negative apparent RTT (clock jitter)
        is clamped — the sample still carries offset information."""
        rtt = max(float(t_rx) - float(t_tx), 0.0)
        offset = float(t_peer) - (float(t_tx) + rtt / 2.0)
        with self._lock:
            self._samples.append((rtt, offset))
            self._total += 1

    def estimate(self) -> Optional[dict]:
        """Best current estimate: the min-RTT sample in the window —
        ``{"offset_s", "rtt_s", "uncertainty_s", "samples"}`` (offset is
        worker-clock minus router-clock), or None before any sample."""
        with self._lock:
            if not self._samples:
                return None
            rtt, offset = min(self._samples)
            total = self._total
        return {"offset_s": offset, "rtt_s": rtt,
                "uncertainty_s": rtt / 2.0, "samples": total}


# --------------------------------------------------------------------------
# handshake

# frame-emit: handshake-to-accepter via=socket
def send_hello(transport: SocketTransport, token: str, slot: int,  # frame-dispatch: handshake-to-dialer via=socket
               pid: int, epoch: Optional[int] = None,
               timeout_s: float = 10.0) -> dict:
    """Connecting side: identify + authenticate, await the ack.

    Two directions share this shape: a WORKER registering against the
    router's registry listener sends no epoch and receives its grant in
    the ack; a ROUTER dialing an advertised remote worker
    (``REPLICA_WORKERS``) already owns the epoch counter and sends the
    epoch it assigned, which the ack echoes. Either way the granted epoch
    is stamped onto the transport (every subsequent frame carries it) and
    the full ack payload is returned."""
    hello = {"token": token, "slot": int(slot),
             "proto": PROTOCOL_VERSION, "pid": int(pid)}
    if epoch is not None:
        hello["epoch"] = int(epoch)
    transport.send((0, "hello", hello))
    got = transport.recv(timeout_s=timeout_s)
    if got is None:
        raise TransportClosed(f"no hello ack within {timeout_s:.0f}s")
    frame, _epoch = got
    _req, kind, payload = frame
    if kind == "hello_reject":
        raise FrameProtocolError(
            f"registration rejected: {payload.get('reason', 'unknown')}")
    if kind != "hello_ack":
        raise FrameProtocolError(f"expected hello_ack, got {kind!r}")
    transport.epoch = int(payload.get("epoch", epoch or 0))
    return payload


# frame-emit: handshake-to-dialer via=socket
def expect_hello(transport: SocketTransport, token: str,  # frame-dispatch: handshake-to-accepter via=socket
                 timeout_s: float = 10.0) -> dict:
    """Accepting side: read + validate the peer's hello. Raises
    :class:`FrameProtocolError` (after sending a reject frame, best-effort)
    on a bad token or version — the caller drops the connection. Returns
    the hello payload; the caller assigns the epoch and sends the ack."""
    got = transport.recv(timeout_s=timeout_s)
    if got is None:
        raise TransportClosed(f"no hello within {timeout_s:.0f}s")
    frame, _epoch = got
    try:
        _req, kind, payload = frame
    except (TypeError, ValueError) as exc:
        raise FrameProtocolError(f"malformed hello frame: {frame!r}") from exc
    reason = ""
    if kind != "hello" or not isinstance(payload, dict):
        reason = "first frame was not a hello"
    else:
        try:
            proto_ok = int(payload.get("proto", -1)) == PROTOCOL_VERSION
        except (TypeError, ValueError):
            proto_ok = False
        if not proto_ok:
            reason = (f"protocol v{payload.get('proto')!r} unsupported "
                      f"(this side v{PROTOCOL_VERSION})")
        else:
            # compare as BYTES: compare_digest raises TypeError on
            # non-ASCII str input, and a hostile hello must never crash
            # the accept loop with an untyped error
            sent = str(payload.get("token", "")).encode("utf-8", "replace")
            if not hmac.compare_digest(sent, token.encode("utf-8",
                                                          "replace")):
                reason = "bad auth token"
    if reason:
        try:
            transport.send((0, "hello_reject", {"reason": reason}))
        except TransportError:
            pass
        raise FrameProtocolError(f"registration rejected: {reason}")
    return payload


def dial(
    addr: tuple,
    connect_timeout_s: float = 10.0,
    max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
    frame_timeout_s: float = DEFAULT_FRAME_TIMEOUT_S,
    fault_scope: str = "",
) -> SocketTransport:
    """Open a TCP connection and wrap it. Connect errors raise
    :class:`TransportClosed` (retryable by the caller's backoff loop)."""
    try:
        sock = socket.create_connection(
            (addr[0], int(addr[1])), timeout=connect_timeout_s)
    except OSError as exc:
        raise TransportClosed(f"connect to {addr} failed: {exc}") from exc
    return SocketTransport(
        sock, max_frame_bytes=max_frame_bytes,
        frame_timeout_s=frame_timeout_s, fault_scope=fault_scope,
    )
