"""Paged KV cache + continuous batching — the long-context serving core.

The reference caps context at ~2000 tokens and serves one request per HTTP
call (/root/reference/src/core/graph/nodes.py:296-338, factory.py:90); its
"batching" is a connection pool. Here the KV cache is *paged*: HBM holds one
pool of fixed-size pages ([L, P, page, Hkv, D]) and every live sequence owns
a page table mapping logical blocks to physical pages. That buys:

* **continuous batching** — requests join and leave decode slots without
  recompiling or re-laying-out anyone else's cache; one compiled decode
  program serves the whole lifetime of the server;
* **long contexts without fragmentation** — a 8K-token sequence and a
  50-token sequence coexist in the same pool, each paying only for the
  pages it touches;
* **instant reclaim** — finishing a request frees integer page ids, not
  device memory.

Device side is pure-functional: ``paged_decode_step`` threads the page pool
through jit with donated buffers (the pool is updated in place, never
copied). Host side, ``PageAllocator`` is a free-list and ``ContinuousBatchingEngine``
owns slot admission / EOS retirement, mirroring the reference's resilience
stance (a failing request fails alone, SURVEY.md §5).

Page 0 is reserved as a scratch page: free slots' page tables point at it,
so masked lanes in the fused decode step write garbage somewhere harmless.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import numpy as np

from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.parallel.batcher import bucket_size

Array = object  # jax.Array — jax imported lazily


# --------------------------------------------------------------------- pool


@dataclass
class PagedPool:
    """Device-side page pool. k/v: [L, P, page, Hkv, D]; page id 0 = scratch."""

    k: Array
    v: Array
    page_size: int

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]


def init_pool(cfg: LlamaConfig, num_pages: int, page_size: int) -> PagedPool:
    import jax.numpy as jnp

    shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    return PagedPool(
        k=jnp.zeros(shape, cfg.jdtype), v=jnp.zeros(shape, cfg.jdtype), page_size=page_size
    )


class PageAllocator:
    """Host free-list over page ids 1..P-1 (0 is the shared scratch page)."""

    def __init__(self, num_pages: int) -> None:
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved scratch)")
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        self.num_pages = num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise MemoryError(f"paged KV pool exhausted: need {n}, have {len(self._free)}")
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, ids: Sequence[int]) -> None:
        for pid in ids:
            if pid == 0:
                continue
            self._free.append(pid)


# ------------------------------------------------------------ device kernels


def _paged_attn_xla(q, k_pages_l, v_pages_l, page_table, lens, n_rep):
    """Decode attention over a page table, XLA gather path.

    q [B,1,H,D]; k/v_pages_l [P,page,Hkv,D]; page_table [B,NB]; lens [B].
    Gathers each row's pages into a contiguous [B, NB*page, Hkv, D] window —
    XLA fuses the gather into the attention when the window is modest; the
    Pallas kernel in kernels/paged_attention.py walks the table in VMEM
    instead and is preferred on TPU for large windows.
    """
    import jax.numpy as jnp

    from sentio_tpu.models import layers as L

    b, nb = page_table.shape
    page = k_pages_l.shape[1]
    kc = k_pages_l[page_table].reshape(b, nb * page, *k_pages_l.shape[2:])
    vc = v_pages_l[page_table].reshape(b, nb * page, *v_pages_l.shape[2:])
    kc = L.repeat_kv(kc, n_rep)
    vc = L.repeat_kv(vc, n_rep)
    kj = jnp.arange(nb * page)[None, None, None, :]
    mask = kj <= lens[:, None, None, None]  # new token sits at index lens
    return L.attention(q, kc, vc, mask, q.dtype)


def paged_decode_forward(params, cfg: LlamaConfig, tok, lens, page_table, k_pages, v_pages,
                         attn_impl=None):
    """One decode step over the paged pool.

    tok [B] int32 (last sampled token per slot); lens [B] absolute position
    the new token occupies; page_table [B, NB]. Returns (logits [B, V],
    k_pages, v_pages) with this step's k/v scattered into each row's current
    page. Masked/free slots must point their page table at scratch page 0.
    """
    import jax
    import jax.numpy as jnp

    from sentio_tpu.models import layers as L

    dt = cfg.jdtype
    b = tok.shape[0]
    h, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    page = k_pages.shape[2]
    positions = lens[:, None]  # [B,1]
    window = page_table.shape[1] * page
    cos, sin = L.rope_frequencies(hd, max(window, cfg.max_len), cfg.rope_theta)

    page_ids = jnp.take_along_axis(page_table, (lens // page)[:, None], axis=1)[:, 0]
    offsets = lens % page

    x = L.embed(params["embed_tokens"], tok[:, None], dt)  # [B,1,d]
    for i in range(cfg.n_layers):
        lp = params[f"layers_{i}"]
        xn = L.rmsnorm(lp["attn_norm"], x, cfg.norm_eps)
        q = L.dense(lp["attn"]["wq"], xn, dt).reshape(b, 1, h, hd)
        k = L.dense(lp["attn"]["wk"], xn, dt).reshape(b, 1, hkv, hd)
        v = L.dense(lp["attn"]["wv"], xn, dt).reshape(b, 1, hkv, hd)
        q = L.apply_rope(q, positions, cos, sin)
        k = L.apply_rope(k, positions, cos, sin)

        k_pages = k_pages.at[i, page_ids, offsets].set(k[:, 0].astype(dt))
        v_pages = v_pages.at[i, page_ids, offsets].set(v[:, 0].astype(dt))

        impl = attn_impl or _paged_attn_xla
        out = impl(q, k_pages[i], v_pages[i], page_table, lens, h // hkv)
        x = x + L.dense(lp["attn"]["wo"], out.reshape(b, 1, cfg.dim), dt)

        xm = L.rmsnorm(lp["mlp_norm"], x, cfg.norm_eps)
        gate = jax.nn.silu(L.dense(lp["mlp"]["w_gate"], xm, dt))
        x = x + L.dense(lp["mlp"]["w_down"], gate * L.dense(lp["mlp"]["w_up"], xm, dt), dt)

    x = L.rmsnorm(params["final_norm"], x, cfg.norm_eps)
    logits = L.dense(params["lm_head"], x, dt)[:, 0]
    return logits.astype(jnp.float32), k_pages, v_pages


def scatter_prefill(k_pages, v_pages, k_cache, v_cache, page_table):
    """Copy a contiguous prefill cache into the pool.

    k/v_cache [L, B, S, Hkv, D] (S a multiple of page size), page_table
    [B, S/page]. Blocks past a row's prompt length should map to scratch
    page 0 in the table — their garbage lands there.
    """
    lcount, b, s, hkv, hd = k_cache.shape
    page = k_pages.shape[2]
    nb = s // page
    kr = k_cache.reshape(lcount, b, nb, page, hkv, hd)
    vr = v_cache.reshape(lcount, b, nb, page, hkv, hd)
    # dims 1 of pages indexed by [B, NB] table → scatter [L, B, NB, page, H, D]
    k_pages = k_pages.at[:, page_table].set(kr)
    v_pages = v_pages.at[:, page_table].set(vr)
    return k_pages, v_pages


# ---------------------------------------------------------------- the engine


@dataclass
class _Slot:
    request_id: int = -1
    pages: list[int] = field(default_factory=list)
    length: int = 0          # tokens currently in cache (prompt + generated)
    prompt_tokens: int = 0
    max_new: int = 0
    temperature: float = 0.0
    emitted: list[int] = field(default_factory=list)
    active: bool = False


@dataclass
class _Request:
    request_id: int
    prompt: str
    max_new: int
    temperature: float


@dataclass
class PagedResult:
    request_id: int
    text: str
    tokens: list[int]
    prompt_tokens: int
    finish_reason: str  # "stop" | "length"


class ContinuousBatchingEngine:
    """Slot-based continuous batching over the paged pool.

    A fixed decode batch of ``max_slots`` lanes runs one fused decode step
    per tick; requests are admitted into free lanes (prefill → scatter into
    pages) and retired on EOS / length, freeing their pages. The decode
    program compiles ONCE for the server's lifetime — admission changes
    only array *contents* (page tables, lengths, masks), never shapes.

    Single-threaded step() core so tests/bench drive it deterministically;
    serve/ wraps it in an asyncio pump.
    """

    PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048, 4096)

    def __init__(
        self,
        model_config: Optional[LlamaConfig] = None,
        params=None,
        tokenizer=None,
        max_slots: int = 8,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        max_pages_per_seq: int = 16,
        rng_seed: int = 0,
        use_pallas: Optional[bool] = None,
    ) -> None:
        import jax

        from sentio_tpu.models.llama import init_llama
        from sentio_tpu.models.tokenizer import ByteTokenizer

        self.cfg = model_config or LlamaConfig.tiny()
        self.tokenizer = tokenizer or ByteTokenizer(self.cfg.vocab_size)
        self.params = params if params is not None else init_llama(
            jax.random.PRNGKey(rng_seed), self.cfg
        )
        self.max_slots = max_slots
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        if num_pages is None:
            num_pages = 1 + max_slots * max_pages_per_seq
        self.pool = init_pool(self.cfg, num_pages, page_size)
        self.allocator = PageAllocator(num_pages)

        self.slots = [_Slot() for _ in range(max_slots)]
        self._queue: list[_Request] = []
        self._finished_buffer: list[PagedResult] = []
        self._next_id = itertools.count()
        self._rng = jax.random.PRNGKey(rng_seed + 1)
        # host mirrors of device state, re-uploaded when admission changes them
        self._page_table = np.zeros((max_slots, max_pages_per_seq), np.int32)
        self._lens = np.zeros(max_slots, np.int32)
        self._temps = np.zeros(max_slots, np.float32)
        self._last_tok = np.zeros(max_slots, np.int32)
        # Pallas paged-attention kernel walks page tables in VMEM on TPU;
        # the XLA gather path is the universal fallback (and CPU test path)
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self._attn_impl = None
        if use_pallas:
            from sentio_tpu.kernels.paged_attention import make_paged_attn_impl

            self._attn_impl = make_paged_attn_impl()
        self._build_fns()

    # ------------------------------------------------------------- compiled

    def _build_fns(self) -> None:
        import jax

        cfg = self.cfg
        attn_impl = self._attn_impl

        @partial(jax.jit, donate_argnums=(4, 5))
        def step(params, tok, lens, page_table, k_pages, v_pages, rng, temps):
            from sentio_tpu.runtime.sampling import sample_tokens

            logits, k_pages, v_pages = paged_decode_forward(
                params, cfg, tok, lens, page_table, k_pages, v_pages,
                attn_impl=attn_impl,
            )
            rng, sub = jax.random.split(rng)
            nxt = sample_tokens(logits, sub, temps)
            return nxt, k_pages, v_pages, rng

        self._step = step

        @partial(jax.jit, donate_argnums=(0, 1))
        def do_scatter(k_pages, v_pages, k_cache, v_cache, page_table):
            return scatter_prefill(k_pages, v_pages, k_cache, v_cache, page_table)

        self._scatter = do_scatter

        @jax.jit
        def prefill(params, ids, positions, cache):
            from sentio_tpu.models.llama import llama_forward

            logits, cache = llama_forward(
                params, cfg, ids, positions=positions, cache=cache, cache_index=0
            )
            return logits, cache

        self._prefill = prefill

    # --------------------------------------------------------------- public

    def submit(self, prompt: str, max_new_tokens: int = 64, temperature: float = 0.0) -> int:
        rid = next(self._next_id)
        self._queue.append(_Request(rid, prompt, max_new_tokens, temperature))
        return rid

    def reset(self) -> None:
        """Rebuild all device/host decode state after a failed tick.

        ``step``'s compiled programs donate the pool buffers — an exception
        mid-dispatch can leave ``pool.k/v`` deleted and slots half-admitted,
        which would poison every later tick. Queued and in-flight requests
        are dropped (their callers were already failed by the layer above);
        weights and compiled programs are kept."""
        import jax

        self.pool = init_pool(self.cfg, self.allocator.num_pages, self.page_size)
        self.allocator = PageAllocator(self.allocator.num_pages)
        self.slots = [_Slot() for _ in range(self.max_slots)]
        self._queue.clear()
        self._finished_buffer.clear()
        self._page_table[:] = 0
        self._lens[:] = 0
        self._temps[:] = 0.0
        self._last_tok[:] = 0
        self._rng = jax.random.PRNGKey(int(np.random.default_rng().integers(2**31)))

    @property
    def has_work(self) -> bool:
        return bool(self._queue) or any(s.active for s in self.slots)

    def run_all(
        self, prompts: Sequence[str], max_new_tokens: int = 64, temperature: float = 0.0
    ) -> list[PagedResult]:
        """Submit-and-drain convenience used by tests and bench."""
        ids = [self.submit(p, max_new_tokens, temperature) for p in prompts]
        done: dict[int, PagedResult] = {}
        while self.has_work:
            for r in self.step():
                done[r.request_id] = r
        return [done[i] for i in ids]

    def step(self) -> list[PagedResult]:
        """One engine tick: admit waiting requests, one fused decode step,
        retire finished slots. Returns results completed this tick."""
        self._admit()
        out, self._finished_buffer = self._finished_buffer, []
        if any(s.active for s in self.slots):
            out.extend(self._decode_tick())
        return out

    # -------------------------------------------------------------- private

    def _free_slot_indices(self) -> list[int]:
        return [i for i, s in enumerate(self.slots) if not s.active]

    def _admit(self) -> None:
        import jax.numpy as jnp

        free = self._free_slot_indices()
        if not free or not self._queue:
            return

        batch: list[tuple[int, _Request, list[int]]] = []
        while self._queue and free:
            req = self._queue[0]
            tok_ids = self.tokenizer.encode(req.prompt, add_bos=True)
            # budget split inside the per-sequence page window: generation
            # gets its requested tokens up to HALF the window (else decode
            # retires on out_of_pages after window - prompt tokens); the
            # prompt always keeps at least the other half, so a huge
            # max_new can never silently truncate most of the context
            window = self.max_pages_per_seq * self.page_size
            reserve = min(req.max_new + 2, window // 2)
            tok_ids = tok_ids[: window - reserve]
            need_now = (len(tok_ids) + self.page_size - 1) // self.page_size
            need_total = min(
                (len(tok_ids) + req.max_new + self.page_size - 1) // self.page_size,
                self.max_pages_per_seq,
            )
            if need_total > self.allocator.free_pages:
                break  # head-of-line blocks until pages free up (no starvation)
            pages = self.allocator.alloc(need_total)
            slot_idx = free.pop(0)
            self._queue.pop(0)
            batch.append((slot_idx, req, tok_ids))
            slot = self.slots[slot_idx]
            slot.request_id = req.request_id
            slot.pages = pages
            slot.prompt_tokens = len(tok_ids)
            slot.length = len(tok_ids)
            slot.max_new = req.max_new
            slot.temperature = req.temperature
            slot.emitted = []
            slot.active = True
            row = np.zeros(self.max_pages_per_seq, np.int32)
            row[: len(pages)] = pages
            self._page_table[slot_idx] = row
            self._lens[slot_idx] = len(tok_ids)
            self._temps[slot_idx] = req.temperature

        if not batch:
            return

        # one prefill per admitted row: width-bucketed contiguous forward,
        # then scatter the cache into that row's pages. Rows are prefilled
        # individually (B=1) so each (width) bucket compiles once.
        from sentio_tpu.models.llama import init_cache
        from sentio_tpu.runtime.sampling import sample_tokens

        import jax

        for slot_idx, req, tok_ids in batch:
            width = bucket_size(
                max(len(tok_ids), self.page_size), tuple(
                    b for b in self.PREFILL_BUCKETS if b % self.page_size == 0
                ) or (self.page_size,),
            )
            width = ((width + self.page_size - 1) // self.page_size) * self.page_size
            ids = np.full((1, width), self.tokenizer.pad_id, np.int32)
            ids[0, : len(tok_ids)] = tok_ids
            positions = np.arange(width, dtype=np.int32)[None, :]
            cache = init_cache(self.cfg, 1, width)
            logits, cache = self._prefill(
                self.params, jnp.asarray(ids), jnp.asarray(positions), cache
            )
            # table for the scatter: blocks holding prompt → this row's pages,
            # padding blocks → scratch 0
            nb = width // self.page_size
            used = (len(tok_ids) + self.page_size - 1) // self.page_size
            scat = np.zeros((1, nb), np.int32)
            scat[0, :used] = self.slots[slot_idx].pages[:used]
            self.pool.k, self.pool.v = self._scatter(
                self.pool.k, self.pool.v, cache["k"], cache["v"], jnp.asarray(scat)
            )
            # first generated token comes from the prefill logits
            self._rng, sub = jax.random.split(self._rng)
            first = sample_tokens(
                logits[:, len(tok_ids) - 1], sub, req.temperature
            )
            self._last_tok[slot_idx] = int(first[0])

        # freshly admitted rows already have token 0 sampled; emit it now so
        # EOS-as-first-token retires before wasting a decode tick
        self._finished_buffer.extend(self._post_sample({i for i, _, _ in batch}))

    def _decode_tick(self) -> list[PagedResult]:
        import jax
        import jax.numpy as jnp

        nxt, self.pool.k, self.pool.v, self._rng = self._step(
            self.params,
            jnp.asarray(self._last_tok),
            jnp.asarray(self._lens),
            jnp.asarray(self._page_table),
            self.pool.k,
            self.pool.v,
            self._rng,
            jnp.asarray(self._temps),
        )
        nxt = np.asarray(nxt)
        for i, slot in enumerate(self.slots):
            if not slot.active:
                continue
            slot.length += 1
            self._lens[i] = slot.length
            self._last_tok[i] = nxt[i]
        return self._post_sample(set(range(self.max_slots)))

    def _post_sample(self, rows: set) -> list[PagedResult]:
        """Fold the freshly sampled token of each row in ``rows`` into its
        slot; retire rows that hit EOS or their token budget."""
        finished: list[PagedResult] = []
        for i in sorted(rows):
            slot = self.slots[i]
            if not slot.active:
                continue
            tok = int(self._last_tok[i])
            hit_eos = tok == self.tokenizer.eos_id
            if not hit_eos:
                slot.emitted.append(tok)
            hit_len = len(slot.emitted) >= slot.max_new
            out_of_pages = slot.length + 1 >= len(slot.pages) * self.page_size
            if hit_eos or hit_len or out_of_pages:
                finished.append(
                    PagedResult(
                        request_id=slot.request_id,
                        text=self.tokenizer.decode(slot.emitted),
                        tokens=list(slot.emitted),
                        prompt_tokens=slot.prompt_tokens,
                        finish_reason="stop" if hit_eos else "length",
                    )
                )
                self.allocator.free(slot.pages)
                slot.active = False
                slot.pages = []
                self._page_table[i] = 0
                self._lens[i] = 0
                self._temps[i] = 0.0
                self._last_tok[i] = 0
        return finished

    # ---------------------------------------------------------------- stats

    def stats(self) -> dict:
        active = sum(s.active for s in self.slots)
        return {
            "active_slots": active,
            "max_slots": self.max_slots,
            "queued": len(self._queue),
            "free_pages": self.allocator.free_pages,
            "total_pages": self.allocator.num_pages,
            "page_size": self.page_size,
        }
