"""Paged KV cache + continuous batching — the long-context serving core.

The reference caps context at ~2000 tokens and serves one request per HTTP
call (/root/reference/src/core/graph/nodes.py:296-338, factory.py:90); its
"batching" is a connection pool. Here the KV cache is *paged*: HBM holds one
pool of fixed-size pages ([L, P, page, Hkv, D]) and every live sequence owns
a page table mapping logical blocks to physical pages. That buys:

* **continuous batching** — requests join and leave decode slots without
  recompiling or re-laying-out anyone else's cache; one compiled decode
  program serves the whole lifetime of the server;
* **long contexts without fragmentation** — a 8K-token sequence and a
  50-token sequence coexist in the same pool, each paying only for the
  pages it touches;
* **instant reclaim** — finishing a request frees integer page ids, not
  device memory.

Device side is pure-functional: the fused tick threads the page pool
through jit with donated buffers (the pool is updated in place, never
copied). Host side, ``PageAllocator`` is a free-list and ``ContinuousBatchingEngine``
owns slot admission / EOS retirement, mirroring the reference's resilience
stance (a failing request fails alone, SURVEY.md §5).

The engine is built around ONE cost model: device dispatches are async and
effectively free, while every host-visible transfer is a round trip (~RTT —
dominant through remote-attached chips, real overhead locally). Hence:

* **multi-step fused ticks** — one ``lax.scan`` dispatch runs up to
  ``max_tick_steps`` decode sub-steps with per-row budgets and EOS halting;
  the host fetches ONE packed [1+steps, B] token array per tick and replays
  the device's halting rule exactly (no mask transfer);
* **batched admission, deferred first tokens** — queued requests prefill as
  width-bucketed batches (prefill + cache scatter + first-token sample in
  one dispatch), and the sampled first tokens stay on device until the next
  tick's fetch carries them back;
* **device-carried decode state** — token/position/halt arrays thread from
  tick to tick as device arrays (host numpy rides jit calls, never eager
  uploads), which enables
* **pipelined ticks** (``pipeline_depth=2``) — tick N+1 dispatches BEFORE
  tick N's fetch, overlapping the round trip with device compute; per-lane
  request ids guard against stale replays when slots retire and are reused
  mid-flight.

Page 0 is reserved as a scratch page: free slots' page tables point at it,
so masked lanes in the fused decode step write garbage somewhere harmless.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from sentio_tpu.analysis.audit.registry import jit_family
from sentio_tpu.analysis.sanitizer import check_engine_invariants, engine_guard
from sentio_tpu.infra import faults
from sentio_tpu.infra.phases import ENGINE_PHASES, PhaseTimer
from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.parallel.batcher import bucket_size

Array = object  # jax.Array — jax imported lazily


# --------------------------------------------------------------------- pool


@dataclass
class PagedPool:
    """Device-side page pool. k/v: [L, P, page, Hkv, D] arrays, or — with
    int8 KV quantization — pytrees ``{"q": int8 [L,P,page,Hkv,D], "s": f16
    [L,P,page,Hkv]}`` (per-token-per-head absmax scales). The pytree form
    rides through every jit signature, scan carry, and donation unchanged;
    only the read/write helpers below understand the representation.
    Page id 0 = scratch."""

    k: Array
    v: Array
    page_size: int
    quantized: bool = False

    @property
    def num_pages(self) -> int:
        return (self.k["q"] if self.quantized else self.k).shape[1]

    @property
    def hbm_bytes(self) -> int:
        """Static device footprint of the k+v page pools (payload + scales
        for the quantized repr) — the number the footprint claims are
        audited by (bench phase A/C, the compile-manifest pools section)."""
        import jax

        return sum(
            int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves((self.k, self.v))
        )


def quantize_kv(x):
    """[..., D] float → (int8 [..., D], f16 scale [...]). Symmetric absmax
    per vector; a zero vector gets scale 0 and dequantizes to exact zeros.
    float16 scales keep the overhead at D/2 bytes per vector with ~0.1%
    scale error — negligible next to the int8 step itself."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    q = jnp.clip(
        jnp.round(xf / jnp.maximum(scale, 1e-8)[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale.astype(jnp.float16)


def dequantize_kv(q, scale, dtype):
    import jax.numpy as jnp

    return (
        q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
    ).astype(dtype)


def _page_write(pages, layer, page_ids, offsets, val):
    """Write val [B, Hkv, D] at (layer, page_ids[b], offsets[b]) per row —
    representation-aware (plain array or int8+scale pytree)."""
    if isinstance(pages, dict):
        q, s = quantize_kv(val)
        return {
            "q": pages["q"].at[layer, page_ids, offsets].set(q),
            "s": pages["s"].at[layer, page_ids, offsets].set(s),
        }
    return pages.at[layer, page_ids, offsets].set(val)


def _layer_pages(pages, layer):
    if isinstance(pages, dict):
        return {"q": pages["q"][layer], "s": pages["s"][layer]}
    return pages[layer]


def _page_dim(pages) -> int:
    return (pages["q"] if isinstance(pages, dict) else pages).shape[-3]


def _gather_pages(pages_l, page_table, dtype):
    """[P, page, Hkv, D](-repr) + table [B, NB] → dense [B, NB*page, Hkv, D]."""
    if isinstance(pages_l, dict):
        q = pages_l["q"][page_table]
        s = pages_l["s"][page_table]
        b, nb = page_table.shape
        out = dequantize_kv(q, s, dtype)
        return out.reshape(b, nb * out.shape[2], *out.shape[3:])
    b, nb = page_table.shape
    kc = pages_l[page_table]
    return kc.reshape(b, nb * kc.shape[2], *kc.shape[3:])


def init_pool(
    cfg: LlamaConfig, num_pages: int, page_size: int, mesh=None,
    quantized: bool = False,
) -> PagedPool:
    """Allocate the page pool; with a mesh, kv heads shard over ``tp`` (the
    same axis the wk/wv weight columns shard on, so per-shard Q·K never
    crosses devices) and page tables stay replicated host-side. With
    ``quantized`` the pool stores int8 + per-vector scales — ~half the HBM
    and half the decode-attention read bandwidth of bf16 pages."""
    import jax
    import jax.numpy as jnp

    shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads, cfg.head_dim)

    def alloc(arr_shape, dtype, spec=None):
        z = jnp.zeros(arr_shape, dtype)
        return z if spec is None else jax.device_put(z, spec)

    kv_spec = scale_spec = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from sentio_tpu.parallel.mesh import AXIS_TP

        tp = mesh.shape[AXIS_TP]
        if cfg.n_kv_heads % tp != 0:
            raise ValueError(
                f"n_kv_heads={cfg.n_kv_heads} not divisible by tp={tp}"
            )
        kv_spec = NamedSharding(mesh, P(None, None, None, AXIS_TP, None))
        scale_spec = NamedSharding(mesh, P(None, None, None, AXIS_TP))

    if quantized:
        k = {"q": alloc(shape, jnp.int8, kv_spec),
             "s": alloc(shape[:-1], jnp.float16, scale_spec)}
        v = {"q": alloc(shape, jnp.int8, kv_spec),
             "s": alloc(shape[:-1], jnp.float16, scale_spec)}
    else:
        k = alloc(shape, cfg.jdtype, kv_spec)
        v = alloc(shape, cfg.jdtype, kv_spec)
    return PagedPool(k=k, v=v, page_size=page_size, quantized=quantized)


class PageAllocator:
    """Host free-list over page ids 1..P-1 (0 is the shared scratch page)."""

    def __init__(self, num_pages: int) -> None:
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved scratch)")
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        self.num_pages = num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise MemoryError(f"paged KV pool exhausted: need {n}, have {len(self._free)}")
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, ids: Sequence[int]) -> None:
        for pid in ids:
            if pid == 0:
                continue
            self._free.append(pid)


# ------------------------------------------------------------ device kernels


def _paged_attn_xla(q, k_pages_l, v_pages_l, page_table, lens, n_rep):
    """Decode attention over a page table, XLA gather path.

    q [B,1,H,D]; k/v_pages_l [P,page,Hkv,D]; page_table [B,NB]; lens [B].
    Gathers each row's pages into a contiguous [B, NB*page, Hkv, D] window —
    XLA fuses the gather into the attention when the window is modest; the
    Pallas kernel in kernels/paged_attention.py walks the table in VMEM
    instead and is preferred on TPU for large windows.
    """
    import jax.numpy as jnp

    from sentio_tpu.models import layers as L

    kc = _gather_pages(k_pages_l, page_table, q.dtype)
    vc = _gather_pages(v_pages_l, page_table, q.dtype)
    window = kc.shape[1]
    kc = L.repeat_kv(kc, n_rep)
    vc = L.repeat_kv(vc, n_rep)
    kj = jnp.arange(window)[None, None, None, :]
    mask = kj <= lens[:, None, None, None]  # new token sits at index lens
    return L.attention(q, kc, vc, mask, q.dtype)


def paged_decode_forward(params, cfg: LlamaConfig, tok, lens, page_table, k_pages, v_pages,
                         attn_impl=None, write_mask=None):
    """One decode step over the paged pool.

    tok [B] int32 (last sampled token per slot); lens [B] absolute position
    the new token occupies; page_table [B, NB]. Returns (logits [B, V],
    k_pages, v_pages) with this step's k/v scattered into each row's current
    page. Masked/free slots must point their page table at scratch page 0.
    ``write_mask`` [B] bool (optional) redirects masked rows' k/v writes to
    the scratch page — the multi-step tick uses it to freeze rows that hit
    EOS or their budget mid-scan without corrupting their cache.
    """
    import jax
    import jax.numpy as jnp

    from sentio_tpu.models import layers as L

    dt = cfg.jdtype
    b = tok.shape[0]
    h, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    page = _page_dim(k_pages)
    positions = lens[:, None]  # [B,1]
    window = page_table.shape[1] * page
    cos, sin = L.rope_frequencies(hd, max(window, cfg.max_len), cfg.rope_theta)

    page_ids = jnp.take_along_axis(page_table, (lens // page)[:, None], axis=1)[:, 0]
    offsets = lens % page
    if write_mask is not None:
        page_ids = jnp.where(write_mask, page_ids, 0)
        offsets = jnp.where(write_mask, offsets, 0)

    x = L.embed(params["embed_tokens"], tok[:, None], dt)  # [B,1,d]
    for i in range(cfg.n_layers):
        lp = params[f"layers_{i}"]
        xn = L.rmsnorm(lp["attn_norm"], x, cfg.norm_eps)
        q = L.dense(lp["attn"]["wq"], xn, dt).reshape(b, 1, h, hd)
        k = L.dense(lp["attn"]["wk"], xn, dt).reshape(b, 1, hkv, hd)
        v = L.dense(lp["attn"]["wv"], xn, dt).reshape(b, 1, hkv, hd)
        q = L.apply_rope(q, positions, cos, sin)
        k = L.apply_rope(k, positions, cos, sin)

        k_pages = _page_write(k_pages, i, page_ids, offsets, k[:, 0].astype(dt))
        v_pages = _page_write(v_pages, i, page_ids, offsets, v[:, 0].astype(dt))

        impl = attn_impl or _paged_attn_xla
        out = impl(
            q, _layer_pages(k_pages, i), _layer_pages(v_pages, i),
            page_table, lens, h // hkv,
        )
        x = x + L.dense(lp["attn"]["wo"], out.reshape(b, 1, cfg.dim), dt)

        xm = L.rmsnorm(lp["mlp_norm"], x, cfg.norm_eps)
        if "moe" in lp:
            # routed-expert family (models/moe.py): frozen/free rows are
            # masked out of routing so they claim no expert capacity
            from sentio_tpu.models.moe import moe_mlp

            routed, _ = moe_mlp(
                lp["moe"], cfg, xm,
                None if write_mask is None else write_mask[:, None],
            )
            x = x + routed
        else:
            gate = jax.nn.silu(L.dense(lp["mlp"]["w_gate"], xm, dt))
            x = x + L.dense(lp["mlp"]["w_down"], gate * L.dense(lp["mlp"]["w_up"], xm, dt), dt)

    x = L.rmsnorm(params["final_norm"], x, cfg.norm_eps)
    logits = L.dense(params["lm_head"], x, dt)[:, 0]
    return logits.astype(jnp.float32), k_pages, v_pages


def scatter_prefill(k_pages, v_pages, k_cache, v_cache, page_table):
    """Copy a contiguous prefill cache into the pool.

    k/v_cache [L, B, S, Hkv, D] (S a multiple of page size), page_table
    [B, S/page]. Blocks past a row's prompt length should map to scratch
    page 0 in the table — their garbage lands there.
    """
    lcount, b, s, hkv, hd = k_cache.shape
    page = _page_dim(k_pages)
    nb = s // page

    def scatter_one(pages, cache):
        r = cache.reshape(lcount, b, nb, page, hkv, hd)
        if isinstance(pages, dict):
            q, sc = quantize_kv(r)
            return {
                "q": pages["q"].at[:, page_table].set(q),
                "s": pages["s"].at[:, page_table].set(sc),
            }
        # dims 1 of pages indexed by [B, NB] table → scatter [L,B,NB,page,H,D]
        return pages.at[:, page_table].set(r)

    return scatter_one(k_pages, k_cache), scatter_one(v_pages, v_cache)


# ---------------------------------------------------------------- the engine


@dataclass
class _Slot:
    request_id: int = -1
    pages: list[int] = field(default_factory=list)
    length: int = 0          # tokens currently in cache (prompt + generated)
    prompt_tokens: int = 0
    max_new: int = 0
    temperature: float = 0.0
    top_k: int = 0
    emitted: list[int] = field(default_factory=list)
    active: bool = False
    # first sampled token still on device (admission defers its fetch; the
    # next tick's packed output materializes it host-side)
    pending_first: bool = False
    # decode sub-steps granted to dispatched-but-unharvested ticks — budget
    # math must count them or a pipelined tick would over-run the limits
    inflight_steps: int = 0
    # tokens served from shared (read-only) prefix-cache pages at the front
    # of this slot's page table — counted in capacity, never freed by retire
    shared_tokens: int = 0
    # radix-cache bookkeeping: the node chain this slot pins (its page table
    # references those pages), the truncated prompt token ids (the insert key
    # once the prompt KV is fully written), and pages whose ownership moved
    # to the cache at insert time (retire must NOT free them)
    prefix_node: object = None
    prompt_ids: Optional[list] = None
    donated: list = field(default_factory=list)
    # wall-clock at submit(); TTFT is measured when the first sampled token
    # becomes host-visible (pending_first flips False)
    submit_t: float = 0.0
    # chunked prefill (prefill_chunk engine option): suffix tokens not yet
    # written to this slot's pages, and how many own tokens already are.
    # While prefill_todo is set the slot holds pages but takes no decode
    # budget — decode ticks for OTHER slots interleave with its segments.
    prefill_todo: Optional[list] = None
    prefill_done: int = 0


@dataclass
class _Request:
    request_id: int
    prompt: str
    max_new: int
    temperature: float
    # per-request top-k (0 = off). Rides every sampling dispatch as TRACED
    # int32 data — one compiled program for any k (PR 4's top_k fix), so
    # sampling stays fused inside the decode scan rather than becoming a
    # second logits-then-sample dispatch per tick.
    top_k: int = 0
    submit_t: float = 0.0
    # absolute time.perf_counter() deadline (None = no deadline). The queue
    # drops an expired request BEFORE admission — prefilling for a caller
    # that already gave up wastes exactly the ticks continuous batching is
    # supposed to reclaim (Yu et al., OSDI '22)
    deadline_ts: Optional[float] = None
    # lazily cached tokenization — _admit may inspect a queued request many
    # times (skip-ahead scans the queue every tick) without re-encoding
    tok_ids: Optional[list] = None
    # prior-prefix admission (resume-by-replay, runtime/replica.py): token
    # ids appended after the truncated prompt as already-generated context.
    # The prompt truncation reserve is computed as if max_new were
    # max_new + len(prior_tokens), which reproduces the ORIGINAL
    # admission's truncation exactly — the resumed context is byte-for-byte
    # the dead replica's context at the splice point.
    prior_tokens: Optional[list] = None
    # per-request sampling seed (None = leave the engine RNG stream alone):
    # folded ONCE into the engine's shared RNG at admission. Best-effort —
    # the engine RNG advances per tick for the whole batch, so this only
    # yields reproducible draws when the request is the engine's sole
    # sampled traffic; it is NOT a per-request pinned stream
    seed: Optional[int] = None


@dataclass
class PagedResult:
    request_id: int
    text: str
    tokens: list[int]
    prompt_tokens: int
    finish_reason: str  # "stop" | "length" | "cancelled" | "expired" | "error"
    # prompt tokens actually forwarded at admission vs served read-only from
    # the radix prefix cache (prefill_tokens + prefix_hit_tokens ==
    # prompt_tokens) — the per-request evidence of prefill work skipped
    prefill_tokens: int = 0
    prefix_hit_tokens: int = 0
    # sampled-token logprob accumulators (sum / min / sample count over
    # every token this request sampled, EOS included) — the raw signal the
    # verify confidence gate (ops/confidence.py) scores. count == 0 means
    # no logprobs were observed (cancelled pre-decode, spec-tick path).
    logprob_sum: float = 0.0
    logprob_min: float = 0.0
    logprob_count: int = 0
    # which serving replica's engine produced this result (-1 = a bare
    # engine outside any service); stamped by PagedGenerationService at
    # completion so tracing spans and stats sinks can name the replica
    replica_id: int = -1

    @property
    def logprob_mean(self) -> Optional[float]:
        if self.logprob_count <= 0:
            return None
        return self.logprob_sum / self.logprob_count

    def stats_dict(self) -> dict:
        """The confidence-gate signal as one dict — THE shape every
        ``stats``/``stats_out`` sink (TpuProvider, generate_stream) fills,
        so the streaming and non-streaming gates can never diverge."""
        out = {
            "logprob_sum": self.logprob_sum,
            "logprob_min": self.logprob_min,
            "logprob_count": self.logprob_count,
            "logprob_mean": self.logprob_mean,
            "tokens": len(self.tokens),
            "finish_reason": self.finish_reason,
        }
        if self.replica_id >= 0:
            out["replica_id"] = self.replica_id
        return out


class ContinuousBatchingEngine:
    """Slot-based continuous batching over the paged pool.

    A fixed decode batch of ``max_slots`` lanes runs one fused decode step
    per tick; requests are admitted into free lanes (prefill → scatter into
    pages) and retired on EOS / length, freeing their pages. The decode
    program compiles ONCE for the server's lifetime — admission changes
    only array *contents* (page tables, lengths, masks), never shapes.

    Single-threaded step() core so tests/bench drive it deterministically;
    serve/ wraps it in an asyncio pump.
    """

    PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)

    def __init__(
        self,
        model_config: Optional[LlamaConfig] = None,
        params=None,
        tokenizer=None,
        max_slots: int = 8,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        max_pages_per_seq: int = 16,
        rng_seed: int = 0,
        use_pallas: Optional[bool] = None,
        steps_per_tick: int = 8,
        max_tick_steps: Optional[int] = None,
        ignore_eos: bool = False,
        pipeline_depth: int = 1,
        mesh=None,
        forward_fn=None,
        kv_quant: str = "none",
        prefill_chunk: Optional[int] = None,
        draft_params=None,
        draft_config=None,
        spec_k: int = 4,
        prefix_cache: bool = True,
    ) -> None:
        """``forward_fn`` swaps the prefill model family (llama_forward
        contract); the fused decode tick detects the family per layer (a
        ``moe`` subtree routes through models/moe.py). See
        runtime/engine.py's identical seam."""
        import jax

        from sentio_tpu.models.llama import init_llama
        from sentio_tpu.models.tokenizer import ByteTokenizer

        self.cfg = model_config or LlamaConfig.tiny()
        self.tokenizer = tokenizer or ByteTokenizer(self.cfg.vocab_size)
        from sentio_tpu.models.llama import llama_forward
        from sentio_tpu.models.moe import MoeConfig, moe_serving_forward

        is_moe = isinstance(self.cfg, MoeConfig)
        explicit_params = params
        if params is None:
            if is_moe:
                from sentio_tpu.models.moe import init_moe

                params = init_moe(jax.random.PRNGKey(rng_seed), self.cfg)
            else:
                params = init_llama(jax.random.PRNGKey(rng_seed), self.cfg)
        self.params = params
        if forward_fn is None:
            forward_fn = moe_serving_forward if is_moe else llama_forward
        elif forward_fn in (moe_serving_forward, llama_forward):
            if (forward_fn is moe_serving_forward) != is_moe:
                raise ValueError(
                    f"forward_fn {forward_fn.__name__} does not match the "
                    f"{type(self.cfg).__name__} model family"
                )
        elif explicit_params is None:
            raise ValueError(
                "forward_fn overrides the model family; pass matching params "
                "explicitly (the default init builds the config family's tree)"
            )
        self.forward_fn = forward_fn
        self.max_slots = max_slots
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        # decode sub-steps fused into ONE device dispatch per tick: host
        # round trips (the dominant per-token cost through remote-attached
        # devices, and real overhead even locally) amortize over the chunk.
        # Admission latency grows by at most steps_per_tick decode steps.
        self.steps_per_tick = max(int(steps_per_tick), 1)
        # with an EMPTY queue nothing waits on admission, so ticks may grow
        # to this cap (rounded to a bucket) — the whole remaining generation
        # of the longest row can ride one dispatch + one fetch
        self.max_tick_steps = max(int(max_tick_steps), self.steps_per_tick) \
            if max_tick_steps is not None else self.steps_per_tick
        # benchmark workloads: random-init weights frequently greedy-sample
        # EOS immediately; fixed-length generation measures the real cost
        self.ignore_eos = bool(ignore_eos)
        # depth 2 dispatches tick N+1 BEFORE fetching tick N's tokens, so
        # the ~RTT host fetch overlaps device compute. Decode state (tok/
        # lens/halted) is carried ON DEVICE between ticks; EOS halting and
        # budget schedules are device/deterministic, so the speculative tick
        # is always semantically correct — at worst it spends masked
        # sub-steps on rows the harvest then retires. Depth 1 = synchronous;
        # a single in-flight record means deeper values are not supported.
        self.pipeline_depth = min(max(int(pipeline_depth), 1), 2)
        self.mesh = mesh
        # chunked prefill (vLLM-style): prompts longer than this admit as
        # page-aligned segments, ONE segment dispatch per tick, so a 4-8K
        # prefill never stalls other slots' decode for its whole length —
        # each tick pays at most one segment of prefill latency. None = off
        # (whole-prompt admission, the default).
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if prefill_chunk <= 0 or prefill_chunk % page_size:
                raise ValueError(
                    f"prefill_chunk must be a positive multiple of page_size "
                    f"({page_size}), got {prefill_chunk}"
                )
        self.prefill_chunk = prefill_chunk
        # paged speculative decoding (runtime/paged_spec.py): a draft model
        # turns each decode tick into draft/verify/accept rounds — exact by
        # construction (greedy rows bit-exact, sampled rows marginally
        # exact) while continuous batching keeps working
        self.draft_params = None
        self.draft_cfg = draft_config
        self.spec_k = max(int(spec_k), 1)
        self._spec_tick = None
        self._spec_dk = self._spec_dv = None
        if draft_params is not None:
            if draft_config is None:
                raise ValueError("draft_params requires draft_config")
            if mesh is not None:
                raise ValueError("paged speculation does not support a mesh yet")
            if prefill_chunk is not None:
                raise ValueError(
                    "paged speculation and chunked prefill are mutually "
                    "exclusive (the draft prefills whole prompts)"
                )
            if draft_config.vocab_size != self.cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_config.vocab_size} != target "
                    f"vocab {self.cfg.vocab_size}"
                )
            self.draft_params = draft_params
        if kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be 'none' or 'int8', got {kv_quant!r}")
        # int8 pages: ~half the pool HBM and decode-read bandwidth; scales
        # add D-th of the bf16 footprint back
        self.kv_quant = kv_quant
        if num_pages is None:
            num_pages = 1 + max_slots * max_pages_per_seq
        self.pool = init_pool(
            self.cfg, num_pages, page_size, mesh=mesh,
            quantized=kv_quant == "int8",
        )
        self.allocator = PageAllocator(num_pages)  # guarded-by: engine-thread

        # SENTIO_SANITIZE=1: single-driver-thread guard on mutating entry
        # points + page-conservation / radix-refcount checks per tick. None
        # when disabled, so the steady-state cost is one attribute test.
        self._san = engine_guard("ContinuousBatchingEngine")

        self.slots = [_Slot() for _ in range(max_slots)]  # guarded-by: engine-thread
        self.last_tick_active = 0
        # tick-phase attribution (infra/phases.py): reset at the top of
        # every step(), accumulated by the dispatch helpers, closed out at
        # the bottom of step() into last_step_phases (seconds per phase,
        # keys == ENGINE_PHASES) — the serving pump merges its own
        # inbox_drain/deliver sections in and records the full phase_ms
        # dict on the flight tick event. Plain perf_counter deltas.
        self._phase = PhaseTimer(ENGINE_PHASES)  # guarded-by: engine-thread
        self.last_step_phases: dict = dict.fromkeys(ENGINE_PHASES, 0.0)  # guarded-by: engine-thread
        # device sub-steps actually executed (the scan runs its full static
        # length; every sub-step streams the weights once) — throughput and
        # HBM-utilization math must use this, not ticks x steps_per_tick
        self.total_sub_steps = 0
        # lifetime prefill-vs-decode token split: the flight recorder's pump
        # diffs these per tick to attribute each tick's work. Prefill counts
        # tokens actually forwarded (suffix-only on a prefix hit; per-segment
        # under chunked prefill); decode counts every folded sampled token.
        self.prefill_tokens_total = 0
        self.decode_tokens_total = 0
        self._queue: list[_Request] = []  # guarded-by: engine-thread
        # skip-ahead admission: a request too large for the current free
        # pages may be jumped by later, smaller requests — but only
        # head_skip_bound times, after which the head gets strict FIFO
        # priority (starvation bound). Counts reset when the head admits.
        self.head_skip_bound = 16
        self._head_skips = 0
        # TTFT telemetry: submit() → first token host-visible, seconds
        self.ttft_samples: deque = deque(maxlen=1024)
        self.ttft_count = 0
        # automatic radix prefix cache (runtime/radix.py): every admitted
        # prompt's full-page KV is inserted into a token-id radix tree and
        # later requests — including the verify node reusing the generate
        # node's prompt head — longest-prefix-match against it, prefilling
        # only their unmatched suffix. prefix_cache=False (PREFIX_CACHE=0)
        # disables it entirely: every admission takes the cold prefill path,
        # byte-for-byte the pre-cache behavior.
        self._prefix_cache_enabled = bool(prefix_cache)
        if self._prefix_cache_enabled:
            from sentio_tpu.runtime.radix import RadixPrefixCache

            self._radix = RadixPrefixCache(page_size, self.allocator)
        else:
            self._radix = None
        # operator visibility for the BPE-boundary failure mode: a cached
        # head that never token-matches is silent otherwise (correct output,
        # zero benefit). Hits/misses count admissions against a non-empty
        # cache; the *_tokens totals count matched vs forwarded prompt
        # tokens — the number the prefill-skip claim is audited by.
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_tokens_total = 0
        self.prefix_miss_tokens_total = 0
        # paged-speculation efficiency: emitted/verifies = tokens-per-verify
        # (how well the draft predicts the target — the number that decides
        # whether the draft pays for itself)
        self.spec_emitted_total = 0
        self.spec_verifies_total = 0
        self._finished_buffer: list[PagedResult] = []  # guarded-by: engine-thread
        # (first_tokens_device_array, [slot_idx, ...]) per admission chunk,
        # consumed by the next decode tick
        self._pending_first: list = []  # guarded-by: engine-thread
        # optional callable the serving layer sets so ticks stay SHORT when
        # callers are waiting upstream of the engine's own queue (the
        # service inbox) — the engine queue alone can't see them
        self.pressure_hint = None
        # warmup override: pins the next ticks' fused-scan length to one
        # declared ladder rung so the compile fence can warm every rung
        # deterministically instead of racing a backlog into existence
        # (service.warmup); ignored unless the value is in tick_step_sizes()
        self.force_tick_steps: Optional[int] = None
        # device-resident decode carry (tok, lens, halted) threaded from the
        # previous tick's outputs; None until the first dispatch
        self._dev_state = None
        # dispatched-but-unfetched tick awaiting harvest (pipeline_depth 2)
        self._inflight: Optional[dict] = None
        self._next_id = itertools.count()
        self._rng = jax.random.PRNGKey(rng_seed + 1)
        # host mirrors of device state, re-uploaded when admission changes them
        self._page_table = np.zeros((max_slots, max_pages_per_seq), np.int32)
        self._lens = np.zeros(max_slots, np.int32)
        self._temps = np.zeros(max_slots, np.float32)
        self._top_ks = np.zeros(max_slots, np.int32)
        self._last_tok = np.zeros(max_slots, np.int32)
        # per-slot logprob accumulator mirrors: seeded into the first
        # dispatch after a reset, refreshed at harvest from the tick's
        # packed lp_state fetch, read by _retire into the PagedResult
        self._lp_sum = np.zeros(max_slots, np.float32)
        self._lp_min = np.zeros(max_slots, np.float32)
        self._lp_cnt = np.zeros(max_slots, np.int32)
        # Pallas paged-attention kernel walks page tables in VMEM on TPU;
        # the XLA gather path is the universal fallback (and CPU test path).
        # The kernel is representation-aware: int8 pools route to the quant
        # variant (int8 pages + f16 scales DMA'd per block, dequantized
        # in-register), so kv_quant="int8" keeps the fast path
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self._attn_impl = None
        if use_pallas:
            from sentio_tpu.kernels.paged_attention import make_paged_attn_impl

            self._attn_impl = make_paged_attn_impl()
        self._build_fns()

    # ------------------------------------------------------------- compiled

    def _build_fns(self) -> None:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        attn_impl = self._attn_impl
        forward_fn = self.forward_fn
        eos_id = self.tokenizer.eos_id

        ignore_eos = self.ignore_eos

        @jit_family("paged.step_n", static_argnames=("steps",),
                    donate_argnums=(5, 6))
        def step_n(params, tok, lens, halted, page_table, k_pages, v_pages,
                   rng, temps, top_ks, budgets, lp_sum, lp_min, lp_cnt,
                   steps):
            """``steps`` decode sub-steps fused into one dispatch (lax.scan).

            Per-row ``budgets`` bound how far each row may advance (token
            budget / page capacity, mirrored host-side); rows halt early on
            EOS. Frozen rows keep their lens/tok and write to scratch.
            Returns per-step sampled tokens [1+steps, B] plus one packed
            [3, B] float32 logprob-state array — the ONLY arrays the host
            fetches per tick — and the carried (tok, lens, halted, lp_sum,
            lp_min, lp_cnt) DEVICE state, so the next tick can dispatch
            without waiting for this tick's fetch (pipelining) and without
            re-uploading host mirrors. The execution mask is not returned:
            the host replay reconstructs it exactly from its own budgets
            plus first-EOS.

            ``lp_sum``/``lp_min``/``lp_cnt`` are per-slot RUNNING logprob
            accumulators (sum, min, sample count over every token this
            request sampled, including an EOS) carried in the scan body as
            traced state — the confidence gate's raw signal, accumulated
            with zero extra dispatches. Admission seeds them with the first
            token's logprob via ``merge_admitted``.
            """
            from sentio_tpu.runtime.sampling import sample_tokens

            def body(carry, idx):
                (tok, lens, k_pages, v_pages, rng, halted,
                 lp_sum, lp_min, lp_cnt) = carry
                active = (~halted) & (idx < budgets)
                logits, k_pages, v_pages = paged_decode_forward(
                    params, cfg, tok, lens, page_table, k_pages, v_pages,
                    attn_impl=attn_impl, write_mask=active,
                )
                rng, sub = jax.random.split(rng)
                # temperature AND top-k sample INSIDE the scan body — the
                # tick is one dispatch, never logits-then-sample. top_ks is
                # traced [B] int32; k<=0 rows keep the full distribution.
                nxt, lp = sample_tokens(logits, sub, temps, top_k=top_ks)
                tok = jnp.where(active, nxt, tok)
                lens = jnp.where(active, lens + 1, lens)
                lp_sum = jnp.where(active, lp_sum + lp, lp_sum)
                lp_min = jnp.where(active, jnp.minimum(lp_min, lp), lp_min)
                lp_cnt = jnp.where(active, lp_cnt + 1, lp_cnt)
                if not ignore_eos:
                    halted = halted | (active & (nxt == eos_id))
                return (tok, lens, k_pages, v_pages, rng, halted,
                        lp_sum, lp_min, lp_cnt), nxt

            tok_in = tok
            # rows whose (deferred) first token is already EOS never run
            if not ignore_eos:
                halted = halted | (tok == eos_id)
            init = (tok, lens, k_pages, v_pages, rng, halted,
                    lp_sum, lp_min, lp_cnt)
            (tok, lens, k_pages, v_pages, rng, halted,
             lp_sum, lp_min, lp_cnt), toks = jax.lax.scan(
                body, init, jnp.arange(steps)
            )
            # packed [1 + steps, B]: row 0 echoes the INPUT tokens so freshly
            # admitted rows' device-resident first tokens reach the host in
            # the same single fetch as the tick outputs
            packed = jnp.concatenate([tok_in[None, :], toks], axis=0)
            # one [3, B] fetch (not three): final accumulators, harvested
            # into the host mirrors the retiring PagedResult reads
            lp_state = jnp.stack(
                [lp_sum, lp_min, lp_cnt.astype(jnp.float32)], axis=0
            )
            return (packed, lp_state, tok, lens, halted,
                    lp_sum, lp_min, lp_cnt, k_pages, v_pages, rng)

        self._step_n = step_n

        @jit_family("paged.merge_admitted")
        def merge_admitted(tok, lens, halted, lp_sum, lp_min, lp_cnt,
                           first, first_lp, new_lens, idxs):
            """Scatter admission's device-resident first tokens (plus their
            prompt lengths, a cleared halt flag, and the first token's
            logprob seeding the per-slot confidence accumulators) into the
            carried decode state. ``idxs`` pads to ``first``'s length with
            an out-of-range index; mode='drop' discards the pad rows."""
            tok = tok.at[idxs].set(first, mode="drop")
            lens = lens.at[idxs].set(new_lens, mode="drop")
            halted = halted.at[idxs].set(False, mode="drop")
            lp_sum = lp_sum.at[idxs].set(first_lp, mode="drop")
            lp_min = lp_min.at[idxs].set(first_lp, mode="drop")
            lp_cnt = lp_cnt.at[idxs].set(1, mode="drop")
            return tok, lens, halted, lp_sum, lp_min, lp_cnt

        self._merge_admitted = merge_admitted

        @jit_family("paged.prefill_scatter", donate_argnums=(7, 8))
        def prefill_scatter(params, ids, positions, lens, rng, temps, scat,
                            k_pages, v_pages, top_ks):
            """Batched admission in ONE dispatch: contiguous prefill forward,
            cache scatter into each row's pages, first-token sample (token +
            its logprob, seeding the confidence accumulators) from each
            row's last prompt logit. Pad rows scatter to scratch page 0."""
            from sentio_tpu.models.llama import init_cache
            from sentio_tpu.runtime.sampling import sample_tokens

            b, width = ids.shape
            cache = init_cache(cfg, b, width)
            # pad tails and junk admission rows must not claim routed-expert
            # capacity (llama ignores the mask on the cache path)
            pad_mask = jnp.arange(width)[None, :] < lens[:, None]
            logits, cache = forward_fn(
                params, cfg, ids, positions=positions, cache=cache, cache_index=0,
                pad_mask=pad_mask,
            )
            k_pages, v_pages = scatter_prefill(
                k_pages, v_pages, cache["k"], cache["v"], scat
            )
            last = jnp.take_along_axis(logits, (lens - 1)[:, None, None], axis=1)[:, 0]
            rng, sub = jax.random.split(rng)
            first, first_lp = sample_tokens(last, sub, temps, top_k=top_ks)
            return first, first_lp, k_pages, v_pages, rng

        self._prefill_scatter = prefill_scatter

        page_size = self.page_size

        @jit_family("paged.prior_prefill_scatter",
                    static_argnames=("do_sample",), donate_argnums=(7, 8))
        def prior_prefill_scatter(params, ids, positions, lens, rng, temps,
                                  scat, k_pages, v_pages, prior_table,
                                  n_prior, top_ks, do_sample):
            """Prefill a batch of suffixes against per-row prior KV already
            in the pool — ONE compiled family for both radix-cache admission
            (prior = the matched shared-prefix pages) and chunked-prefill
            segments (prior = the row's own earlier segments + any matched
            prefix). Primes a contiguous cache from each row's prior pages,
            runs the suffix tokens at per-row offset positions, scatters
            only the new blocks.

            ``prior_table`` [B, PNB] is padded to a power-of-two page-count
            bucket with scratch page 0 and ``n_prior`` [B] carries the TRUE
            per-row prior lengths (traced, not static): pad pages' garbage
            stays masked because every key index past a row's real tokens
            exceeds all of its query positions, and the bucketing bounds
            compile variants to O(log window) instead of one fresh XLA
            program per (prior, width) pair. The first token samples only
            when ``do_sample`` (chunked prefill's non-final segments pass
            False), keeping the rng stream identical to whole-prompt
            admission."""
            from sentio_tpu.models.llama import init_cache
            from sentio_tpu.runtime.sampling import sample_tokens

            b, width = ids.shape
            pnb = prior_table.shape[1]
            prior_w = pnb * page_size
            cache = init_cache(cfg, b, prior_w + width)
            if pnb:
                def prime(cache_arr, pages):
                    if isinstance(pages, dict):
                        qv = pages["q"][:, prior_table]
                        sc = pages["s"][:, prior_table]
                        dense = dequantize_kv(qv, sc, cache_arr.dtype)
                    else:
                        dense = pages[:, prior_table]  # [L, B, PNB, pg, Hk, Hd]
                    lcount, bb, nb_, pg_, hk_, hd_ = dense.shape
                    return cache_arr.at[:, :, :prior_w].set(
                        dense.reshape(lcount, bb, nb_ * pg_, hk_, hd_))

                cache = dict(cache)
                cache["k"] = prime(cache["k"], k_pages)
                cache["v"] = prime(cache["v"], v_pages)

            pad_mask = jnp.arange(width)[None, :] < lens[:, None]
            logits, cache = forward_fn(
                params, cfg, ids, positions=positions, cache=cache,
                cache_index=n_prior, pad_mask=pad_mask,
            )
            # each row's new KV sits at its own dynamic offset in the primed
            # cache — slice the [n_prior, n_prior + width) window per row
            def row_window(arr, start):  # [L, S, Hk, Hd] → [L, width, Hk, Hd]
                return jax.lax.dynamic_slice(
                    arr, (0, start, 0, 0),
                    (arr.shape[0], width, arr.shape[2], arr.shape[3]))

            k_new = jax.vmap(row_window, in_axes=(1, 0), out_axes=1)(
                cache["k"], n_prior)
            v_new = jax.vmap(row_window, in_axes=(1, 0), out_axes=1)(
                cache["v"], n_prior)
            k_pages, v_pages = scatter_prefill(k_pages, v_pages, k_new, v_new, scat)
            if do_sample:
                last = jnp.take_along_axis(
                    logits, (lens - 1)[:, None, None], axis=1)[:, 0]
                rng, sub = jax.random.split(rng)
                first, first_lp = sample_tokens(last, sub, temps, top_k=top_ks)
            else:
                first = jnp.zeros((b,), jnp.int32)
                first_lp = jnp.zeros((b,), jnp.float32)
            return first, first_lp, k_pages, v_pages, rng

        self._prior_prefill_scatter = prior_prefill_scatter

        if self.draft_params is not None:
            from sentio_tpu.models.llama import llama_forward as _draft_fwd
            from sentio_tpu.runtime.paged_spec import build_spec_tick

            dcfg = self.draft_cfg
            self._spec_tick = build_spec_tick(
                self.forward_fn, cfg, _draft_fwd, dcfg,
                eos_id=self.tokenizer.eos_id, ignore_eos=self.ignore_eos,
                page_size=self.page_size,
            )

            @jit_family("paged.draft_prefill", donate_argnums=(2, 3))
            def draft_prefill(params_d, ids, d_k, d_v, rows_idx, lens):
                """Fill the persistent draft cache rows for freshly admitted
                slots (the draft's analogue of prefill_scatter; prefix pages
                are target-only, so the draft always prefills the FULL
                prompt). Pad rows index max_slots and drop."""
                from sentio_tpu.models.llama import init_cache

                b, width = ids.shape
                cache = init_cache(dcfg, b, width)
                positions = jnp.broadcast_to(
                    jnp.arange(width, dtype=jnp.int32)[None, :], (b, width)
                )
                pad_mask = jnp.arange(width)[None, :] < lens[:, None]
                _, cache = _draft_fwd(
                    params_d, dcfg, ids, positions=positions, cache=cache,
                    cache_index=0, pad_mask=pad_mask,
                )
                d_k = d_k.at[:, rows_idx, :width].set(cache["k"], mode="drop")
                d_v = d_v.at[:, rows_idx, :width].set(cache["v"], mode="drop")
                return d_k, d_v

            self._draft_prefill = draft_prefill

    def _ensure_draft_cache(self) -> None:
        import jax.numpy as jnp

        if self._spec_dk is not None:
            return
        dcfg = self.draft_cfg
        window = self.max_pages_per_seq * self.page_size
        shape = (dcfg.n_layers, self.max_slots, window,
                 dcfg.n_kv_heads, dcfg.head_dim)
        self._spec_dk = jnp.zeros(shape, dcfg.jdtype)
        self._spec_dv = jnp.zeros(shape, dcfg.jdtype)

    # --------------------------------------------------------------- public

    def submit(self, prompt: str, max_new_tokens: int = 64, temperature: float = 0.0,
               deadline_ts: Optional[float] = None, top_k: int = 0,
               prior_tokens: Optional[Sequence[int]] = None,
               seed: Optional[int] = None) -> int:
        """``deadline_ts`` is an absolute ``time.perf_counter()`` deadline:
        the queue drops the request (finish_reason="expired") if it is still
        waiting for a slot when the deadline passes. ``top_k`` (0 = off)
        rides the fused decode dispatch as traced per-row data — any value
        shares the one compiled tick program.

        ``prior_tokens`` is the prior-prefix admission surface (resume-by-
        replay, runtime/replica.py): already-generated token ids appended
        after the (truncation-exact) prompt as context, so decode continues
        from the splice point. The radix cache turns the replay into a
        prefix hit when the pages survive here, and a bounded replay
        prefill otherwise; emitted tokens are post-splice only.
        ``seed`` (None = off) folds into the engine RNG at admission."""
        if self._san is not None:
            self._san.enter("submit")
        top_k = int(top_k)
        if top_k > 0 and self._spec_tick is not None:
            raise ValueError(
                "top_k sampling is not supported with paged speculation "
                "(the spec tick's accept/correct rule is temperature-only)"
            )
        rid = next(self._next_id)
        self._queue.append(_Request(
            rid, prompt, max_new_tokens, temperature, top_k=max(top_k, 0),
            submit_t=time.perf_counter(), deadline_ts=deadline_ts,
            prior_tokens=(list(prior_tokens) if prior_tokens else None),
            seed=seed,
        ))
        return rid

    def warm_prefix(self, text: str) -> int:
        """Pre-populate the radix prefix cache with ``text``'s full-page KV
        so even the FIRST matching request admits suffix-only (without
        warming, request one prefills cold and seeds the cache itself).
        Returns the number of tokens now cached (0 = cache disabled or text
        shorter than one page). Idempotent; safe while slots are active —
        the cache is append-only from the engine's single driver thread and
        warming never frees pages a live table references. Warmed nodes are
        unpinned: LRU eviction reclaims them under page-pool pressure like
        any other cached prefix."""
        if self._san is not None:
            self._san.enter("warm_prefix")
        if self._radix is None:
            return 0
        toks = self.tokenizer.encode(text, add_bos=True)
        # leave at least one page of table room for suffix + decode
        n_blocks = min(len(toks) // self.page_size, self.max_pages_per_seq - 1)
        if n_blocks <= 0:
            return 0
        full = n_blocks * self.page_size
        matched, _pages, _node = self._radix.match(toks[:full])
        if matched >= full:
            return full  # already warm
        need = (full - matched) // self.page_size
        if need > self.allocator.free_pages:
            self._radix.evict(need - self.allocator.free_pages)
            matched, _pages, _node = self._radix.match(toks[:full])
            need = (full - matched) // self.page_size
            if need > self.allocator.free_pages:
                return 0  # pool pinned by live slots; requests warm it later
        pages = self.allocator.alloc(need)
        # cold-prefill the whole span, scatter only the uncovered blocks
        # (already-cached blocks scatter to scratch page 0 and are dropped);
        # the sampled token is discarded — this dispatch only fills pages
        width = self._prefill_width(full)
        ids, lens, temps, top_ks, scat, positions = self._assemble_prefill(
            [(toks[:full], 0.0, 0, [0] * (matched // self.page_size) + pages)],
            width,
        )
        _first, _first_lp, self.pool.k, self.pool.v, self._rng = \
            self._prefill_scatter(
                self.params, ids, positions, lens, self._rng, temps, scat,
                self.pool.k, self.pool.v, top_ks,
            )
        _node, donated = self._radix.insert(toks[:full], matched, pages)
        leftover = set(pages) - set(donated)
        if leftover:  # span raced into the tree between match and insert
            self.allocator.free(list(leftover))
        return full

    def peek_prefix(self, tok_ids: Sequence[int]) -> int:
        """Read-only routing probe: how many leading tokens of ``tok_ids``
        this engine's radix cache could serve from cached KV, clamped the
        same way admission clamps a real match (at least one suffix token
        must remain to prefill). Takes no refcounts, touches no LRU state,
        and — alone among engine methods — is safe to call from a non-driver
        thread: the result is an affinity HINT for the replica router, so a
        stale read during a concurrent insert/evict merely routes one
        request suboptimally. No ``_san.enter`` for the same reason: the
        single-driver contract guards mutation, and this mutates nothing."""
        if self._radix is None or not tok_ids:
            return 0
        try:
            matched = self._radix.peek_prefix(tok_ids)
        except Exception:  # noqa: BLE001 — torn concurrent read: no hint
            return 0
        max_shared = ((len(tok_ids) - 1) // self.page_size) * self.page_size
        return max(min(matched, max_shared), 0)

    def cancel(self, request_id: int) -> bool:
        """Abandon a request: queued → dropped; decoding → slot retired and
        pages freed (the tokens so far are discarded). Must be called by the
        engine's single driver thread, like every other engine method."""
        if self._san is not None:
            self._san.enter("cancel")
        for idx, req in enumerate(self._queue):
            if req.request_id == request_id:
                del self._queue[idx]
                if idx == 0:
                    # the skip budget belongs to the departed head; the new
                    # head must not inherit an exhausted one (it would
                    # disable skip-ahead on its first blocked scan)
                    self._head_skips = 0
                return True
        for i, slot in enumerate(self.slots):
            if slot.active and slot.request_id == request_id:
                self._retire(i, "cancelled")
                return True
        return False

    def reset(self) -> None:
        """Rebuild all device/host decode state after a failed tick.

        ``step``'s compiled programs donate the pool buffers — an exception
        mid-dispatch can leave ``pool.k/v`` deleted and slots half-admitted,
        which would poison every later tick. Queued and in-flight requests
        are dropped (their callers were already failed by the layer above);
        weights and compiled programs are kept."""
        if self._san is not None:
            self._san.enter("reset")
        # chaos seam: lets drills force the reset itself to fail (the path
        # that latches a service _broken and quarantines a replica) —
        # previously reachable only implicitly through a re-armed paged.step
        faults.hit("engine.reset")
        import jax

        self.pool = init_pool(
            self.cfg, self.allocator.num_pages, self.page_size, mesh=self.mesh,
            quantized=self.kv_quant == "int8",
        )
        self.allocator = PageAllocator(self.allocator.num_pages)
        self.slots = [_Slot() for _ in range(self.max_slots)]
        self._queue.clear()
        self._head_skips = 0
        self._finished_buffer.clear()
        self._pending_first.clear()
        self._dev_state = None
        self._inflight = None
        if self._prefix_cache_enabled:
            from sentio_tpu.runtime.radix import RadixPrefixCache

            self._radix = RadixPrefixCache(self.page_size, self.allocator)
        self._spec_dk = self._spec_dv = None  # rebuilt lazily (zeros)
        self._page_table[:] = 0
        self._lens[:] = 0
        self._temps[:] = 0.0
        self._top_ks[:] = 0
        self._last_tok[:] = 0
        self._lp_sum[:] = 0.0
        self._lp_min[:] = 0.0
        self._lp_cnt[:] = 0
        self._rng = jax.random.PRNGKey(int(np.random.default_rng().integers(2**31)))

    # FamilyFn instances owned by THIS engine (fresh jit wrappers per
    # engine): the pump's per-engine compile attribution and the rebuild
    # path's fence exemption both iterate exactly these attributes
    FAMILY_ATTRS = ("_step_n", "_merge_admitted", "_prefill_scatter",
                    "_prior_prefill_scatter", "_draft_prefill", "_spec_tick")

    def set_fence_exempt(self, exempt: bool) -> None:
        """Mark this engine's own jit families exempt from (or again subject
        to) an armed compile fence. A supervised in-place rebuild constructs
        a FRESH engine whose families are all cold — its warmup compiles are
        expected and must not trip the fence, while a steady-state recompile
        on any sibling replica's engine still does (the exemption is scoped
        to these instances, not global)."""
        for attr in self.FAMILY_ATTRS:
            fn = getattr(self, attr, None)
            if fn is not None and hasattr(fn, "fence_exempt"):
                fn.fence_exempt = bool(exempt)

    def spawn_fresh(self) -> "ContinuousBatchingEngine":
        """A brand-new engine sharing ONLY this engine's immutable state
        (weights, tokenizer, config) — private pool, allocator, radix tree,
        slots, and jit wrappers. The replica supervisor's in-place rebuild
        path: when ``reset()`` itself failed, the old engine's device
        buffers are unrecoverable and the only safe move is a clean
        re-instantiation from the shared weights (the same constructor path
        serve/dependencies.py uses to build replicas at startup)."""
        return ContinuousBatchingEngine(
            model_config=self.cfg,
            params=self.params,
            tokenizer=self.tokenizer,
            max_slots=self.max_slots,
            page_size=self.page_size,
            # baselined cross-thread-race: a config-constant read of an
            # engine-thread-owned object from the rebuild/supervisor roles —
            # spawn_fresh only runs after the wedged engine is QUARANTINED
            # (its pump abandoned), an ownership handoff the static model
            # cannot see but the runtime ThreadGuard enforces
            num_pages=self.allocator.num_pages,
            max_pages_per_seq=self.max_pages_per_seq,
            use_pallas=self._attn_impl is not None,
            steps_per_tick=self.steps_per_tick,
            max_tick_steps=self.max_tick_steps,
            ignore_eos=self.ignore_eos,
            pipeline_depth=self.pipeline_depth,
            mesh=self.mesh,
            forward_fn=self.forward_fn,
            kv_quant=self.kv_quant,
            prefill_chunk=self.prefill_chunk,
            draft_params=self.draft_params,
            draft_config=self.draft_cfg,
            spec_k=self.spec_k,
            prefix_cache=self._prefix_cache_enabled,
        )

    @property
    def has_work(self) -> bool:
        return (
            bool(self._queue)
            or any(s.active for s in self.slots)
            or self._inflight is not None
        )

    def run_all(
        self, prompts: Sequence[str], max_new_tokens: int = 64, temperature: float = 0.0
    ) -> list[PagedResult]:
        """Submit-and-drain convenience used by tests and bench."""
        ids = [self.submit(p, max_new_tokens, temperature) for p in prompts]
        done: dict[int, PagedResult] = {}
        while self.has_work:
            for r in self.step():
                done[r.request_id] = r
        return [done[i] for i in ids]

    def step(self) -> list[PagedResult]:
        """One engine tick: admit waiting requests (prefill dispatches, no
        fetch), one fused multi-step decode dispatch, ONE host fetch, retire
        finished slots. With ``pipeline_depth`` 2 the dispatch goes out
        BEFORE the previous tick's fetch, overlapping the host round trip
        with device compute (results then lag one tick). Returns results
        completed this tick."""
        if self._san is not None:
            self._san.enter("step")
        # the timer resets BEFORE the injection point: whatever a failed
        # step leaves in the accumulator belongs to THIS step alone, so the
        # pump's crash-path flush (partial_step_phases) can never re-count
        # the previous tick's already-recorded phases
        acc = self._phase.acc
        self._phase.reset()
        # chaos-drill injection point: a raised fault propagates exactly like
        # a real failed device dispatch (the serving pump resets + requeues)
        faults.hit("paged.step")
        t0 = time.perf_counter()
        self.last_tick_active = 0
        self._admit()
        if self.prefill_chunk is not None:
            self._advance_prefill()
        t_admit = time.perf_counter()
        # the admission span minus its jit dispatch calls is pure host build
        # work (tokenize, radix match, page alloc, padded array assembly)
        acc["admission_build"] += (t_admit - t0) - acc["prefill_dispatch"]
        record = self._dispatch_tick() if any(s.active for s in self.slots) else None
        t_dispatch = time.perf_counter()
        # decode dispatch is HOST CALL time of an async dispatch; any
        # blocking first-token fold inside it already went to device_wait
        acc["decode_dispatch"] += (t_dispatch - t_admit) - acc["device_wait"]
        # buffer swap AFTER dispatch: defensive retires made while budgeting
        # must ride THIS step's results (there may not be a next step)
        out, self._finished_buffer = self._finished_buffer, []
        if self.pipeline_depth <= 1:
            if record is not None:
                out.extend(self._harvest(record))
        else:
            prev, self._inflight = self._inflight, record
            if prev is not None:
                out.extend(self._harvest(prev))
        t_harvest = time.perf_counter()
        # the harvest span is dominated by the blocking packed-token fetch;
        # with pipeline_depth=2 this wait belongs to the PREVIOUS tick's
        # dispatch but is charged to the iteration that harvests it — that
        # is where the wall clock went, so per-tick conservation holds
        acc["device_wait"] += t_harvest - t_dispatch
        if self._san is not None:
            # page conservation + radix refcounts, checked on the tick that
            # broke them — not at pool exhaustion three workloads later
            check_engine_invariants(self)
        acc["other"] += time.perf_counter() - t_harvest
        self.last_step_phases = dict(acc)
        return out

    def partial_step_phases(self) -> dict:
        """Live (possibly mid-step) phase accumulations. When ``step()``
        raises, ``last_step_phases`` still holds the PREVIOUS tick's
        decomposition — the pump's crash-containment path reads these
        partials instead, so a failed iteration's wall time is attributed
        rather than holed (the timer reset at step entry guarantees they
        cover only the failed step)."""
        return dict(self._phase.acc)

    # -------------------------------------------------------------- private

    def _free_slot_indices(self) -> list[int]:
        return [i for i, s in enumerate(self.slots) if not s.active]

    ADMIT_BUCKETS = (1, 2, 4, 8)

    def _prefill_width(self, n_tokens: int) -> int:
        width = bucket_size(
            max(n_tokens, self.page_size), tuple(
                b for b in self.PREFILL_BUCKETS if b % self.page_size == 0
            ) or (self.page_size,),
        )
        return ((width + self.page_size - 1) // self.page_size) * self.page_size

    def _prior_bucket(self, n_blocks: int) -> int:
        """Static prior-table width for ``n_blocks`` prior pages: the next
        power of two (capped at the per-sequence window) so prior-primed
        prefill compiles O(log window) variants. 0 stays 0 (no prior)."""
        if n_blocks <= 0:
            return 0
        return min(1 << (n_blocks - 1).bit_length(), self.max_pages_per_seq)

    def tick_step_sizes(self) -> tuple[int, ...]:
        """Every fused-tick scan length ``_dispatch_tick`` can request: the
        idle-queue big tick plus the 3-rung pressure ladder. Each distinct
        value is one compiled ``step_n`` (or spec-tick) variant — the set
        the compile manifest commits to."""
        sizes = {self.max_tick_steps}
        for shrink in (1, 2, 4):
            sizes.add(max(self.steps_per_tick // shrink, 2))
        return tuple(sorted(sizes))

    def compile_variant_space(self) -> dict[str, list[dict]]:
        """The DECLARED compile-variant space per jit family, derived from
        the same bucketing helpers the admission/decode paths call
        (``_prefill_width`` / ``_prior_bucket`` / ``tick_step_sizes`` /
        ADMIT_BUCKETS). ``sentio audit`` lowers every descriptor and gates
        the result against the committed manifest, so growing any of these
        sets is a deliberate, reviewable act."""
        window = self.max_pages_per_seq * self.page_size
        # reserve = min(max_new + 2, window // 2) >= 3, so admitted prompts
        # never exceed window - 3 tokens
        max_prompt = max(window - 3, 1)
        widths = sorted({self._prefill_width(n)
                         for n in range(1, max_prompt + 1)})
        pnbs = sorted({self._prior_bucket(b)
                       for b in range(1, self.max_pages_per_seq)})
        rows = list(self.ADMIT_BUCKETS)
        space: dict[str, list[dict]] = {
            "paged.step_n": [{"steps": s} for s in self.tick_step_sizes()],
            "paged.merge_admitted": [{"rows": r} for r in rows],
            "paged.prefill_scatter": [
                {"width": w, "rows": r} for w in widths for r in rows
            ],
            # radix-hit admission: suffix width x prior bucket x row bucket,
            # always sampling the first token
            "paged.prior_prefill_scatter": [
                {"width": w, "pnb": p, "rows": r, "do_sample": True}
                for w in widths for p in pnbs for r in rows
            ],
        }
        if self.prefill_chunk is not None:
            # chunked segments dispatch one row at a time; non-final
            # segments skip sampling and the first segment may have no
            # prior at all (pnb 0)
            seg_widths = sorted({self._prefill_width(n)
                                 for n in range(1, self.prefill_chunk + 1)})
            space["paged.prior_prefill_scatter"] += [
                {"width": w, "pnb": p, "rows": 1, "do_sample": False}
                for w in seg_widths for p in [0] + pnbs
            ]
        if self.draft_params is not None:
            # the draft always prefills the FULL prompt, width clamped to
            # its cache window
            full_widths = sorted({min(self._prefill_width(n), window)
                                  for n in range(1, max_prompt + 1)})
            space["paged.draft_prefill"] = [
                {"width": w, "rows": r} for w in full_widths for r in rows
            ]
            space["paged_spec.spec_tick"] = [
                {"steps": s} for s in self.tick_step_sizes()
            ]
        return space

    def _match_radix(self, tok_ids: Sequence[int]):
        """Longest-prefix match against the radix cache, clamped so at
        least one suffix token remains to prefill (the first sampled token
        comes from the last prompt logit). → (shared, pages, node)."""
        if self._radix is None or self._radix.empty:
            return 0, [], None
        matched, pages, node = self._radix.match(tok_ids)
        max_shared = ((len(tok_ids) - 1) // self.page_size) * self.page_size
        if matched > max_shared:
            matched = max_shared
            pages = pages[: matched // self.page_size]
        if matched <= 0:
            return 0, [], None
        return matched, pages, node

    def _radix_insert(self, slot_idx: int, tok_ids, shared: int) -> None:
        """Move slot ``slot_idx``'s freshly prefilled full-page prompt span
        ``[shared, full)`` into the radix cache. Donated pages change owner
        (retire no longer frees them); the slot re-pins the deepest node so
        eviction can't touch pages its table references. Must run AFTER the
        dispatch that writes those pages — matches by later admissions are
        then ordered behind the write on device."""
        if self._radix is None:
            return
        slot = self.slots[slot_idx]
        full = (len(tok_ids) // self.page_size) * self.page_size
        if full <= shared:
            return
        own = slot.pages[: (full - shared) // self.page_size]
        node, donated = self._radix.insert(list(tok_ids[:full]), shared, own)
        slot.donated.extend(donated)
        if node is not None and node is not slot.prefix_node:
            self._radix.lock(node)
            self._radix.unlock(slot.prefix_node)
            slot.prefix_node = node

    def _admit(self) -> None:
        free = self._free_slot_indices()
        if not free or not self._queue:
            return

        batch: list[tuple[int, _Request, list[int], int]] = []
        now = time.perf_counter()
        qi = 0
        while qi < len(self._queue) and free:
            req = self._queue[qi]
            if req.deadline_ts is not None and now >= req.deadline_ts:
                # caller's deadline passed while queued: drop BEFORE paying
                # prefill — the result surfaces so the layer above can close
                # out its waiter with a typed deadline error
                self._queue.pop(qi)
                if qi == 0:
                    self._head_skips = 0
                self._finished_buffer.append(PagedResult(
                    request_id=req.request_id, text="", tokens=[],
                    prompt_tokens=0, finish_reason="expired",
                ))
                continue
            if req.tok_ids is None:
                prompt_ids = self.tokenizer.encode(req.prompt, add_bos=True)
                # budget split inside the per-sequence page window:
                # generation gets its requested tokens up to HALF the window
                # (else decode retires on out_of_pages after window - prompt
                # tokens); the prompt always keeps at least the other half,
                # so a huge max_new can never silently truncate most of the
                # context. A prior-prefix admission (resume-by-replay)
                # counts the prior toward the reserve — max_new + len(prior)
                # equals the ORIGINAL request's max_new, so the prompt
                # truncates exactly as it did at first admission and the
                # resumed context is byte-identical up to the splice.
                window = self.max_pages_per_seq * self.page_size
                prior = req.prior_tokens or []
                reserve = min(req.max_new + len(prior) + 2, window // 2)
                req.tok_ids = prompt_ids[: window - reserve] + list(prior)
                if req.seed is not None:
                    # fold the caller's seed into the ENGINE-SHARED RNG
                    # once, at first admission scan. Best-effort seeding:
                    # with concurrent sampled traffic the shared stream's
                    # position depends on tick interleaving, so this pins
                    # draws only for a lone sampled request (the resumed
                    # continuation's correctness does not depend on it —
                    # it conditions on the replayed prefix either way)
                    import jax

                    self._rng = jax.random.fold_in(
                        self._rng, int(req.seed) & 0x7FFFFFFF)
            tok_ids = req.tok_ids
            # radix-cache hit: longest page-aligned prefix of this prompt
            # already in the pool → the table reuses those pages read-only
            # and only the unmatched suffix prefills
            cache_live = self._radix is not None and not self._radix.empty
            shared, match_pages, match_node = self._match_radix(tok_ids)
            # speculation headroom: a verify block writes KV for up to
            # spec_k+1 positions past the accepted length before acceptance
            # is known — those writes need real pages behind them
            spec_head = (self.spec_k + 1) if self._spec_tick is not None else 0

            def pages_needed(sh: int) -> int:
                return min(
                    (len(tok_ids) - sh + req.max_new + spec_head
                     + self.page_size - 1) // self.page_size,
                    self.max_pages_per_seq - sh // self.page_size,
                )

            need_total = pages_needed(shared)
            if need_total > self.allocator.free_pages and self._radix is not None:
                # reclaim LRU unpinned cached prefixes; the match may have
                # walked nodes the eviction just freed, so rematch after
                if self._radix.evict(need_total - self.allocator.free_pages):
                    shared, match_pages, match_node = self._match_radix(tok_ids)
                    need_total = pages_needed(shared)
            if need_total > self.allocator.free_pages:
                # skip-ahead: a too-large request must not idle free slots
                # while smaller requests queue behind it (round-4 weak #3:
                # avg occupancy 2.95/8 with head-of-line FIFO). Starvation
                # bound: after head_skip_bound jumps the head reverts to
                # strict FIFO — nothing admits past it until its pages free.
                if qi == 0 and self._head_skips >= self.head_skip_bound:
                    break
                qi += 1
                continue
            pages = self.allocator.alloc(need_total)
            slot_idx = free.pop(0)
            self._queue.pop(qi)
            if qi == 0:
                self._head_skips = 0
            else:
                self._head_skips += 1
            # counted per ADMISSION (not per scan attempt — skip-ahead may
            # examine a queued request many times before it admits). Hits/
            # misses count only against a non-empty cache (the very first
            # admission has nothing to hit); token totals always accrue so
            # the hit ratio reflects the cold start honestly.
            if cache_live:
                if shared:
                    self.prefix_hits += 1
                else:
                    self.prefix_misses += 1
            if self._radix is not None:
                self.prefix_hit_tokens_total += shared
                self.prefix_miss_tokens_total += len(tok_ids) - shared
                self._radix.lock(match_node)
            chunked = (
                self.prefill_chunk is not None
                and len(tok_ids) - shared > self.prefill_chunk
            )
            if not chunked:
                batch.append((slot_idx, req, tok_ids, shared))
            slot = self.slots[slot_idx]
            slot.request_id = req.request_id
            slot.pages = pages
            slot.prompt_tokens = len(tok_ids)
            slot.length = len(tok_ids)
            slot.max_new = req.max_new
            slot.temperature = req.temperature
            slot.top_k = req.top_k
            slot.emitted = []
            slot.inflight_steps = 0
            slot.shared_tokens = shared
            slot.prefix_node = match_node
            slot.prompt_ids = list(tok_ids) if self._radix is not None else None
            slot.donated = []
            slot.submit_t = req.submit_t
            slot.prefill_todo = list(tok_ids[shared:]) if chunked else None
            slot.prefill_done = 0
            slot.active = True
            shared_blocks = shared // self.page_size
            row = np.zeros(self.max_pages_per_seq, np.int32)
            if shared_blocks:
                row[:shared_blocks] = match_pages
            row[shared_blocks : shared_blocks + len(pages)] = pages
            self._page_table[slot_idx] = row
            self._lens[slot_idx] = len(tok_ids)
            self._temps[slot_idx] = req.temperature
            self._top_ks[slot_idx] = req.top_k

        if not batch:
            return

        # batched admission: rows group by prefill-width bucket, each group
        # splits into batch-bucket chunks → admitting N same-width requests
        # costs ceil(N / max_batch_bucket) prefill dispatches, not N. The
        # sampled first tokens STAY ON DEVICE (slot.pending_first): the next
        # tick merges them into its token input and its single packed fetch
        # carries them back — admission adds zero host round trips.
        # rows with a prefix hit group by (suffix width, prior-page bucket)
        # — per-row prior lengths ride the dispatch as data, so different
        # match depths share one compiled program; cold rows keep the plain
        # path (identical dispatch to a cache-disabled engine)
        groups: dict[tuple[int, int], list] = {}
        for item in batch:
            shared = item[3]
            width = self._prefill_width(len(item[2]) - shared)
            pnb = self._prior_bucket(shared // self.page_size)
            groups.setdefault((width, pnb), []).append(item)
        max_rows = max(self.ADMIT_BUCKETS)
        for (width, pnb), members in sorted(groups.items()):
            for start in range(0, len(members), max_rows):
                chunk = members[start : start + max_rows]
                if pnb:
                    self._prefill_chunk_prior(width, pnb, chunk)
                else:
                    self._prefill_chunk(width, [m[:3] for m in chunk])
        if self._spec_tick is not None:
            self._draft_prefill_admitted(batch)

    def _draft_prefill_admitted(self, batch: list) -> None:
        """Fill the draft cache for freshly admitted slots — always over the
        FULL prompt (prefix-shared pages are target-side only), grouped by
        full-length width bucket like target admission."""
        self._ensure_draft_cache()
        # the draft cache window is max_pages_per_seq * page_size per row;
        # a bucketed width past it would make the [:width] update overhang
        # the cache axis and fail at trace time (prompts are already
        # truncated below the window at admission, so clamping is lossless)
        window = self.max_pages_per_seq * self.page_size
        groups: dict[int, list] = {}
        for slot_idx, _req, tok_ids, _shared in batch:
            width = min(self._prefill_width(len(tok_ids)), window)
            groups.setdefault(width, []).append((slot_idx, tok_ids))
        max_rows = max(self.ADMIT_BUCKETS)
        for width, members in sorted(groups.items()):
            for start in range(0, len(members), max_rows):
                chunk = members[start : start + max_rows]
                rows = bucket_size(len(chunk), self.ADMIT_BUCKETS)
                ids = np.full((rows, width), self.tokenizer.pad_id, np.int32)
                lens = np.ones(rows, np.int32)
                rows_idx = np.full(rows, self.max_slots, np.int32)  # pad→drop
                for r, (slot_idx, tok_ids) in enumerate(chunk):
                    ids[r, : len(tok_ids)] = tok_ids
                    lens[r] = len(tok_ids)
                    rows_idx[r] = slot_idx
                with self._phase.phase("prefill_dispatch"):
                    self._spec_dk, self._spec_dv = self._draft_prefill(
                        self.draft_params, ids, self._spec_dk, self._spec_dv,
                        rows_idx, lens,
                    )

    def _assemble_prefill(self, rows_data, width: int, pos_offset: int = 0):
        """Build the padded admission arrays ONE way for every prefill
        flavor. rows_data: [(token_ids, temperature, top_k, pages)]. Pad
        rows and unused scatter blocks point at scratch page 0; args stay
        host numpy (a jit call ships them asynchronously, while an explicit
        jnp.asarray is a SYNCHRONOUS upload — ~RTT each on remote-attached
        devices)."""
        rows = bucket_size(len(rows_data), self.ADMIT_BUCKETS)
        nb = width // self.page_size
        ids = np.full((rows, width), self.tokenizer.pad_id, np.int32)
        lens = np.ones(rows, np.int32)
        temps = np.zeros(rows, np.float32)
        top_ks = np.zeros(rows, np.int32)
        scat = np.zeros((rows, nb), np.int32)
        for r, (tok_ids, temp, top_k, pages) in enumerate(rows_data):
            ids[r, : len(tok_ids)] = tok_ids
            lens[r] = len(tok_ids)
            temps[r] = temp
            top_ks[r] = top_k
            used = (len(tok_ids) + self.page_size - 1) // self.page_size
            scat[r, :used] = pages[:used]
        positions = (
            pos_offset
            + np.broadcast_to(
                np.arange(width, dtype=np.int32)[None, :], (rows, width)
            )
        ).astype(np.int32)
        return ids, lens, temps, top_ks, scat, positions

    def _prefill_chunk(
        self, width: int, chunk: list[tuple[int, _Request, list[int]]]
    ) -> None:
        """One prefill+scatter+sample dispatch for up to max(ADMIT_BUCKETS)
        same-width-bucket rows (rows pad up to a batch bucket)."""
        faults.hit("paged.admit_scatter")
        ids, lens, temps, top_ks, scat, positions = self._assemble_prefill(
            [(tok_ids, req.temperature, req.top_k, self.slots[slot_idx].pages)
             for slot_idx, req, tok_ids in chunk],
            width,
        )
        with self._phase.phase("prefill_dispatch"):
            first, first_lp, self.pool.k, self.pool.v, self._rng = \
                self._prefill_scatter(
                    self.params, ids, positions, lens, self._rng, temps, scat,
                    self.pool.k, self.pool.v, top_ks,
                )
        self.prefill_tokens_total += sum(len(t) for _i, _r, t in chunk)
        slot_idxs = [slot_idx for slot_idx, _req, _ids in chunk]
        for slot_idx in slot_idxs:
            self.slots[slot_idx].pending_first = True
        self._pending_first.append((first, first_lp, slot_idxs))
        # the dispatch above writes these rows' full prompt KV — their
        # full-page spans now seed the radix cache for later requests
        for slot_idx, _req, tok_ids in chunk:
            self._radix_insert(slot_idx, tok_ids, 0)

    def _prefill_chunk_prior(self, width: int, pnb: int, chunk: list) -> None:
        """Suffix-only admission for radix-cache hits: ids/positions/scatter
        cover ONLY the unmatched tokens; the compiled fn primes each row's
        cache from its matched prefix pages (per-row table padded to the
        ``pnb`` page bucket with scratch page 0, per-row true prior lengths
        riding as data)."""
        faults.hit("paged.admit_scatter")
        rows_data = []
        n_prior = []
        for slot_idx, req, tok_ids, shared in chunk:
            rows_data.append(
                (tok_ids[shared:], req.temperature, req.top_k,
                 self.slots[slot_idx].pages)
            )
            n_prior.append(shared)
        rows = bucket_size(len(chunk), self.ADMIT_BUCKETS)
        n_prior = np.asarray(n_prior + [0] * (rows - len(chunk)), np.int32)
        prior_tables = np.zeros((rows, pnb), np.int32)
        for r, (slot_idx, _req, _t, shared) in enumerate(chunk):
            sb = shared // self.page_size
            prior_tables[r, :sb] = self._page_table[slot_idx, :sb]
        ids, lens, temps, top_ks, scat, positions = self._assemble_prefill(
            rows_data, width, pos_offset=n_prior[:, None],
        )
        with self._phase.phase("prefill_dispatch"):
            first, first_lp, self.pool.k, self.pool.v, self._rng = \
                self._prior_prefill_scatter(
                    self.params, ids, positions, lens, self._rng, temps, scat,
                    self.pool.k, self.pool.v, prior_tables, n_prior, top_ks,
                    do_sample=True,
                )
        self.prefill_tokens_total += sum(len(t) - s for _i, _r, t, s in chunk)
        slot_idxs = [slot_idx for slot_idx, _req, _ids, _sh in chunk]
        for slot_idx in slot_idxs:
            self.slots[slot_idx].pending_first = True
        self._pending_first.append((first, first_lp, slot_idxs))
        for slot_idx, _req, tok_ids, shared in chunk:
            self._radix_insert(slot_idx, tok_ids, shared)

    def _advance_prefill(self) -> None:
        """Dispatch ONE chunked-prefill segment per tick (bounding how much
        prefill latency any single tick adds to live decodes). The slot with
        the OLDEST submit time goes first — index order would let a steady
        stream of long prompts landing in lower slots starve a higher one
        indefinitely while it pins its pages."""
        waiting = [
            (slot.submit_t, i) for i, slot in enumerate(self.slots)
            if slot.active and slot.prefill_todo is not None
        ]
        for _, i in sorted(waiting):
            slot = self.slots[i]
            chunk = self.prefill_chunk
            seg = slot.prefill_todo[:chunk]
            is_last = len(slot.prefill_todo) <= chunk
            prior = slot.shared_tokens + slot.prefill_done
            width = self._prefill_width(len(seg))
            # the segment's own pages start right after the prior blocks in
            # this slot's table (prior is page-aligned: shared and every
            # non-final segment are page multiples)
            pb = prior // self.page_size
            nb = (len(seg) + self.page_size - 1) // self.page_size
            seg_pages = self._page_table[i, pb : pb + nb].tolist()
            n_prior = np.asarray([prior], np.int32)
            ids, lens, temps, top_ks, scat, positions = self._assemble_prefill(
                [(seg, slot.temperature, slot.top_k, seg_pages)], width,
                pos_offset=n_prior[:, None],
            )
            # prior-table width buckets to a power-of-two page count (padded
            # with scratch page 0) so an 8K prompt compiles O(log window)
            # segment variants, not one per (prior, width) pair
            pnb = self._prior_bucket(pb)
            prior_table = np.zeros((1, pnb), np.int32)
            prior_table[0, :pb] = self._page_table[i, :pb]
            with self._phase.phase("prefill_dispatch"):
                first, first_lp, self.pool.k, self.pool.v, self._rng = \
                    self._prior_prefill_scatter(
                        self.params, ids, positions, lens, self._rng, temps,
                        scat, self.pool.k, self.pool.v, prior_table,
                        n_prior, top_ks, do_sample=is_last,
                    )
            self.prefill_tokens_total += len(seg)
            if is_last:
                slot.prefill_todo = None
                slot.pending_first = True
                self._pending_first.append((first, first_lp, [i]))
                # the final segment completes the prompt's KV — its
                # full-page span can now enter the radix cache
                self._radix_insert(i, slot.prompt_ids, slot.shared_tokens)
            else:
                slot.prefill_todo = slot.prefill_todo[chunk:]
                slot.prefill_done += len(seg)
            return

    def _dispatch_tick(self) -> Optional[dict]:
        """Compute per-row budgets, merge freshly admitted rows into the
        device-carried decode state, and dispatch ONE fused multi-step scan.
        No host fetch happens here — the returned record is harvested later
        (immediately at pipeline depth 1, one step() later at depth 2)."""
        pending, self._pending_first = self._pending_first, []
        remaining = np.zeros(self.max_slots, np.int32)
        for i, slot in enumerate(self.slots):
            if not slot.active:
                continue
            if slot.prefill_todo is not None:
                continue  # mid-chunked-prefill: no decode budget, no retire
            capacity = slot.shared_tokens + len(slot.pages) * self.page_size
            # a pending (still-on-device) first token and any sub-steps
            # already granted to an unharvested tick count against the
            # budget exactly as if they had been folded
            base_emit = (
                len(slot.emitted) + slot.inflight_steps
                + (1 if slot.pending_first else 0)
            )
            written = slot.length + slot.inflight_steps
            # spec mode reserves verify-block headroom inside capacity.
            # Admission over-allocates by the same amount, EXCEPT when the
            # request already hits the max_pages_per_seq window — there the
            # headroom comes out of the emission budget, so window-limited
            # requests finish up to spec_k+1 tokens earlier than the plain
            # engine would (documented in runtime/paged_spec.py)
            spec_head = (self.spec_k + 1) if self._spec_tick is not None else 0
            remaining[i] = max(
                min(slot.max_new - base_emit,
                    capacity - 1 - spec_head - written), 0
            )
            if (remaining[i] == 0 and not slot.pending_first
                    and slot.inflight_steps == 0):
                # defensive: a zero-budget row with nothing in flight can't
                # progress
                self._finished_buffer.append(self._retire(i, "length"))
        # adaptive tick size, scaled by backlog depth: waiting requests
        # (engine queue + the serving layer's inbox, via pressure_hint) cap
        # the tick so admission waits fewer decode sub-steps the deeper the
        # backlog grows — freed slots refill at tick boundaries, so shorter
        # ticks under pressure directly cut queueing delay (round-4 weak #3:
        # 9.6x p95/p50 tail with the old two-size switch). An idle queue
        # runs the big tick so long generations cost few fetches. Each
        # distinct step count is its own compiled variant; the pressured
        # ladder is capped at 3 sizes (+1 idle) to bound compilations —
        # ``tick_step_sizes()`` declares exactly this set for the audit.
        waiting = len(self._queue)
        if self.pressure_hint is not None:
            waiting += int(self.pressure_hint())
        if waiting == 0:
            steps = self.max_tick_steps
        else:
            shrink = 1 << min(waiting // max(self.max_slots, 1), 2)  # 1, 2, 4
            steps = max(self.steps_per_tick // shrink, 2)
        if self.force_tick_steps in self.tick_step_sizes():
            steps = self.force_tick_steps  # warmup rung pin, never off-ladder
        budgets = np.minimum(remaining, steps).astype(np.int32)
        pending_slots = [i for _f, _lp, idxs in pending for i in idxs
                         if self.slots[i].active]
        # rows sharing THIS fused dispatch — the honest occupancy number
        # (post-tick slot counts miss requests that retire inside the tick)
        self.last_tick_active = int(
            ((budgets > 0) | [s.active and s.pending_first for s in self.slots]).sum()
        )
        if not budgets.any():
            if not pending_slots:
                return None
            # nothing can decode but deferred first tokens need folding
            # (e.g. a max_new_tokens=1 burst): fetch them directly instead
            # of dispatching a fully-masked scan that would stream the
            # weights steps-many times just to echo the inputs back
            for first_dev, first_lp_dev, slot_idxs in pending:
                # a direct fetch of not-yet-ready device arrays BLOCKS —
                # this is device wait, not dispatch cost
                with self._phase.phase("device_wait"):
                    vals = np.asarray(first_dev)
                    lps = np.asarray(first_lp_dev)
                for r, i in enumerate(slot_idxs):
                    if not self.slots[i].active:
                        continue
                    self.slots[i].pending_first = False
                    self._note_ttft(self.slots[i])
                    self._last_tok[i] = int(vals[r])
                    self._lp_sum[i] = lps[r]
                    self._lp_min[i] = lps[r]
                    self._lp_cnt[i] = 1
                    result = self._fold_and_maybe_retire(i)
                    if result is not None:
                        self._finished_buffer.append(result)
            return None

        # decode state rides ON DEVICE, threaded from the previous tick's
        # outputs (host mirrors seed the first tick); admission's device-
        # resident first tokens / prompt lengths scatter in via the jitted
        # merge. Jit dispatches are async; eager index-update ops and
        # explicit jnp.asarray uploads each block ~RTT on remote devices.
        if self._dev_state is None:
            tok_in = self._last_tok.copy()
            lens_in = self._lens.copy()
            halted_in = np.zeros(self.max_slots, bool)
            lp_sum_in = self._lp_sum.copy()
            lp_min_in = self._lp_min.copy()
            lp_cnt_in = self._lp_cnt.copy()
        else:
            (tok_in, lens_in, halted_in,
             lp_sum_in, lp_min_in, lp_cnt_in) = self._dev_state
        for first_dev, first_lp_dev, slot_idxs in pending:
            idxs = np.full(first_dev.shape[0], self.max_slots, np.int32)
            idxs[: len(slot_idxs)] = slot_idxs
            new_lens = np.zeros(first_dev.shape[0], np.int32)
            new_lens[: len(slot_idxs)] = [
                self.slots[i].length for i in slot_idxs
            ]
            (tok_in, lens_in, halted_in,
             lp_sum_in, lp_min_in, lp_cnt_in) = self._merge_admitted(
                tok_in, lens_in, halted_in, lp_sum_in, lp_min_in, lp_cnt_in,
                first_dev, first_lp_dev, new_lens, idxs
            )

        if self._spec_tick is not None:
            self._ensure_draft_cache()
            packed, tok_out, lens_out, halted_out, self.pool.k, self.pool.v, \
                self._spec_dk, self._spec_dv, self._rng = self._spec_tick(
                    self.params, self.draft_params, tok_in, lens_in,
                    halted_in, self._page_table.copy(), self.pool.k,
                    self.pool.v, self._spec_dk, self._spec_dv, self._rng,
                    self._temps.copy(), budgets,
                    # + k + 1 slack: dynamic_update_slice CLAMPS a start
                    # index whose k+1-wide update would overhang, silently
                    # corrupting the tail rounds' token offsets otherwise
                    k=self.spec_k, out_w=int(steps) + self.spec_k + 1,
                )
            spec = True
            # the spec tick has its own accept/correct rule and samples no
            # per-token logprobs; the accumulators thread through UNCHANGED
            # (stale first-token seeds) and the host mirrors stay zeroed, so
            # spec results report logprob_count == 0 — the confidence gate
            # reads that as "no signal" and never skips verify on spec mode
            lp_state = None
            lp_sum_out, lp_min_out, lp_cnt_out = lp_sum_in, lp_min_in, lp_cnt_in
        else:
            (packed, lp_state, tok_out, lens_out, halted_out,
             lp_sum_out, lp_min_out, lp_cnt_out,
             self.pool.k, self.pool.v, self._rng) = self._step_n(
                self.params,
                tok_in,
                lens_in,
                halted_in,
                self._page_table.copy(),
                self.pool.k,
                self.pool.v,
                self._rng,
                self._temps.copy(),
                self._top_ks.copy(),
                budgets,
                lp_sum_in,
                lp_min_in,
                lp_cnt_in,
                steps=steps,
            )
            self.total_sub_steps += steps
            spec = False
        self._dev_state = (tok_out, lens_out, halted_out,
                           lp_sum_out, lp_min_out, lp_cnt_out)
        for i, slot in enumerate(self.slots):
            if slot.active:
                slot.inflight_steps += int(budgets[i])
        return {"packed": packed, "budgets": budgets, "spec": spec,
                "lp_state": lp_state,
                "pending_slots": set(pending_slots),
                # request ids pin each lane: a slot retired at harvest time
                # and re-admitted before THIS record is harvested must not
                # have the old request's speculative tokens replayed into it
                "rids": [s.request_id for s in self.slots]}

    def _harvest(self, record: dict) -> list[PagedResult]:
        """Fetch a dispatched tick's packed tokens ([1 + steps, B] — the ONE
        host fetch per tick) and replay the device scan host-side: each
        executed sub-step is exactly one old-style tick — write counted,
        token folded, retirement checked. Execution-mask reconstruction: a
        row runs until its budget (host-known) or the step after its first
        EOS (visible in packed) — identical to the device's halting rule."""
        budgets = record["budgets"]
        packed = np.asarray(record["packed"])
        spec = record.get("spec", False)
        # the tick's final logprob accumulators ([3, B]: sum / min / count),
        # one fetch riding the same dispatch as the packed tokens; refreshed
        # into the host mirrors so a retire inside this harvest reports the
        # request's full-trajectory confidence signal
        lp_state = record.get("lp_state")
        lp_rows = np.asarray(lp_state) if lp_state is not None else None
        finished: list[PagedResult] = []
        for i, slot in enumerate(self.slots):
            if not slot.active or slot.request_id != record["rids"][i]:
                continue  # lane retired+reused since dispatch: stale tokens
            consumed = int(budgets[i])
            if consumed or i in record["pending_slots"]:
                slot.inflight_steps = max(slot.inflight_steps - consumed, 0)
            else:
                continue
            if lp_rows is not None:
                self._lp_sum[i] = lp_rows[0, i]
                self._lp_min[i] = lp_rows[1, i]
                self._lp_cnt[i] = int(lp_rows[2, i])
            if slot.pending_first and i in record["pending_slots"]:
                slot.pending_first = False
                self._note_ttft(slot)
                echo = packed[i, 0] if spec else packed[0, i]
                self._last_tok[i] = int(echo)
                result = self._fold_and_maybe_retire(i)
                if result is not None:
                    finished.append(result)
                    continue
            if spec:
                # spec packed row: [echo, emitted_n, verifies, tokens...] —
                # the device already applied budgets and EOS truncation;
                # fold exactly what it emitted. total_sub_steps counts
                # emitted tokens (the spec analogue of decode sub-steps)
                n = int(packed[i, 1])
                toks = packed[i, 3 : 3 + n]
                self.total_sub_steps += n
                self.spec_emitted_total += n
                self.spec_verifies_total += int(packed[i, 2])
            else:
                n = consumed
                toks = packed[1 : 1 + n, i]
            for s in range(n):
                slot.length += 1
                self._lens[i] = slot.length
                self._last_tok[i] = int(toks[s])
                result = self._fold_and_maybe_retire(i)
                if result is not None:
                    finished.append(result)
                    break
        return finished

    def _fold_and_maybe_retire(self, i: int) -> Optional[PagedResult]:
        """Fold ``_last_tok[i]`` (sampled, not yet forwarded) into slot ``i``;
        retire on EOS / token budget / page capacity. The ONE place the
        retirement conditions live — admission-time and decode-replay paths
        must never diverge, and the decode budgets mirror these bounds."""
        slot = self.slots[i]
        tok = int(self._last_tok[i])
        self.decode_tokens_total += 1
        hit_eos = tok == self.tokenizer.eos_id and not self.ignore_eos
        if not hit_eos:
            slot.emitted.append(tok)
        hit_len = len(slot.emitted) >= slot.max_new
        capacity = slot.shared_tokens + len(slot.pages) * self.page_size
        out_of_pages = slot.length + 1 >= capacity
        if hit_eos or hit_len or out_of_pages:
            return self._retire(i, "stop" if hit_eos else "length")
        return None

    def _note_ttft(self, slot: _Slot) -> None:
        """Called exactly where pending_first flips False — the moment the
        first sampled token is host-visible (deferred-fetch admission means
        prefill alone does NOT make it visible)."""
        if slot.submit_t > 0.0:
            self.ttft_samples.append(time.perf_counter() - slot.submit_t)
            self.ttft_count += 1

    def _retire(self, i: int, reason: str) -> PagedResult:
        """Free a slot's pages (minus any donated to the radix cache), drop
        its prefix pins, and zero its device-mirror row."""
        slot = self.slots[i]
        result = PagedResult(
            request_id=slot.request_id,
            text=self.tokenizer.decode(slot.emitted),
            tokens=list(slot.emitted),
            prompt_tokens=slot.prompt_tokens,
            finish_reason=reason,
            prefill_tokens=slot.prompt_tokens - slot.shared_tokens,
            prefix_hit_tokens=slot.shared_tokens,
            logprob_sum=float(self._lp_sum[i]),
            logprob_min=float(self._lp_min[i]),
            logprob_count=int(self._lp_cnt[i]),
        )
        if slot.donated:
            donated = set(slot.donated)
            self.allocator.free([p for p in slot.pages if p not in donated])
        else:
            self.allocator.free(slot.pages)
        if self._radix is not None:
            self._radix.unlock(slot.prefix_node)
        slot.prefix_node = None
        slot.prompt_ids = None
        slot.donated = []
        slot.active = False
        slot.pending_first = False
        slot.inflight_steps = 0
        slot.pages = []
        slot.shared_tokens = 0
        slot.prefill_todo = None
        slot.prefill_done = 0
        self._page_table[i] = 0
        self._lens[i] = 0
        self._temps[i] = 0.0
        self._top_ks[i] = 0
        self._last_tok[i] = 0
        self._lp_sum[i] = 0.0
        self._lp_min[i] = 0.0
        self._lp_cnt[i] = 0
        return result

    # ---------------------------------------------------------------- stats

    def stats(self) -> dict:
        active = sum(s.active for s in self.slots)
        out = {
            "active_slots": active,
            "max_slots": self.max_slots,
            "queued": len(self._queue),
            "free_pages": self.allocator.free_pages,
            "total_pages": self.allocator.num_pages,
            "page_size": self.page_size,
            "kv_quant": self.kv_quant,
            "pool_hbm_bytes": self.pool.hbm_bytes,
            "head_skips": self._head_skips,
            "ttft_count": self.ttft_count,
            "prefill_tokens": self.prefill_tokens_total,
            "decode_tokens": self.decode_tokens_total,
        }
        if self._radix is not None:
            hit, miss = self.prefix_hit_tokens_total, self.prefix_miss_tokens_total
            out["prefix_hits"] = self.prefix_hits
            out["prefix_misses"] = self.prefix_misses
            out["prefix_hit_tokens"] = hit
            out["prefix_miss_tokens"] = miss
            if hit + miss:
                out["prefix_hit_token_ratio"] = round(hit / (hit + miss), 4)
            out["prefix_cache_pages"] = self._radix.pages_held
            out["prefix_cache_nodes"] = self._radix.node_count
        if self.ttft_samples:
            s = sorted(self.ttft_samples)
            out["ttft_p50_ms"] = round(s[len(s) // 2] * 1e3, 2)
            out["ttft_p95_ms"] = round(s[int(len(s) * 0.95)] * 1e3, 2)
        if self.spec_verifies_total:
            out["spec_tokens_per_verify"] = round(
                self.spec_emitted_total / self.spec_verifies_total, 2
            )
            out["spec_verifies"] = self.spec_verifies_total
            out["spec_emitted"] = self.spec_emitted_total
        return out
