"""Checkpoint → live model resolution for the serving stack.

The reference has no weights anywhere — its models are remote APIs keyed by
env credentials (settings.py:27-191 there picks providers/urls). Here the
equivalent configuration surface is a *checkpoint path* per model family
(generator, embedder, reranker): ``cli convert`` writes framework
checkpoints (runtime/checkpoint.py format, meta carrying the model family
and config), and this module loads them back into (params, model_config,
tokenizer) triples for the constructors in ops/ and runtime/engine.py.

Resolution order per model (mirrors the reference's provider-selection
semantics, factory.py:20-27 there, with its mock-mode fallback):

1. ``checkpoint_path`` set → load params + config from the checkpoint;
   tokenizer from ``tokenizer_path`` (a local HF tokenizer dir — usually
   the original HF checkpoint dir) when given.
2. No path → random-init at the preset size (the deterministic fake-model
   mode tests and offline dev run on, SURVEY.md §4).
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from sentio_tpu.runtime.checkpoint import CheckpointError, load_pytree

logger = logging.getLogger(__name__)

_FAMILY_CONFIGS = {
    "llama": ("sentio_tpu.models.llama", "LlamaConfig"),
    "moe": ("sentio_tpu.models.moe", "MoeConfig"),
    "encoder": ("sentio_tpu.models.transformer", "EncoderConfig"),
    "cross-encoder": ("sentio_tpu.models.transformer", "EncoderConfig"),
}


class WeightsError(Exception):
    pass


def load_model(
    checkpoint_path: str,
    expect_family: Optional[str] = None,
    tokenizer_path: str = "",
    mmap: bool = False,
) -> tuple[Any, Any, Optional[Any]]:
    """→ (params, model_config, tokenizer|None) from a ``cli convert`` /
    ``save_pytree`` checkpoint. The meta's recorded config reconstructs the
    exact dataclass the weights were converted for — a preset mismatch
    cannot silently produce shape errors deep in the first forward pass.
    ``mmap=True`` memory-maps the param leaves in place (process-mode
    replica workers share one page-cache copy per host)."""
    try:
        params, meta = load_pytree(checkpoint_path, mmap=mmap)
    except CheckpointError as exc:
        raise WeightsError(f"cannot load checkpoint {checkpoint_path!r}: {exc}") from exc

    family = meta.get("family")
    if expect_family and family and family != expect_family:
        raise WeightsError(
            f"checkpoint {checkpoint_path!r} holds a {family!r} model, "
            f"expected {expect_family!r}"
        )
    cfg_dict = meta.get("config")
    if not cfg_dict:
        raise WeightsError(f"checkpoint {checkpoint_path!r} has no config in meta")
    lookup = family or expect_family
    if lookup not in _FAMILY_CONFIGS:
        raise WeightsError(f"unknown model family {lookup!r} in {checkpoint_path!r}")
    mod_name, cls_name = _FAMILY_CONFIGS[lookup]
    import importlib

    cfg_cls = getattr(importlib.import_module(mod_name), cls_name)
    # tuples serialize as lists in JSON meta; convert back for fields whose
    # annotation is a tuple type so frozen configs stay hashable
    fields = {f.name: f.type for f in cfg_cls.__dataclass_fields__.values()}  # type: ignore[attr-defined]
    kwargs = {}
    for k, v in cfg_dict.items():
        if k not in fields:
            continue
        ann = str(fields[k]).lower()
        if isinstance(v, list) and ("tuple" in ann):
            v = tuple(v)
        kwargs[k] = v
    model_config = cfg_cls(**kwargs)

    tokenizer = None
    if tokenizer_path:
        from sentio_tpu.models.tokenizer import HFTokenizer

        tokenizer = HFTokenizer(tokenizer_path)
        if tokenizer.vocab_size > model_config.vocab_size:
            raise WeightsError(
                f"tokenizer at {tokenizer_path!r} has vocab {tokenizer.vocab_size} "
                f"> model vocab {model_config.vocab_size}"
            )
    logger.info(
        "loaded %s checkpoint from %s (dim=%s, layers=%s)",
        lookup, checkpoint_path, getattr(model_config, "dim", "?"),
        getattr(model_config, "n_layers", "?"),
    )
    return params, model_config, tokenizer
