"""Duty-cycle autoscaler: a load-following policy loop over the replica set.

The replica tier already exposes the HPA-style saturation signal — every
replica pushes duty-cycle fractions (host/device/idle) and backlog depth
through its status/telemetry frames, and ``ReplicaSet.fleet_load()`` folds
them into a single cached sample (zero RPCs at poll cadence). This module
closes the loop:

``AutoscalePolicy``
    A pure decision kernel — ``observe()`` accumulates (busy, backlog)
    samples over a sliding ``window_s``; ``decide()`` returns ``"out"`` /
    ``"in"`` / ``None`` with a reason. Sustained busy fraction or backlog
    fraction above the scale-out threshold grows the fleet; sustained
    idle below the scale-in threshold shrinks it. Hysteresis is the gap
    between the two thresholds (the constructor clamps ``in_busy <=
    out_busy``), and each direction has its own cooldown — scale-in
    additionally measures from the *last change in either direction* so
    an out→in flap cannot happen inside ``in_cooldown_s``. Min/max
    bounds clamp every decision. No clocks, no threads: fully
    unit-testable with synthetic timestamps.

``Autoscaler``
    The actuator thread (role ``autoscaler``, thread name
    ``fleet-autoscaler``): samples the set, feeds the policy, and acts —
    scale-out through a pluggable *launcher seam* (a zero-arg callable;
    local fleets spawn a socket worker that dials the registry with the
    elastic-join sentinel slot ``-1``, remote fleets just register on
    their own), scale-in by retiring the most-idle serving replica via
    ``ReplicaSet.retire()`` (drain + handoff + token-exact stream
    completion — see replica.py). Every decision is a flight-recorder
    event plus ``sentio_tpu_autoscale_decisions_total{direction,reason}``.

The whole subsystem is inert by default: ``serve/dependencies.py`` only
constructs an ``Autoscaler`` when ``AUTOSCALE=1``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Optional

from sentio_tpu.analysis.sanitizer import make_lock
from sentio_tpu.infra.metrics import get_metrics

logger = logging.getLogger(__name__)

__all__ = ["AutoscalePolicy", "Autoscaler", "socket_worker_launcher"]


class AutoscalePolicy:
    """Pure scale-out/scale-in decision kernel (no clocks, no threads).

    Callers own the clock: pass the same monotonic ``now`` to
    ``observe()`` and ``decide()``. A decision is only actionable once
    the sample window has real coverage (span >= 80% of ``window_s``),
    so a single hot poll after startup or after a scale event (which
    clears the window — old samples describe the old fleet) can never
    trigger a flap.
    """

    def __init__(
        self,
        min_replicas: int = 1,
        max_replicas: int = 4,
        window_s: float = 15.0,
        out_busy: float = 0.75,
        in_busy: float = 0.15,
        out_backlog: float = 0.5,
        out_cooldown_s: float = 30.0,
        in_cooldown_s: float = 60.0,
    ) -> None:
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.window_s = max(float(window_s), 0.1)
        self.out_busy = min(max(float(out_busy), 0.0), 1.0)
        # hysteresis: the scale-in threshold can never meet or cross the
        # scale-out threshold, whatever the env knobs say
        self.in_busy = min(max(float(in_busy), 0.0), self.out_busy)
        self.out_backlog = min(max(float(out_backlog), 0.0), 1.0)
        self.out_cooldown_s = max(float(out_cooldown_s), 0.0)
        self.in_cooldown_s = max(float(in_cooldown_s), 0.0)
        # leaf lock: nothing is called while holding it. Tests drive the
        # policy from the caller thread while the autoscaler thread polls.
        self._mutex = make_lock("AutoscalePolicy._mutex")
        self._samples: deque = deque()  # guarded-by: _mutex
        self._last_out: Optional[float] = None  # guarded-by: _mutex
        self._last_change: Optional[float] = None  # guarded-by: _mutex

    def observe(self, now: float, busy_fraction: float,
                backlog_fraction: float) -> None:
        """Fold one fleet sample into the sliding window."""
        with self._mutex:
            self._samples.append((float(now), float(busy_fraction),
                                  float(backlog_fraction)))
            self._prune_locked(now)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()

    def note_scaled(self, now: float, direction: str) -> None:
        """Book an executed decision: start the cooldowns and clear the
        window (samples taken against the old fleet size say nothing
        about the new one)."""
        with self._mutex:
            if direction == "out":
                self._last_out = now
            self._last_change = now
            self._samples.clear()

    def decide(self, now: float, current_replicas: int) -> tuple:
        """Return ``("out"|"in", reason)`` or ``(None, reason)``."""
        with self._mutex:
            self._prune_locked(now)
            if len(self._samples) < 2:
                return None, "window_warming"
            span = self._samples[-1][0] - self._samples[0][0]
            if span + 1e-9 < self.window_s * 0.8:
                return None, "window_warming"
            busy = sum(s[1] for s in self._samples) / len(self._samples)
            backlog = sum(s[2] for s in self._samples) / len(self._samples)
            if busy >= self.out_busy or backlog >= self.out_backlog:
                if current_replicas >= self.max_replicas:
                    return None, "at_max"
                if self._last_out is not None and \
                        now - self._last_out < self.out_cooldown_s:
                    return None, "out_cooldown"
                return "out", ("busy" if busy >= self.out_busy
                               else "backlog")
            if busy <= self.in_busy and backlog <= self.out_backlog / 4.0:
                if current_replicas <= self.min_replicas:
                    return None, "at_min"
                if self._last_change is not None and \
                        now - self._last_change < self.in_cooldown_s:
                    return None, "in_cooldown"
                return "in", "idle"
            return None, "steady"

    def saturated(self, now: float) -> bool:
        """True when the windowed mean load sits at or above the
        scale-out thresholds (used for the at-max alert gauge)."""
        with self._mutex:
            self._prune_locked(now)
            if not self._samples:
                return False
            busy = sum(s[1] for s in self._samples) / len(self._samples)
            backlog = sum(s[2] for s in self._samples) / len(self._samples)
            return busy >= self.out_busy or backlog >= self.out_backlog


def socket_worker_launcher(address, spec) -> Callable[[], None]:
    """Launcher seam for local socket fleets: each call spawns one worker
    process that dials the registry at ``address`` with the elastic-join
    sentinel slot ``-1`` — the registry allocates a fresh slot, the
    membership source wires the replica in, and the autoscaler never
    touches the registration path itself. Remote fleets skip this seam
    entirely and just register."""
    def _launch() -> None:
        import multiprocessing

        from sentio_tpu.runtime.worker import worker_main_socket

        ctx = multiprocessing.get_context("spawn")
        proc = ctx.Process(  # lint: allow(no-fork) — spawn context
            target=worker_main_socket,
            args=(tuple(address), spec, -1),
            name="sentio-elastic-worker",
            daemon=True,
        )
        proc.start()
        logger.info("launched elastic worker pid=%s", proc.pid)

    return _launch


class Autoscaler:
    """Actuator thread gluing ``AutoscalePolicy`` to a ``ReplicaSet``.

    One poll = one ``step()``: sample ``fleet_load()``, feed the policy,
    and on a decision either invoke the launcher (scale-out) or retire
    the most-idle serving replica (scale-in). ``step()`` is public so
    drills and units can drive the loop with synthetic clocks instead of
    waiting out real cooldowns. In-flight launches count toward the
    max-replicas clamp until the worker actually joins (or
    ``launch_grace_s`` expires) — a slow compile+register must not let
    the policy re-fire past the bound. The loop thread is fully
    exception-guarded — a failed launch or a refused retire (e.g. the
    last-serving guard) is logged and retried at the next poll, never
    fatal."""

    def __init__(
        self,
        replica_set,
        policy: AutoscalePolicy,
        launcher: Optional[Callable[[], None]] = None,
        poll_interval_s: float = 1.0,
        launch_grace_s: float = 120.0,
    ) -> None:
        self._set = replica_set
        self._policy = policy
        self._launcher = launcher
        self.poll_interval_s = max(float(poll_interval_s), 0.05)
        self.launch_grace_s = max(float(launch_grace_s), 1.0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # leaf lock for the decision counters: step() may run on the
        # autoscaler thread or a drill's caller thread
        self._mutex = make_lock("Autoscaler._mutex")
        self._decisions = {"out": 0, "in": 0}  # guarded-by: _mutex
        self._skipped = 0  # guarded-by: _mutex
        self._pending_launches: list = []  # guarded-by: _mutex
        self._last_serving: Optional[int] = None  # guarded-by: _mutex

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True,
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the autoscaler must outlive any single bad pass
                logger.exception("autoscale pass failed")

    def step(self, now: Optional[float] = None) -> Optional[str]:
        """One observe→decide→act pass; returns the executed direction
        (``"out"``/``"in"``) or ``None``."""
        now = time.monotonic() if now is None else now
        load = self._set.fleet_load()
        self._policy.observe(now, load["busy"], load["backlog_fraction"])
        serving = int(load["serving"])
        with self._mutex:
            # a launched worker is invisible to fleet_load() until it
            # compiles, registers, and attaches (tens of seconds) — count
            # in-flight launches toward the bound, or the policy re-fires
            # every cooldown and storms past max_replicas. A serving-count
            # rise absorbs one pending entry per new replica; entries
            # older than launch_grace_s are presumed dead and dropped so
            # a failed launch can't pin the fleet below max forever.
            if self._last_serving is not None and \
                    serving > self._last_serving:
                del self._pending_launches[:serving - self._last_serving]
            self._last_serving = serving
            self._pending_launches = [
                t for t in self._pending_launches
                if now - t < self.launch_grace_s
            ]
            pending = len(self._pending_launches)
        effective = serving + pending
        direction, reason = self._policy.decide(now, effective)
        at_max = effective >= self._policy.max_replicas
        try:
            get_metrics().record_fleet_saturation(
                1.0 if (at_max and self._policy.saturated(now)) else 0.0)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            logger.debug("fleet saturation gauge failed", exc_info=True)
        if direction is None:
            return None
        if direction == "out":
            ok = self._scale_out(reason)
        else:
            ok = self._scale_in(load, reason)
        if ok:
            self._policy.note_scaled(now, direction)
            with self._mutex:
                self._decisions[direction] += 1
                if direction == "out":
                    self._pending_launches.append(now)
            self._book_decision(direction, reason)
            return direction
        with self._mutex:
            self._skipped += 1
        return None

    def _scale_out(self, reason: str) -> bool:
        if self._launcher is None:
            logger.debug("scale-out wanted (%s) but no launcher is wired",
                         reason)
            return False
        try:
            self._launcher()
        except Exception:  # noqa: BLE001 — a failed launch must not kill the loop
            logger.exception("elastic worker launch failed")
            return False
        return True

    def _scale_in(self, load: dict, reason: str) -> bool:
        per = load.get("replicas") or []
        if not per:
            return False
        # most idle first; backlog breaks ties so we never drain a
        # replica that still holds queued work while an emptier one exists
        target = min(per, key=lambda p: (p["busy"], p["backlog"]))
        try:
            result = self._set.retire(target["replica"])
        except Exception:  # noqa: BLE001 — last-serving guard / races: retry next poll
            logger.info("scale-in of replica %s refused",
                        target["replica"], exc_info=True)
            return False
        return bool(result.get("retired"))

    def _book_decision(self, direction: str, reason: str) -> None:
        logger.info("autoscale decision: %s (%s)", direction, reason)
        try:
            get_metrics().record_autoscale_decision(direction, reason)
            from sentio_tpu.infra.flight import get_flight_recorder

            get_flight_recorder().record_tick(
                event="autoscale_decision", direction=direction,
                reason=reason,
            )
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            logger.debug("autoscale decision telemetry failed",
                         exc_info=True)

    def stats(self) -> dict:
        with self._mutex:
            return {
                "scale_out": self._decisions["out"],
                "scale_in": self._decisions["in"],
                "skipped": self._skipped,
                "pending_launches": len(self._pending_launches),
            }

    def close(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            # a retire mid-pass blocks up to the drain deadline
            t.join(timeout=timeout_s)
        self._thread = None
