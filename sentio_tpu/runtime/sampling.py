"""Token sampling: greedy / temperature / top-k / top-p, jit-safe.

All functions operate on a [B, V] float32 logits batch and are called inside
jitted decode steps — no data-dependent Python control flow; temperature==0
routes through ``lax.cond``-free masking (greedy is argmax; the temperature
path divides by max(temp, eps) and greedy is selected by a boolean).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def sample_tokens(
    logits: Array,
    rng: Array,
    temperature: Array | float = 0.0,
    top_k: Array | int = 0,
    top_p: float = 1.0,
) -> tuple[Array, Array]:
    """[B, V] → ([B] int32 tokens, [B] float32 logprobs). ``temperature``
    may be a traced scalar or a [B] vector (continuous batching mixes
    generator/verifier rows at different temperatures); 0 = greedy.
    ``top_k`` may be a static Python int (0 = off, compiled in) or a TRACED
    int32 scalar / [B] vector — the serving engines pass it traced so
    per-request values share ONE compiled program instead of recompiling
    the decode loop per distinct k; <= 0 disables per row. top_p is static
    (compiled in).

    The returned logprob is the chosen token's log-probability under the
    UNMODIFIED model distribution (float32 log-softmax of the raw logits,
    before temperature scaling or top-k/top-p filtering) — a sampling-
    hyperparameter-independent confidence signal the verify gate
    (ops/confidence.py) consumes. Callers that only need tokens discard
    the second element; XLA dead-code-eliminates the log-softmax then."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.asarray(temperature, jnp.float32)
    temp_col = temp[:, None] if temp.ndim == 1 else temp
    scaled = logits / jnp.maximum(temp_col, 1e-6)

    if isinstance(top_k, int):
        if top_k > 0:
            kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    else:
        k = jnp.asarray(top_k, jnp.int32)
        k_col = (
            k[:, None] if k.ndim == 1
            else jnp.broadcast_to(k, (scaled.shape[0],))[:, None]
        )
        v = scaled.shape[-1]

        def _mask_topk(s):
            # kth-largest per row via one ascending sort + traced-index
            # gather; rows with k <= 0 keep everything (the jnp.where arm).
            # Matches the static path exactly: values == kth survive.
            srt = jnp.sort(s, axis=-1)
            idx = jnp.clip(v - k_col, 0, v - 1)
            kth = jnp.take_along_axis(srt, idx, axis=-1)
            return jnp.where((k_col > 0) & (s < kth), -jnp.inf, s)

        # cond skips the [B, V] sort entirely on the common top_k=0 ticks
        scaled = jax.lax.cond(
            jnp.any(k_col > 0), _mask_topk, lambda s: s, scaled
        )
    if top_p < 1.0:
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p (always >= 1 tok)
        cutoff_idx = jnp.argmax(cum >= top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    chosen = jnp.where(
        jnp.broadcast_to(temp, greedy.shape) <= 0.0, greedy, sampled
    )
    logprobs = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), chosen[:, None], axis=-1
    )[:, 0]
    return chosen, logprobs
