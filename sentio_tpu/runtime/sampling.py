"""Token sampling: greedy / temperature / top-k / top-p, jit-safe.

All functions operate on a [B, V] float32 logits batch and are called inside
jitted decode steps — no data-dependent Python control flow; temperature==0
routes through ``lax.cond``-free masking (greedy is argmax; the temperature
path divides by max(temp, eps) and greedy is selected by a boolean).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def sample_tokens(
    logits: Array,
    rng: Array,
    temperature: Array | float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> Array:
    """[B, V] → [B] int32. ``temperature`` may be a traced scalar or a [B]
    vector (continuous batching mixes generator/verifier rows at different
    temperatures); 0 = greedy. top_k / top_p are static (compiled in)."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.asarray(temperature, jnp.float32)
    temp_col = temp[:, None] if temp.ndim == 1 else temp
    scaled = logits / jnp.maximum(temp_col, 1e-6)

    if top_k and top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p < 1.0:
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p (always >= 1 tok)
        cutoff_idx = jnp.argmax(cum >= top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(jnp.broadcast_to(temp, greedy.shape) <= 0.0, greedy, sampled)
