"""Process-level replica workers: one engine+service+pump per OS process.

The thread-mode ReplicaSet (runtime/replica.py) made the replica a complete
*logical* failure domain — health state machine, breakers, watchdog, inbox
handoff — but all N pumps share one Python process, so a "replica kill" is
an injected exception and N dispatches contend for one GIL (BENCH_r08's GIL
probe measured a 0.978 scaling ratio at 1→2 in-process replicas). This
module promotes the replica to a real **OS-level** failure domain, the way
production inference stacks isolate engine crashes from the frontend
(vLLM's engine-per-process serving, Orca-style continuous-batching
workers):

* :func:`worker_main` runs in a child process (**spawn** start method —
  JAX is not fork-safe: a fork duplicates its runtime threads' locks in a
  held state and the child deadlocks on the first dispatch) and owns a
  private ``ContinuousBatchingEngine`` + ``PagedGenerationService`` +
  pump thread. It serves a small RPC protocol over the spawn pipe
  (``multiprocessing.Pipe`` — length-prefixed pickle frames) and pushes
  unsolicited **status frames** (heartbeat age, backlog, breaker signals)
  at a fixed cadence so the router's supervisor probes never pay an RPC
  round trip.
* :class:`ProcessReplica` is the router-side shim: it presents the same
  ``generate / generate_stream / check_admission / peek_prefix / warmup /
  drain / stats / close`` surface as a ``PagedGenerationService``, so
  ``ReplicaSet`` routing, WFQ, affinity, health supervision, and failover
  drive it **unchanged**. Streaming arrives as incremental token frames;
  worker death (``SIGKILL``, OOM-kill, crash) surfaces as broken-pipe /
  ``proc.is_alive()`` and every in-flight RPC fails with a typed
  :class:`ReplicaUnavailable` — callers spend their normal failover
  budget, exactly as if an in-process replica had latched broken.
* the supervisor rebuilds a dead replica by **respawning the process**
  (:meth:`ProcessReplica.respawn` — the ``ReplicaSet._rebuild`` path
  duck-types it), with the existing exponential backoff and rebuild
  worker pool carrying over.
* weights are mapped **once per host**: a checkpoint loaded with
  ``load_pytree(..., mmap=True)`` memory-maps the uncompressed ``.npy``
  members of ``arrays.npz`` in place, so N workers reading the same
  checkpoint share the page cache instead of holding N private host
  copies (runtime/checkpoint.py stores ``np.savez`` zips uncompressed
  precisely so this works).

**Router-side ticket shadowing** — the router mirrors every admitted-but-
not-yet-answered request in a shadow queue of real
:class:`~sentio_tpu.runtime.service._Ticket` objects (the same dataclass
thread mode hands off), keyed by RPC id. A request leaves the shadow the
moment its first answer frame arrives (first token frame for a stream,
the result frame for a generate). When the fronting ReplicaSet enables
handoff (:meth:`ProcessReplica.enable_shadow_handoff` — it does so
whenever it supervises), worker death or stall-quarantine no longer fails
those callers typed: ``extract_inbox``/``abandon`` return the shadowed
tickets and the ReplicaSet's existing ``_handoff_inbox`` re-admits them
on survivors via ``adopt()`` with the PR 10 WFQ recharge semantics —
handoff parity with thread mode. A LIVE but quarantined worker
additionally answers a bounded-timeout ``extract_inbox`` RPC that names
exactly its never-dispatched inbox tickets (by ``shadow_id``), so only
truly queued work moves and mid-decode work keeps its normal typed-
failover path. ``adopt`` re-registers the SAME ticket object against the
survivor's pipe — the blocked caller (event for generates, ``stream_q``
for streams) just wakes with the survivor's answer, spending no failover
budget. Without an enabling ReplicaSet the shadow stays passive and death
keeps its fail-fast typed surface.

**Transports & the multi-host tier** — the pickle-frame protocol runs
behind a transport seam (runtime/transport.py): ``REPLICA_MODE=process``
keeps the spawn pipe, byte-identical; ``REPLICA_MODE=socket`` runs the
SAME frames over length-prefixed TCP with a versioned auth handshake —
spawned workers self-register against the router's ``WorkerRegistry``
listener (:func:`worker_main_socket`), or the router dials workers
already serving on OTHER hosts (``REPLICA_WORKERS`` →
:func:`worker_serve`). Every (re)registration is a fresh **incarnation
epoch** stamped into frame headers; the dispatcher drops stale-epoch
frames, so a worker that vanished behind a partition and later
reconnects can never resurrect dead tickets or double-deliver stream
chunks. Death detection generalizes to a transport-liveness contract —
status-frame staleness past ``partition_timeout_s``, a broken ping
write, EOF — feeding the same quarantine machinery; recovery prefers
**heal** (the live worker re-registers, keeping its warm engine) over
respawn, and duck-types to redial-with-backoff for remote workers the
router cannot spawn.

Deliberate semantic deltas from thread mode, all documented here:

* **stream cancellation propagates at chunk granularity** — closing the
  router-side iterator sends a cancel frame; the worker notices between
  token frames, so an abandoned stream decodes at most one more chunk.
* **compile fences are per-process** — worker compiles never trip the
  router's fence; ``set_fence_exempt`` on the engine facade is a no-op.
* **mid-decode generates may re-execute on handoff** — a dead worker
  cannot report which shadowed generates had already dispatched, so after
  a process death every shadowed (unanswered) ticket is handed off; a
  re-executed generate is idempotent from the caller's view (no partial
  output ever escaped). Streams are exact: delivered-token streams leave
  the shadow at their first token frame and ride the ReplicaSet's
  resume-by-replay path instead.
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from sentio_tpu.infra import faults
from sentio_tpu.infra.exceptions import (
    DeadlineExceededError,
    ReplicaUnavailable,
    SentioError,
)
from sentio_tpu.runtime.paged import PagedResult
from sentio_tpu.runtime.service import (
    StreamProgress,
    _Ticket,
    finish_ticket_error,
)
from sentio_tpu.runtime.transport import (
    DEFAULT_FRAME_TIMEOUT_S,
    DEFAULT_MAX_FRAME_BYTES,
    ClockSync,
    FrameProtocolError,
    PipeTransport,
    SocketTransport,
    TransportClosed,
    TransportError,
    dial,
    expect_hello,
    send_hello,
)

logger = logging.getLogger(__name__)

__all__ = [
    "WorkerSpec",
    "ProcessReplica",
    "worker_main",
    "worker_main_socket",
    "worker_serve",
    "default_service_factory",
    "REPLICA_MODE_THREAD",
    "REPLICA_MODE_PROCESS",
    "REPLICA_MODE_SOCKET",
]

REPLICA_MODE_THREAD = "thread"
REPLICA_MODE_PROCESS = "process"
# socket transport: same worker protocol over length-prefixed TCP frames
# (runtime/transport.py) — spawned workers self-register against the
# router's WorkerRegistry listener; REPLICA_WORKERS=host:port,... makes the
# router dial advertised workers on OTHER hosts instead of spawning
REPLICA_MODE_SOCKET = "socket"

# worker → router frame kinds (req_id 0 is reserved for unsolicited frames)
_F_READY = "ready"
_F_STATUS = "status"
_F_OK = "ok"
_F_ERR = "err"
_F_TOK = "tok"
_F_END = "end"
# fleet telemetry plane (ISSUE 16): low-priority unsolicited frames — a
# telemetry frame ships the worker's cumulative metrics registry + duty
# snapshot at spec.telemetry_interval_s; a pong answers a timestamped ping
# with the worker's clock so the router's ClockSync can estimate the offset
_F_TELEMETRY = "telemetry"
_F_PONG = "pong"
# elastic fleet (ISSUE 20): a voluntary deregister — the worker asks the
# router to retire it gracefully (drain + handoff + close + slot release).
# Unsolicited (req_id 0); serving continues until the router-side
# supervisor drains the replica, so no in-flight work is ever dropped.
_F_DEREGISTER = "deregister"

# the bounded stats subset a telemetry frame carries (full svc.stats() is
# an RPC surface — the cadence frame only ships what the router merges:
# phase/duty for fleet duty gauges, occupancy/pool for {replica} gauges)
_TELEMETRY_STAT_KEYS = (
    "phase_seconds", "duty_elapsed_s", "duty_cycle", "active_slots",
    "queued", "queued_inbox", "free_pages", "total_pages",
    "pool_hbm_bytes",
)


@dataclass(frozen=True)
class WorkerSpec:
    """Everything a spawned worker needs to build its replica. Must be
    picklable: the spawn start method ships it through the process pipe.

    ``factory`` is a ``"module:function"`` path resolved **inside the
    worker** — it returns a ready ``PagedGenerationService``. The default
    (:func:`default_service_factory`) builds a llama/moe engine from a
    checkpoint path (mmap-shared across workers) or a seeded random init;
    tests point it at tiny configs through ``factory_kwargs``."""

    factory: str = "sentio_tpu.runtime.worker:default_service_factory"
    factory_kwargs: dict = field(default_factory=dict)
    # cadence of unsolicited status frames (the router-side supervisor's
    # probe source); also bounds how stale a liveness read can be
    status_interval_s: float = 0.1
    # cadence of unsolicited telemetry frames (metrics-registry snapshot +
    # duty/phase stats + flight high-water marks). 0 DISABLES the plane
    # entirely: no telemetry thread, no pong frames, no clock stamps on
    # pings — the wire protocol is byte-identical to the pre-telemetry
    # baseline (the TELEMETRY_INTERVAL_S=0 parity contract)
    telemetry_interval_s: float = 1.0
    # ---- socket transport (REPLICA_MODE=socket / REPLICA_WORKERS) ----
    # shared secret for the versioned registration handshake; the registry
    # rejects hellos that fail the constant-time compare
    auth_token: str = ""
    # frame bounds: an oversized frame is refused typed on both sides, a
    # partial frame (or a write the peer stopped draining) past the
    # timeout drops the connection instead of hanging a reader
    max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES
    frame_timeout_s: float = DEFAULT_FRAME_TIMEOUT_S
    # worker-side re-registration: when the router link dies (EOF, broken
    # write, or router silence past router_silence_timeout_s), redial the
    # registry with exponential backoff — the reconnection is a FRESH
    # incarnation (higher epoch); reconnect_deadline_s of continuous dial
    # failure means the router is gone for good and the worker exits
    # rather than orphan itself
    reconnect: bool = False
    reconnect_backoff_s: float = 0.5
    reconnect_max_backoff_s: float = 5.0
    reconnect_deadline_s: float = 60.0
    # a socket worker that has heard NOTHING from the router (requests,
    # pings, anything) for this long treats the link as partitioned and
    # redials; 0 disables (pipe mode never needs it — a dead router is a
    # broken pipe). The router pings at ping_interval_s, so a healthy
    # idle link never trips this.
    router_silence_timeout_s: float = 3.0


def _resolve_factory(path: str):
    import importlib

    mod_name, _, fn_name = path.partition(":")
    if not fn_name:
        raise ValueError(f"factory {path!r} is not 'module:function'")
    return getattr(importlib.import_module(mod_name), fn_name)


def default_service_factory(
    model_family: str = "llama",
    model_config: Optional[dict] = None,
    checkpoint_path: str = "",
    tokenizer_path: str = "",
    draft_checkpoint_path: str = "",
    rng_seed: int = 0,
    engine_kwargs: Optional[dict] = None,
    service_kwargs: Optional[dict] = None,
    warm_prefix_text: str = "",
) -> Any:
    """Build the worker's engine+service. With a ``checkpoint_path`` the
    params are loaded **memory-mapped** so sibling workers on the same host
    share one page-cache copy; without one, a seeded random init keeps all
    replicas' weights identical (the test / offline-dev mode). A
    ``draft_checkpoint_path`` arms paged speculation inside the worker —
    the draft loads here, in the worker process, mmap-shared like the
    target weights."""
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine
    from sentio_tpu.runtime.service import PagedGenerationService

    params = tokenizer = None
    cfg = None
    if checkpoint_path:
        from sentio_tpu.runtime.weights import load_model

        params, cfg, tokenizer = load_model(
            checkpoint_path,
            expect_family=model_family,
            tokenizer_path=tokenizer_path,
            mmap=True,
        )
    elif model_config is not None:
        if model_family == "moe":
            from sentio_tpu.models.moe import MoeConfig

            cfg = MoeConfig(**model_config)
        else:
            from sentio_tpu.models.llama import LlamaConfig

            cfg = LlamaConfig(**model_config)
    engine_kwargs = dict(engine_kwargs or {})
    if draft_checkpoint_path:
        from sentio_tpu.runtime.weights import load_model

        draft_params, draft_cfg, _ = load_model(
            draft_checkpoint_path, expect_family="llama", mmap=True,
        )
        engine_kwargs.setdefault("draft_params", draft_params)
        engine_kwargs.setdefault("draft_config", draft_cfg)
    engine = ContinuousBatchingEngine(
        model_config=cfg,
        params=params,
        tokenizer=tokenizer,
        rng_seed=rng_seed,
        **engine_kwargs,
    )
    if warm_prefix_text:
        engine.warm_prefix(warm_prefix_text)
    return PagedGenerationService(engine, **(service_kwargs or {}))


# --------------------------------------------------------------------------
# exception codec: typed errors must survive the process boundary

def _encode_exc(exc: BaseException) -> dict:
    data = {
        "cls": type(exc).__name__,
        "module": type(exc).__module__,
        "message": str(exc),
    }
    if isinstance(exc, SentioError):
        data.update(
            status=exc.status,
            details=exc.details,
            retryable=exc.retryable,
            code=exc.code.value,
        )
    return data


def _decode_exc(data: dict) -> BaseException:
    """Rebuild the worker's exception router-side. SentioError subclasses
    reconstruct with their full wire surface (status / details /
    retry_after_s) so HTTP mapping and failover logic behave identically;
    the service's own GenerationTimeout and common builtins round-trip by
    name; anything else degrades to RuntimeError carrying the original
    type — a worker *bug* must not masquerade as a retryable 503."""
    from sentio_tpu.infra import exceptions as exc_mod
    from sentio_tpu.runtime.service import GenerationTimeout

    name, message = data.get("cls", ""), data.get("message", "")
    cls = getattr(exc_mod, name, None)
    if isinstance(cls, type) and issubclass(cls, exc_mod.SentioError):
        err = cls.__new__(cls)
        Exception.__init__(err, message)
        err.message = message
        err.status = data.get("status", 500)
        err.details = data.get("details") or {}
        err.retryable = bool(data.get("retryable", False))
        err.error_id = ""
        err.timestamp = 0.0
        try:
            err.code = exc_mod.ErrorCode(data.get("code", cls.code.value))
        except ValueError:
            pass
        return err
    if name == "GenerationTimeout":
        return GenerationTimeout(message)
    import builtins

    builtin = getattr(builtins, name, None)
    if isinstance(builtin, type) and issubclass(builtin, Exception):
        try:
            return builtin(message)
        except Exception:  # noqa: BLE001 — odd constructor signature
            pass
    return RuntimeError(f"worker raised {name}: {message}")


# --------------------------------------------------------------------------
# worker side

class _WorkerServer:  # frame-emit: worker-to-router
    """Runs inside the child process: one recv loop dispatching RPC frames
    to handler threads, a status thread pushing liveness. Framing and
    send-side locking live in the transport (runtime/transport.py) — the
    server is transport-agnostic, so the spawn pipe and a TCP socket serve
    the identical protocol.

    A server instance covers ONE connection (one incarnation). In socket
    reconnect mode the outer loop (:func:`worker_main_socket`) builds a
    fresh server per connection, handing the already-built service across
    so a reconnection is a fresh incarnation of the LINK, not of the
    engine."""

    def __init__(self, transport, spec: WorkerSpec, svc=None) -> None:
        self.transport = transport
        self.spec = spec
        self.svc = svc
        self._stop = threading.Event()
        # why this run() returned: "shutdown" (router asked), "link_lost"
        # (transport died / router silent), or "fatal" (factory failed)
        self.outcome = ""
        # stream cancellation flags by req_id (checked between token frames)
        self._cancelled: set[int] = set()  # guarded-by: _cancel_lock
        self._cancel_lock = threading.Lock()

    def _send(self, req_id: int, kind: str, payload: Any) -> None:
        try:
            self.transport.send((req_id, kind, payload))
        except TransportError:
            # router link gone (EOF, broken write, frame refused): stop
            # this incarnation; the outer loop decides whether to redial
            self._stop.set()

    # ------------------------------------------------------------- handlers

    def _status_loop(self) -> None:
        interval = max(self.spec.status_interval_s, 0.02)
        while not self._stop.wait(interval):
            svc = self.svc
            if svc is None:
                continue
            try:
                status = {
                    "heartbeat_age": svc.heartbeat_age(),
                    "backlog": svc.backlog(),
                    "projected_wait": svc.projected_wait(),
                    "broken": svc.broken,
                    "closed": svc.closed,
                    "tick_failure_count": svc.tick_failure_count,
                    "pump_leaked": svc.pump_leaked_count,
                    "duty_cycle": svc.duty_cycle(),
                    "pid": os.getpid(),
                }
            except Exception:  # noqa: BLE001 — status is best-effort
                continue
            self._send(0, _F_STATUS, status)

    def _telemetry_loop(self) -> None:
        """Ship the fleet-telemetry frame at ``spec.telemetry_interval_s``:
        the worker's CUMULATIVE metrics registry (the router differences
        consecutive snapshots into deltas — cumulative-on-the-wire makes a
        dropped frame lossless, the next one carries everything), the
        bounded duty/occupancy stats subset, the flight ring's high-water
        marks, and the clock stamps (pid / perf_counter / recorder origin)
        the merge fence and trace re-basing need. Runs only when the
        interval is > 0 — the hot path pays nothing either way (one extra
        unsolicited frame per second rides the same transport send lock
        status frames already take)."""
        from sentio_tpu.infra.flight import get_flight_recorder
        from sentio_tpu.infra.metrics import get_metrics

        interval = max(self.spec.telemetry_interval_s, 0.05)
        recorder = get_flight_recorder()
        while not self._stop.wait(interval):
            svc = self.svc
            if svc is None:
                continue
            try:
                stats = svc.stats()
            except Exception:  # noqa: BLE001 — stats mid-teardown
                stats = {}
            try:
                payload = {
                    "series": get_metrics().export_worker_series(),
                    "stats": {k: stats[k] for k in _TELEMETRY_STAT_KEYS
                              if k in stats},
                    "flight": recorder.highwater(),
                    "pid": os.getpid(),
                    "origin_s": recorder.origin(),
                    "t_worker": time.perf_counter(),
                }
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                continue
            self._send(0, _F_TELEMETRY, payload)

    # frame-dispatch: router-to-worker via=pipe,socket
    def _handle(self, req_id: int, method: str, kwargs: dict) -> None:
        svc = self.svc
        try:
            if method == "generate":
                self._send(req_id, _F_OK, svc.generate(**kwargs))
            elif method == "stream_open":
                self._handle_stream(req_id, kwargs)
            elif method == "check_admission":
                rel = kwargs.get("deadline_rel_s")
                svc.check_admission(
                    time.perf_counter() + rel if rel is not None else None
                )
                self._send(req_id, _F_OK, None)
            elif method == "peek_prefix":
                self._send(req_id, _F_OK,
                           svc.engine.peek_prefix(kwargs["toks"]))
            elif method == "stats":
                self._send(req_id, _F_OK, svc.stats())
            elif method == "warmup":
                self._send(req_id, _F_OK, svc.warmup(**kwargs))
            elif method == "drain":
                self._send(req_id, _F_OK, svc.drain(**kwargs))
            elif method == "abandon":
                tickets = svc.abandon(kwargs.get("reason",
                                                 "abandoned by router"))
                # never-dispatched inbox tickets come back by shadow id so
                # the router can hand EXACTLY them to survivors; the
                # admitted tickets abandon() failed typed are reaching
                # their callers as _F_ERR frames right now
                self._send(req_id, _F_OK, self._shadow_ids(tickets))
            elif method == "extract_inbox":
                # breaker-flavor quarantine of a LIVE worker: name the
                # never-dispatched inbox tickets (by shadow id) back to
                # the router's shadow queue; only truly queued work moves
                self._send(req_id, _F_OK,
                           self._shadow_ids(svc.extract_inbox()))
            elif method == "duty_cycle":
                self._send(req_id, _F_OK, svc.duty_cycle())
            elif method == "fetch_flight":
                # on-demand flight shipping: the detailed per-request tick/
                # phase/verify data moves ONLY when asked (the 1 Hz frame
                # carries counters; /debug/flight and `sentio trace --fleet`
                # pay one RPC each) — the hot path never ships a tick
                from sentio_tpu.infra.flight import get_flight_recorder

                recorder = get_flight_recorder()
                payload = {
                    "pid": os.getpid(),
                    "origin_s": recorder.origin(),
                    "t_worker": time.perf_counter(),
                }
                if kwargs.get("t_tx") is not None:
                    # echo the router's transmit stamp: the reply doubles
                    # as a clock sample (pipe mode has no ping loop, so
                    # this is its only offset source)
                    payload["t_tx"] = kwargs["t_tx"]
                rid = kwargs.get("request_id")
                if rid is not None:
                    payload["record"] = recorder.get(rid)
                else:
                    payload["ticks"] = recorder.timeline(kwargs.get("last"))
                    payload["records"] = recorder.records()
                self._send(req_id, _F_OK, payload)
            elif method == "reset_duty_cycle":
                svc.reset_duty_cycle()
                self._send(req_id, _F_OK, None)
            elif method == "inject_fault":
                from sentio_tpu.infra import faults

                point = kwargs.pop("point")
                faults.arm(point, faults.FaultRule(**kwargs))
                self._send(req_id, _F_OK, None)
            elif method == "reset_faults":
                from sentio_tpu.infra import faults

                faults.reset()
                self._send(req_id, _F_OK, None)
            elif method == "ping":
                self._send(req_id, _F_OK, os.getpid())
            elif method == "leave":
                # voluntary deregister trigger (operator CLI / drills): the
                # worker emits the unsolicited deregister frame and KEEPS
                # SERVING — the router's supervisor owns the graceful
                # retire (drain, handoff, close); shutting down here would
                # drop in-flight work the retire path exists to save
                self._send(0, _F_DEREGISTER, {
                    "reason": kwargs.get("reason", "leave"),
                    "pid": os.getpid(),
                })
                self._send(req_id, _F_OK, None)
            else:
                raise ValueError(f"unknown worker method {method!r}")
        except BaseException as exc:  # noqa: BLE001 — everything goes typed  # lint: allow(baseexception-swallow) — converted to a typed wire frame
            self._send(req_id, _F_ERR, _encode_exc(exc))

    @staticmethod
    def _shadow_ids(tickets: list) -> list:
        return [t.shadow_id for t in tickets if t.shadow_id is not None]

    def _handle_stream(self, req_id: int, kwargs: dict) -> None:
        """Token frames for one stream. The iterator is created (call-time
        validation) BEFORE the ok frame, so the router-side caller sees
        validation errors synchronously — the SSE pre-200 contract.

        Each token frame carries ``(piece, token_id_delta)`` — the exact
        ids behind the piece, mirrored from the service's
        :class:`StreamProgress` — so the router can accumulate the
        delivered prefix a mid-flight resume re-admits. The
        ``worker.stream_chunk`` fault point fires BETWEEN delivered
        chunks: chaos drills arm ``kill_process`` (a real mid-stream
        SIGKILL) or a stall there via the ``inject_fault`` RPC."""
        stats_out: dict = {}
        progress = StreamProgress()
        it = self.svc.generate_stream(stats_out=stats_out,
                                      progress=progress, **kwargs)
        self._send(req_id, _F_OK, None)
        sent = 0
        delivered = False
        try:
            for piece in it:
                if delivered:
                    faults.hit("worker.stream_chunk")
                with self._cancel_lock:
                    if req_id in self._cancelled:
                        self._cancelled.discard(req_id)
                        it.close()  # marks the ticket cancelled in finally
                        return
                toks = list(progress.tokens)
                self._send(req_id, _F_TOK, (piece, toks[sent:]))
                sent = len(toks)
                delivered = True
            # the end frame carries the AUTHORITATIVE final token ids:
            # tokens whose text the UTF-8 withholding never flushed ride
            # no token frame, and the router's delivered-state mirror must
            # still converge on the service's final sequence
            self._send(req_id, _F_END, (stats_out, list(progress.tokens)))
        except BaseException as exc:  # noqa: BLE001  # lint: allow(baseexception-swallow) — converted to a typed wire frame
            self._send(req_id, _F_ERR, _encode_exc(exc))
        finally:
            with self._cancel_lock:
                self._cancelled.discard(req_id)

    # ----------------------------------------------------------------- main

    # frame-dispatch: router-to-worker via=pipe,socket
    def run(self) -> str:
        """Serve this connection until shutdown / link loss. Returns the
        outcome (also latched on ``self.outcome``); the SERVICE is left
        open — the caller owns its lifetime (a socket reconnection reuses
        it across incarnations)."""
        if self.svc is None:
            try:
                factory = _resolve_factory(self.spec.factory)
                self.svc = factory(**self.spec.factory_kwargs)
            except BaseException as exc:  # noqa: BLE001 — report, then die  # lint: allow(baseexception-swallow) — reported as a typed wire frame
                self._send(0, _F_ERR, _encode_exc(exc))
                self.outcome = "fatal"
                return self.outcome
        eng = self.svc.engine
        self._send(0, _F_READY, {
            "pid": os.getpid(),
            "page_size": eng.page_size,
            "max_slots": eng.max_slots,
            "max_queue": self.svc.max_queue,
            "default_timeout_s": self.svc.default_timeout_s,
            "default_deadline_s": self.svc.default_deadline_s,
            "retry_budget": self.svc.retry_budget,
            "tick_stall_budget_s": self.svc.tick_stall_budget_s,
        })
        status = threading.Thread(target=self._status_loop,
                                  name="worker-status", daemon=True)
        status.start()
        if self.spec.telemetry_interval_s > 0:
            threading.Thread(target=self._telemetry_loop,
                             name="worker-telemetry", daemon=True).start()
        # router-silence watch (socket links only): a half-open partition
        # can leave this side's reads idle forever while its writes still
        # land — no error will ever arrive, so silence IS the signal
        silence_s = (self.spec.router_silence_timeout_s
                     if isinstance(self.transport, SocketTransport) else 0.0)
        poll_s = 0.25 if silence_s > 0 else None
        last_rx = time.perf_counter()
        self.outcome = "link_lost"
        while not self._stop.is_set():
            try:
                got = self.transport.recv(timeout_s=poll_s)
            except FrameProtocolError:
                if isinstance(self.transport, PipeTransport):
                    # a pipe preserves message boundaries: one undecodable
                    # frame does not poison the next (pre-transport parity)
                    logger.exception("worker dropped an undecodable frame")
                    continue
                logger.exception("worker dropped the connection on a "
                                 "protocol error")
                break
            except TransportError:
                break  # router died or closed: this incarnation is over
            if got is None:
                if (silence_s > 0
                        and time.perf_counter() - last_rx > silence_s):
                    logger.warning(
                        "router silent for %.1fs; treating the link as "
                        "partitioned", time.perf_counter() - last_rx)
                    break
                continue
            frame, _epoch = got
            last_rx = time.perf_counter()
            try:
                req_id, method, kwargs = frame
            except (TypeError, ValueError):
                # a malformed frame is a peer bug, not a reason to die
                # with a bare unpack traceback: answer typed and move on
                self._send(0, _F_ERR, _encode_exc(FrameProtocolError(
                    f"malformed request frame: {frame!r}")))
                continue
            if method == "__shutdown__":
                self.outcome = "shutdown"
                break
            if method == "__ping__":
                # router liveness probe: receiving it IS the point. A ping
                # carrying a transmit stamp (telemetry plane on) gets a
                # pong with this side's clock — the router's ClockSync
                # turns the exchange into an offset/RTT sample. Bare pings
                # (telemetry off, or an older router) stay answerless:
                # byte-identical to the pre-telemetry protocol.
                t_tx = (kwargs.get("t_tx")
                        if isinstance(kwargs, dict) else None)
                if t_tx is not None:
                    from sentio_tpu.infra.flight import get_flight_recorder

                    self._send(0, _F_PONG, {
                        "t_tx": t_tx,
                        "t_worker": time.perf_counter(),
                        "origin_s": get_flight_recorder().origin(),
                        "pid": os.getpid(),
                    })
                continue
            if method == "stream_cancel":
                with self._cancel_lock:
                    self._cancelled.add(int(kwargs["stream_id"]))
                continue
            threading.Thread(
                target=self._handle, args=(req_id, method, kwargs),
                name=f"worker-rpc-{req_id}", daemon=True,
            ).start()
        self._stop.set()
        return self.outcome


def worker_main(conn, spec: WorkerSpec) -> None:
    """Child-process entry point (spawned by :class:`ProcessReplica`)."""
    # the worker must die with its router even when wedged in XLA: the
    # router holds the other pipe end, so a clean router close() still
    # reaches the recv loop; SIGTERM from terminate() gets a fast exit
    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))
    logging.basicConfig(level=logging.WARNING)
    server = _WorkerServer(PipeTransport(conn), spec)
    server.run()
    if server.svc is not None:
        try:
            server.svc.close()
        except Exception:  # noqa: BLE001 — exiting anyway
            logger.exception("worker service close failed")
    # skip interpreter/static teardown: daemon threads (pump, RPC
    # handlers) may still sit inside XLA, and C++ static destructors
    # running under them abort with "terminate called without an active
    # exception" — the service already closed, nothing left to flush
    os._exit(0)


def worker_main_socket(addr, spec: WorkerSpec, slot: int) -> None:
    """Child-process entry point for SOCKET workers spawned by
    :class:`ProcessReplica` (``REPLICA_MODE=socket``): dial the router's
    registry listener, register (versioned auth handshake → incarnation
    epoch), serve the connection — and, with ``spec.reconnect``, REDIAL
    with exponential backoff whenever the link dies. Each reconnection is
    a fresh incarnation (higher epoch): the engine+service survive, the
    link identity does not — everything sent before the reconnect is
    fenced router-side as stale. A worker that cannot reach the router
    for ``spec.reconnect_deadline_s`` straight exits rather than orphan
    itself.

    ``slot == -1`` is an ELASTIC JOIN: the registry assigns a slot and
    acks it back; the worker adopts the assignment so every redial keeps
    the same fleet identity instead of allocating a new slot per
    reconnect."""
    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))
    logging.basicConfig(level=logging.WARNING)
    svc = None
    backoff = max(spec.reconnect_backoff_s, 0.05)
    give_up_at = None
    while True:
        try:
            transport = dial(
                addr, max_frame_bytes=spec.max_frame_bytes,
                frame_timeout_s=spec.frame_timeout_s, fault_scope="worker",
            )
            ack = send_hello(transport, spec.auth_token, slot, os.getpid())
            acked_slot = ack.get("slot") if isinstance(ack, dict) else None
            if isinstance(acked_slot, int) and acked_slot >= 0 \
                    and acked_slot != slot:
                # elastic join (slot == -1): adopt the registry's
                # assignment so a redial re-registers the SAME identity,
                # and re-label the yet-unbuilt service so worker-side
                # flight/telemetry lanes carry the granted slot
                slot = acked_slot
                skw = spec.factory_kwargs.get("service_kwargs")
                if isinstance(skw, dict):
                    skw["replica_id"] = slot
        except FrameProtocolError as exc:
            # definitive rejection (token/version drift): redialing burns
            # the reconnect deadline on a config error — die loudly; the
            # supervisor's respawn carries the current spec
            logger.error("worker registration rejected: %s", exc)
            break
        except TransportError as exc:
            now = time.perf_counter()
            if give_up_at is None:
                give_up_at = now + max(spec.reconnect_deadline_s, 1.0)
            if svc is None and not spec.reconnect:
                # never connected and no reconnect policy: die loudly; the
                # router's registration wait surfaces the typed timeout
                logger.error("worker registration failed: %s", exc)
                break
            if now >= give_up_at:
                logger.error("router unreachable for %.0fs; worker exiting",
                             spec.reconnect_deadline_s)
                break
            time.sleep(backoff)
            backoff = min(backoff * 2.0, spec.reconnect_max_backoff_s)
            continue
        give_up_at = None
        backoff = max(spec.reconnect_backoff_s, 0.05)
        server = _WorkerServer(transport, spec, svc=svc)
        outcome = server.run()
        svc = server.svc
        transport.close()
        if outcome in ("shutdown", "fatal") or not spec.reconnect:
            break
        logger.warning("worker slot %d lost its router link; redialing",
                       slot)
    if svc is not None:
        try:
            svc.close()
        except Exception:  # noqa: BLE001 — exiting anyway
            logger.exception("worker service close failed")
    os._exit(0)


# frame-emit: worker-to-router via=socket
def _push_final_err(transport, exc: BaseException) -> None:
    """One unsolicited typed err frame (req_id 0) outside any RPC loop —
    worker_serve's factory-failure and supersede notices ride the same
    worker-to-router channel the router's dispatcher already handles."""
    transport.send((0, _F_ERR, _encode_exc(exc)))


# frame-emit: handshake-to-dialer via=socket
def worker_serve(
    bind_host: str,
    bind_port: int,
    spec: WorkerSpec,
    stop_event: Optional[threading.Event] = None,
    bound_cb=None,
) -> None:
    """Advertised-worker entry (``REPLICA_WORKERS=host:port,...``): listen
    on ``bind_host:bind_port`` and serve router connections. The router
    dials in, authenticates (its hello carries the incarnation epoch its
    registry assigned), and drives the same RPC protocol. The accept loop
    KEEPS ACCEPTING while a connection is live: a router that restarted
    (or lost its old socket to a half-open partition) redials and the
    NEWEST handshake wins — the superseded connection gets a typed final
    error frame and closes, its server exits, and the shared service
    (engine, radix cache) carries straight over to the new link with no
    worker restart. A router ``__shutdown__`` closes the CONNECTION only:
    an advertised worker belongs to its operator, not to whichever router
    last dialed it. ``bound_cb`` (tests) receives the bound
    ``(host, port)``."""
    import socket as _socket

    stop = stop_event or threading.Event()
    listener = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    listener.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
    listener.settimeout(0.2)
    listener.bind((bind_host, int(bind_port)))
    listener.listen(4)
    if bound_cb is not None:
        bound_cb(listener.getsockname())
    # one service shared across router connections, built ON the accept
    # thread exactly once — two racing router dials must never build two
    # engines. The CURRENT connection's server/transport/thread live here;
    # only the accept loop mutates them (single writer, no lock needed).
    svc = None
    current: dict = {"server": None, "transport": None, "thread": None}

    def _serve_conn(server: _WorkerServer, transport) -> None:
        try:
            server.run()
        except Exception:  # noqa: BLE001 — one connection, not the listener
            logger.exception("router connection serving crashed")
        finally:
            transport.close()

    try:
        while not stop.is_set():
            try:
                conn, _peer = listener.accept()
            except _socket.timeout:
                continue
            except OSError:
                break
            transport = SocketTransport(
                conn, max_frame_bytes=spec.max_frame_bytes,
                frame_timeout_s=spec.frame_timeout_s, fault_scope="worker",
            )
            try:
                hello = expect_hello(transport, spec.auth_token,
                                     timeout_s=10.0)
                epoch = int(hello.get("epoch", 0))
                transport.epoch = epoch
                transport.send((0, "hello_ack",
                                {"epoch": epoch, "pid": os.getpid()}))
            except TransportError as exc:
                logger.warning("rejected router connection: %s", exc)
                transport.close()
                continue
            except Exception:  # noqa: BLE001 — a hostile hello must not kill the listener
                logger.exception("router handshake crashed; connection "
                                 "dropped")
                transport.close()
                continue
            if svc is None:
                try:
                    factory = _resolve_factory(spec.factory)
                    svc = factory(**spec.factory_kwargs)
                except BaseException as exc:  # noqa: BLE001 — report, then die  # lint: allow(baseexception-swallow) — reported as a typed wire frame
                    logger.exception("worker service factory failed")
                    try:
                        _push_final_err(transport, exc)
                    except TransportError:
                        pass
                    transport.close()
                    break
            prev = current["server"]
            if prev is not None and not prev._stop.is_set():
                # newest connection wins: the stale link gets a typed
                # final error, then its transport is cut — its server
                # exits link_lost without touching the shared service
                try:
                    _push_final_err(current["transport"], ReplicaUnavailable(
                        "superseded by a newer router connection",
                        retryable=False,
                    ))
                except TransportError:
                    pass  # the stale link is already dead — cutting it anyway
                prev._stop.set()
                current["transport"].close()
            if current["thread"] is not None:
                current["thread"].join(timeout=5.0)
            server = _WorkerServer(transport, spec, svc=svc)
            thread = threading.Thread(
                target=_serve_conn, args=(server, transport),
                name="worker-serve-conn", daemon=True,
            )
            current.update(server=server, transport=transport,
                           thread=thread)
            thread.start()
    finally:
        try:
            listener.close()
        except OSError:
            pass
        server = current["server"]
        if server is not None:
            server._stop.set()
            current["transport"].close()
            if current["thread"] is not None:
                current["thread"].join(timeout=5.0)
        if svc is not None:
            try:
                svc.close()
            except Exception:  # noqa: BLE001 — shutting down anyway
                logger.exception("worker service close failed")


# --------------------------------------------------------------------------
# router side

class _PendingCall:
    __slots__ = ("q", "streaming")

    def __init__(self, streaming: bool = False) -> None:
        self.q: _queue.Queue = _queue.Queue()
        # a streaming call stays registered past its open ack (_F_OK): the
        # token frames that follow reuse the same req_id, and popping on
        # the ack would silently drop every one of them
        self.streaming = streaming


class _EngineFacade:
    """The slice of the engine surface ReplicaSet touches on a replica:
    routing probes and rebuild-warmup hooks. Compiles happen in the worker
    process, outside the router's compile fence, so the fence exemption is
    a no-op here."""

    def __init__(self, owner: "ProcessReplica", tokenizer,
                 page_size: int, max_slots: int) -> None:
        self._owner = owner
        self.tokenizer = tokenizer
        self.page_size = page_size
        self.max_slots = max_slots

    def peek_prefix(self, toks) -> int:
        return self._owner._peek_prefix(toks)

    def set_fence_exempt(self, exempt: bool) -> None:  # noqa: ARG002
        return None


class ProcessReplica:  # frame-emit: router-to-worker
    """Router-process shim over one worker process; presents the
    ``PagedGenerationService`` surface so ReplicaSet drives it unchanged.

    Liveness model: the worker pushes status frames at
    ``spec.status_interval_s``; every read-side probe (``backlog``,
    ``heartbeat_age``, ``broken``…) is served from the cached frame, so
    supervisor passes cost zero RPCs. Worker death is observed three ways,
    any of which flips :attr:`broken`: the dispatcher hits EOF/broken pipe,
    ``proc.is_alive()`` goes false, or the worker itself reports a latched
    ``broken``. All pending RPCs then fail with typed
    :class:`ReplicaUnavailable` — the same caller surface as an in-process
    replica whose engine latched broken."""

    def __init__(
        self,
        spec: WorkerSpec,
        tokenizer,
        replica_id: int = 0,
        build_timeout_s: float = 600.0,
        transport_mode: str = REPLICA_MODE_PROCESS,
        registry=None,
        connect_addr: Optional[tuple] = None,
        partition_timeout_s: float = 2.0,
        ping_interval_s: float = 0.5,
        heal_grace_s: float = 5.0,
        adopt_registration: bool = False,
        _adopt_state: Optional[dict] = None,
    ) -> None:
        self.spec = spec
        self.replica_id = replica_id
        self.build_timeout_s = build_timeout_s
        self._tokenizer = tokenizer
        # transport tier: "process" = spawn pipe (single host, PR 13
        # behavior, the default); "socket" = TCP frames — either a locally
        # spawned worker self-registering against the router's
        # WorkerRegistry listener, or (connect_addr set) an advertised
        # worker on ANOTHER host the router dials (REPLICA_WORKERS)
        self._transport_mode = (REPLICA_MODE_SOCKET
                                if transport_mode == REPLICA_MODE_SOCKET
                                else REPLICA_MODE_PROCESS)
        self._registry = registry
        self._connect_addr = connect_addr
        self.partition_timeout_s = max(float(partition_timeout_s), 0.0)
        self.ping_interval_s = max(float(ping_interval_s), 0.0)
        self.heal_grace_s = max(float(heal_grace_s), 0.0)
        if (self._transport_mode == REPLICA_MODE_SOCKET
                and registry is None):
            raise ValueError(
                "socket transport needs a WorkerRegistry (it owns the "
                "incarnation epochs and the stale-frame fence)")
        self._mutex = threading.Lock()
        self._calls: dict[int, _PendingCall] = {}  # guarded-by: _mutex
        self._next_id = 1  # guarded-by: _mutex
        # router-side ticket shadow (module docstring): every unanswered
        # generate/stream mirrored as a real _Ticket keyed by its RPC id,
        # so worker death or quarantine hands never-answered work to
        # survivors instead of failing it typed. Passive until a
        # supervising ReplicaSet calls enable_shadow_handoff().
        self._handoff_enabled = False  # guarded-by: _mutex
        self._shadow: dict[int, tuple[_Ticket, _PendingCall]] = {}  # guarded-by: _mutex
        # tickets ADOPTED from a dead sibling: this replica executes them
        # via RPC and the dispatcher finishes the ticket itself (the
        # original caller blocks on the ticket, not on a pending call)
        self._adopted: dict[int, dict] = {}  # guarded-by: _mutex
        self._dead = False  # guarded-by: _mutex
        self._death_reason = ""  # guarded-by: _mutex
        self._death_kind = ""  # guarded-by: _mutex
        self._closed = False  # guarded-by: _mutex
        self._status: dict = {}
        self._status_ts = 0.0
        self._last_stats: dict = {}
        # elastic fleet: reason string of a voluntary deregister frame
        # (None until one arrives). Single writer — the dispatcher thread —
        # with GIL-atomic reads from the supervisor, same discipline as
        # _status.
        self._deregister_reason: Optional[str] = None
        # fleet telemetry plane: last ACCEPTED telemetry frame (cached for
        # stats overlays), its arrival stamp (the telemetry-age source),
        # the worker flight recorder's perf_counter origin (trace
        # re-basing), and the NTP-style offset estimator the ping loop
        # feeds. Plain attribute writes from the dispatcher thread —
        # GIL-atomic snapshots, same discipline as _status.
        self._telemetry: dict = {}
        self._telemetry_ts = 0.0
        self._worker_origin_s: Optional[float] = None
        self._clock = ClockSync()
        self.epoch = 0  # incarnation epoch of THIS connection (socket)
        self._proc = None
        self._transport = None
        if _adopt_state is not None:
            # HEAL path (respawn after a partition): a live worker
            # re-registered — adopt the fresh connection + epoch, keep the
            # existing process
            self._proc = _adopt_state.get("proc")
            self._transport = _adopt_state["transport"]
            self.epoch = _adopt_state["epoch"]
        elif self._transport_mode == REPLICA_MODE_PROCESS:
            import multiprocessing

            # JAX is not fork-safe (see module docstring): the worker MUST
            # come up via spawn so its runtime initializes in a clean
            # interpreter
            ctx = multiprocessing.get_context("spawn")
            conn, child_conn = ctx.Pipe()
            self._proc = ctx.Process(  # lint: allow(no-fork) — spawn context
                target=worker_main, args=(child_conn, spec),
                name=f"sentio-replica-worker-{replica_id}", daemon=True,
            )
            self._proc.start()
            child_conn.close()  # the parent's copy; the worker holds its own
            self._transport = PipeTransport(conn)
        elif connect_addr is not None:
            # REPLICA_WORKERS dial-out: the worker runs on another host
            # behind worker_serve(); the router owns the epoch counter and
            # ships it in its hello. Dial failures retry with backoff up
            # to the build timeout — re-registration IS redialing here.
            self._transport, self.epoch = self._dial_advertised(
                build_timeout_s)
        elif adopt_registration:
            # elastic join: the worker ALREADY dialed the registry (hello
            # slot -1) and holds the granted slot — adopt the queued
            # registration instead of spawning anything. The process is
            # not ours to reap (it may live on another host); a broken
            # link is a plain socket death.
            (self._transport, _hello,
             self.epoch) = registry.await_registration(
                replica_id, build_timeout_s)
        else:
            # local socket spawn: the worker connects BACK to the
            # registry's listener and registers; frames then carry the
            # granted epoch
            import multiprocessing

            ctx = multiprocessing.get_context("spawn")
            self._proc = ctx.Process(  # lint: allow(no-fork) — spawn context
                target=worker_main_socket,
                args=(tuple(registry.address), spec, replica_id),
                name=f"sentio-replica-worker-{replica_id}", daemon=True,
            )
            self._proc.start()
            try:
                (self._transport, _hello,
                 self.epoch) = registry.await_registration(
                    replica_id, build_timeout_s)
            except BaseException:
                # the spawned child must not outlive a failed construction
                self._reap(join_timeout_s=5.0)
                raise
        # the handshake call is registered BEFORE the dispatcher starts: a
        # factory that fails instantly would otherwise race its err frame
        # past an unregistered req_id 0 and the build would time out instead
        # of surfacing the real error
        ready_call = _PendingCall()
        self._calls[0] = ready_call
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name=f"replica-worker-rx-{replica_id}", daemon=True,
        )
        self._dispatcher.start()
        ready = self._wait_ready(ready_call, build_timeout_s)
        self.engine = _EngineFacade(self, tokenizer,
                                    ready["page_size"], ready["max_slots"])
        self.max_queue = ready["max_queue"]
        self.default_timeout_s = ready["default_timeout_s"]
        self.default_deadline_s = ready["default_deadline_s"]
        self.retry_budget = ready["retry_budget"]
        self.tick_stall_budget_s = ready["tick_stall_budget_s"]
        if self._transport_mode == REPLICA_MODE_SOCKET:
            # socket liveness, send side: periodic pings keep the worker's
            # router-silence watch fed, and a ping whose write breaks is
            # the broken-write death signal no status frame can deliver.
            # Stamp the handshake as the first "status" so the partition
            # detector has a baseline before the first status frame lands.
            self._status_ts = time.perf_counter()
            if self.ping_interval_s > 0:
                threading.Thread(
                    target=self._ping_loop,
                    name=f"replica-worker-ping-{replica_id}", daemon=True,
                ).start()

    def _dial_advertised(self, build_timeout_s: float):
        """Dial a REPLICA_WORKERS-advertised worker with backoff; the
        registry assigns the incarnation epoch the hello carries."""
        deadline = time.perf_counter() + max(build_timeout_s, 1.0)
        backoff = 0.25
        last: Optional[Exception] = None
        while time.perf_counter() < deadline:
            transport = None
            try:
                transport = dial(
                    self._connect_addr,
                    max_frame_bytes=self.spec.max_frame_bytes,
                    frame_timeout_s=self.spec.frame_timeout_s,
                    fault_scope=f"r{self.replica_id}",
                )
                epoch = self._registry.assign_epoch(self.replica_id)
                send_hello(transport, self.spec.auth_token, self.replica_id,
                           os.getpid(), epoch=epoch)
                return transport, epoch
            except FrameProtocolError as exc:
                # a DEFINITIVE rejection (bad token, version mismatch):
                # redialing cannot fix configuration — fail fast so the
                # operator sees the real error instead of a 10-minute
                # build timeout
                if transport is not None:
                    transport.close()
                raise ReplicaUnavailable(
                    f"advertised worker {self._connect_addr} rejected the "
                    f"handshake: {exc}",
                    retryable=False,
                    details={"replica": self.replica_id,
                             "reason": "handshake_rejected"},
                ) from exc
            except TransportError as exc:
                last = exc
                if transport is not None:
                    transport.close()
                time.sleep(min(backoff,
                               max(deadline - time.perf_counter(), 0.0)))
                backoff = min(backoff * 2.0, 5.0)
        raise ReplicaUnavailable(
            f"advertised worker {self._connect_addr} unreachable within "
            f"{build_timeout_s:.0f}s: {last}",
            retry_after_s=2.0,
            details={"replica": self.replica_id, "reason": "dial_failed"},
        )

    def _ping_loop(self) -> None:
        # with the telemetry plane on, pings carry a transmit stamp and the
        # worker pongs with its clock — each round trip is one ClockSync
        # offset sample. Telemetry off keeps the bare {} payload: the wire
        # stays byte-identical to the pre-telemetry protocol.
        stamp = self.spec.telemetry_interval_s > 0
        while True:
            time.sleep(self.ping_interval_s)
            with self._mutex:
                if self._dead or self._closed:
                    return
            try:
                self._send_frame((0, "__ping__",
                                  {"t_tx": time.perf_counter()}
                                  if stamp else {}))
            except (TransportError, OSError):
                self._on_death(
                    "worker link broken on ping (broken write)",
                    kind="partition",
                )
                return

    # ------------------------------------------------------------- plumbing

    # frame-dispatch: worker-to-router via=pipe,socket
    def _wait_ready(self, call: "_PendingCall", timeout_s: float) -> dict:
        try:
            kind, payload = call.q.get(timeout=timeout_s)
        except _queue.Empty:
            self.close()
            raise ReplicaUnavailable(
                f"worker did not come up within {timeout_s:.0f}s",
                retryable=False,
            ) from None
        if kind == _F_ERR:
            self.close()
            raise _decode_exc(payload)
        if kind != _F_READY:
            self.close()
            raise ReplicaUnavailable(
                f"worker handshake sent {kind!r} before ready",
                retryable=False,
            )
        return payload

    # frame-dispatch: worker-to-router via=pipe,socket
    def _dispatch_loop(self) -> None:
        transport = self._transport
        while True:
            try:
                got = transport.recv()
            except TransportError as exc:
                # the dispatcher owns the read side: when it exits, the
                # connection is spent — close it so a dead incarnation
                # never parks an open fd (the partition-heal window keeps
                # the transport open precisely BECAUSE this loop is still
                # draining it; once it errors out, the drain is over)
                transport.close()
                self._on_death(f"worker connection lost: {exc}")
                return
            frame, epoch = got
            if (self._registry is not None
                    and epoch != self._registry.current_epoch(
                        self.replica_id)):
                # incarnation fence: this frame was sent by a PREVIOUS
                # incarnation of the slot's worker (e.g. buffered behind a
                # partition that later healed). Its tickets are already
                # terminal router-side — delivering it could resurrect a
                # dead ticket or double-deliver a stream chunk, so it is
                # dropped and counted instead.
                self._registry.note_stale_frame(self.replica_id)
                continue
            req_id, kind, payload = frame
            if kind == _F_STATUS:
                # plain attribute writes: GIL-atomic snapshot for probes
                self._status = payload
                self._status_ts = time.perf_counter()
                continue
            if kind == _F_TELEMETRY:
                self._ingest_telemetry(payload, epoch)
                continue
            if kind == _F_PONG:
                self._ingest_pong(payload)
                continue
            if kind == _F_DEREGISTER:
                # voluntary leave: latch the request (GIL-atomic write, one
                # writer — this dispatcher); the ReplicaSet supervisor
                # observes `deregister_requested` and runs the graceful
                # retire on its own cadence
                reason = (payload or {}).get("reason", "deregister") \
                    if isinstance(payload, dict) else "deregister"
                self._deregister_reason = str(reason)
                logger.info("replica %d worker requested deregistration "
                            "(%s)", self.replica_id, reason)
                continue
            call = None
            with self._mutex:
                adopted = self._adopted.get(req_id)
                if adopted is not None:
                    if kind in (_F_ERR, _F_END) or (
                        kind == _F_OK and not adopted["streaming"]
                    ):
                        self._adopted.pop(req_id, None)
                else:
                    call = self._calls.get(req_id)
                    if call is not None and (
                        kind in (_F_ERR, _F_END, _F_READY)
                        or (kind == _F_OK and not call.streaming)
                    ):
                        self._calls.pop(req_id, None)
                    # a request leaves the shadow at its first ANSWER
                    # frame: result/err for generates, first token frame
                    # (or end/err) for streams — the open ack only means
                    # the worker built the iterator, not that it admitted
                    if kind in (_F_TOK, _F_END, _F_ERR) or (
                        kind == _F_OK
                        and call is not None and not call.streaming
                    ):
                        self._shadow.pop(req_id, None)
            if adopted is not None:
                self._finish_adopted(adopted, kind, payload)
            elif call is not None:
                call.q.put((kind, payload))

    def _on_death(self, reason: str, *, process_death: bool = True,
                  keep_shadow: Optional[bool] = None,
                  kind: str = "") -> None:
        """Latch dead and wake every waiter. Shadowed tickets are the
        exception: with handoff enabled (and the replica not closing),
        they are KEPT for the supervisor's quarantine pass to extract and
        re-admit on survivors — their callers stay blocked on the pending
        queue until the handoff sentinel arrives. ``keep_shadow=False``
        (abandon, close) fails the remainder typed instead.
        ``kind="partition"`` marks a LINK death of a possibly-live worker:
        the rebuild path then waits for re-registration (heal) before
        reaching for the reap-and-respawn hammer."""
        with self._mutex:
            if self._dead:
                return
            self._dead = True
            self._death_reason = reason
            self._death_kind = kind
            keep = (self._handoff_enabled and not self._closed
                    if keep_shadow is None else keep_shadow)
            shadow_entries: list[tuple[_Ticket, _PendingCall]] = []
            if keep:
                # shadowed callers must NOT get the typed death error —
                # their tickets are about to move to a survivor
                for rid in self._shadow:
                    self._calls.pop(rid, None)
            else:
                for rid, entry in list(self._shadow.items()):
                    self._calls.pop(rid, None)
                    shadow_entries.append(entry)
                self._shadow.clear()
            adopted = list(self._adopted.values())
            self._adopted.clear()
            pending = list(self._calls.values())
            self._calls.clear()
            closed = self._closed
        exc = self._death_error()
        payload = _encode_exc(exc)
        for call in pending:
            call.q.put((_F_ERR, payload))
        for ticket, call in shadow_entries:
            call.q.put((_F_ERR, payload))
            finish_ticket_error(ticket, exc, "failed_over")
        for state in adopted:
            # the adopting ReplicaSet already finished its handoff pass;
            # a typed terminal outcome is all the remote caller needs
            finish_ticket_error(state["ticket"], exc, "failed_over")
        if not closed:
            logger.warning("replica %d worker died: %s", self.replica_id,
                           reason)
            if process_death:
                # the worker_deaths counter feeds the respawn-loop alert
                # (SentioTpuReplicaWorkerDead) — only actual process deaths
                # count; a stall-quarantine abandon of a live worker is the
                # stall watchdog's story, not a death
                try:
                    from sentio_tpu.infra.metrics import get_metrics

                    get_metrics().record_worker_death(self.replica_id)
                except Exception:  # noqa: BLE001 — telemetry is best-effort
                    pass

    def _death_error(self) -> ReplicaUnavailable:
        # _death_reason is written exactly once (under _mutex, before _dead
        # latches true) and only read after; the lock-free read is a
        # GIL-atomic str fetch
        reason = self._death_reason or "killed"  # lint: allow(lock-discipline) — GIL-atomic read after latch
        return ReplicaUnavailable(
            f"replica worker process died: {reason}",
            retry_after_s=2.0,
            details={"replica": self.replica_id, "reason": "worker_dead"},
        )

    def _send_frame(self, frame: tuple) -> None:
        self._transport.send(frame)

    def _call(self, method: str, kwargs: dict,
              timeout_s: Optional[float],
              shadow_ticket: Optional[_Ticket] = None) -> Any:
        """One blocking RPC. A dead worker — before or during the call —
        raises the typed death error; an unresponsive worker past
        ``timeout_s`` does too (a wedged RPC loop is indistinguishable
        from a dead one, and both are replica failures the caller should
        fail over from).

        With a ``shadow_ticket`` (generates, handoff enabled) the call is
        mirrored in the shadow queue: on worker death the supervisor's
        quarantine extracts the ticket and re-admits it on a survivor —
        the ``("handoff", ticket)`` sentinel tells this caller to wait on
        the ticket's event instead, spending no failover budget."""
        call = _PendingCall()
        shadowed = False
        with self._mutex:
            if self._dead:
                raise self._death_error()
            req_id = self._next_id
            self._next_id += 1
            self._calls[req_id] = call
            if shadow_ticket is not None and self._handoff_enabled:
                shadow_ticket.shadow_id = req_id
                self._shadow[req_id] = (shadow_ticket, call)
                kwargs = {**kwargs, "shadow_id": req_id}
                shadowed = True
        t0 = time.perf_counter()
        try:
            self._send_frame((req_id, method, kwargs))
        except (TransportClosed, BrokenPipeError, OSError):
            self._on_death("worker pipe broken on send")
            if not shadowed:
                with self._mutex:
                    self._calls.pop(req_id, None)
                raise self._death_error() from None
            # shadowed: fall through to the wait — the worker never saw
            # this request, so the dead-worker extraction hands it off
            # wholesale and the sentinel below wakes us
        wait = timeout_s if timeout_s and timeout_s > 0 else None
        try:
            kind, payload = call.q.get(timeout=wait)
        except _queue.Empty:
            with self._mutex:
                self._calls.pop(req_id, None)
                # unanswered AND un-handed-off: drop the shadow so a late
                # handoff cannot execute work whose caller already left
                self._shadow.pop(req_id, None)
            raise ReplicaUnavailable(
                f"worker RPC {method!r} unanswered after {timeout_s:.0f}s",
                retry_after_s=2.0,
                details={"replica": self.replica_id, "reason": "rpc_timeout"},
            ) from None
        if kind == "handoff":
            ticket: _Ticket = payload
            remaining = (max(wait - (time.perf_counter() - t0), 1.0)
                         if wait is not None else None)
            if not ticket.event.wait(remaining):
                raise ReplicaUnavailable(
                    f"handed-off {method!r} unanswered after "
                    f"{timeout_s:.0f}s",
                    retry_after_s=2.0,
                    details={"replica": self.replica_id,
                             "reason": "handoff_timeout"},
                )
            if ticket.error is not None:
                raise ticket.error
            return ticket.result
        if kind == _F_ERR:
            raise _decode_exc(payload)
        return payload

    @staticmethod
    def _rel_deadline(deadline_s: Optional[float],
                      deadline_ts: Optional[float]) -> Optional[float]:
        """perf_counter clocks do not compare across processes: absolute
        router deadlines cross the boundary as remaining seconds. An
        ALREADY-expired deadline raises here, router-side — shipping a
        non-positive remainder would read as ``deadline_s=0``, the
        explicit no-deadline opt-out, and silently un-expire the
        request (thread mode sheds it typed at admission)."""
        if deadline_ts is not None:
            rel = deadline_ts - time.perf_counter()
            if rel <= 0:
                raise DeadlineExceededError("deadline expired before submit")
            return rel
        return deadline_s

    # ------------------------------------------------------------------ api

    def generate(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        cost_tokens: int = 0,
        seed: Optional[int] = None,
    ):
        wait = (timeout_s or self.default_timeout_s) + 30.0
        rel = self._rel_deadline(deadline_s, deadline_ts)
        shadow = None
        if self._handoff_enabled:  # lint: allow(lock-discipline) — GIL-atomic bool; _call re-checks under _mutex
            # the shadow mirror a dead-worker handoff re-admits on a
            # survivor; _call stamps shadow_id once the RPC id is known
            shadow = _Ticket(
                prompt, max_new_tokens, temperature, top_k=top_k,
                request_id=request_id, t_submit=time.perf_counter(),
                deadline_ts=(time.perf_counter() + rel
                             if rel is not None else None),
                tenant=tenant, priority=priority,
                cost_tokens=int(cost_tokens), seed=seed,
            )
        result = self._call("generate", dict(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, timeout_s=timeout_s,
            request_id=request_id,
            deadline_s=rel,
            top_k=top_k, tenant=tenant, priority=priority,
            cost_tokens=cost_tokens, seed=seed,
        ), timeout_s=wait, shadow_ticket=shadow)
        if shadow is not None and shadow.result is result:
            # handed off: the SURVIVOR already stamped its own replica_id
            return result
        result.replica_id = self.replica_id
        return result

    def generate_stream(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        cost_tokens: int = 0,
        stats_out: Optional[dict] = None,
        prior_tokens: Optional[list] = None,
        seed: Optional[int] = None,
        progress: Optional[StreamProgress] = None,
    ) -> Iterator[str]:
        """Lazy, matching thread mode: the ``stream_open`` RPC — which
        admits AND starts decoding in the worker — defers to the first
        ``next()``. ``ReplicaSet._stream_impl`` discards and re-creates
        not-yet-started iterators (WFQ overflow re-bucketing, failover) on
        the promise that doing so costs nothing; an eager open here would
        leak a phantom decode per discarded iterator. The process-mode
        delta: thread mode's CALL-time validation (top_k vs speculation)
        also moves to the first ``next()`` — the SSE handler's admission
        pre-check still runs before its 200, and a validation error past
        that surfaces as the typed mid-stream error.

        ``progress`` mirrors the token ids behind every yielded piece
        (accumulated from the worker's per-frame token-id deltas), and
        ``prior_tokens``/``seed`` ride the RPC into the worker's service —
        the full resume-by-replay surface works across the boundary."""
        wait = (timeout_s or self.default_timeout_s) + 30.0
        return self._stream_open_and_pump(dict(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, timeout_s=timeout_s,
            request_id=request_id,
            deadline_s=deadline_s, deadline_ts=deadline_ts,
            top_k=top_k, tenant=tenant, priority=priority,
            cost_tokens=cost_tokens,
            prior_tokens=(list(prior_tokens) if prior_tokens else None),
            seed=seed,
        ), wait, stats_out, progress)

    def _stream_open_and_pump(self, req: dict, wait: float,
                              stats_out: Optional[dict],
                              progress: Optional[StreamProgress],
                              ) -> Iterator[str]:
        # generator body: nothing below runs until the first next()
        abs_deadline = req["deadline_ts"]
        req["deadline_s"] = self._rel_deadline(
            req.pop("deadline_s"), req.pop("deadline_ts"))
        if abs_deadline is None and req["deadline_s"]:
            abs_deadline = time.perf_counter() + req["deadline_s"]
        call = _PendingCall(streaming=True)
        shadowed = False
        with self._mutex:
            if self._dead:
                raise self._death_error()
            req_id = self._next_id
            self._next_id += 1
            self._calls[req_id] = call
            if self._handoff_enabled:
                # the stream's shadow mirror: it leaves the shadow at its
                # first token frame (a delivered-token stream rides the
                # ReplicaSet resume path, not the handoff)
                ticket = _Ticket(
                    req["prompt"], req["max_new_tokens"],
                    req["temperature"], top_k=req["top_k"],
                    stream_q=_queue.Queue(),
                    request_id=req.get("request_id"),
                    t_submit=time.perf_counter(),
                    deadline_ts=abs_deadline,
                    tenant=req.get("tenant"), priority=req.get("priority"),
                    cost_tokens=int(req.get("cost_tokens") or 0),
                    prior_tokens=req.get("prior_tokens"),
                    seed=req.get("seed"), shadow_id=req_id,
                )
                self._shadow[req_id] = (ticket, call)
                req["shadow_id"] = req_id
                shadowed = True
        try:
            self._send_frame((req_id, "stream_open", req))
        except (TransportClosed, BrokenPipeError, OSError):
            self._on_death("worker pipe broken on send")
            if not shadowed:
                with self._mutex:
                    self._calls.pop(req_id, None)
                raise self._death_error() from None
            # shadowed: the dead-worker extraction hands the ticket off;
            # the sentinel arrives on the pending queue below
        try:
            kind, payload = call.q.get(timeout=wait)
        except _queue.Empty:
            with self._mutex:
                self._calls.pop(req_id, None)
                self._shadow.pop(req_id, None)
            raise ReplicaUnavailable(
                f"worker stream open unanswered after {wait:.0f}s",
                retry_after_s=2.0,
                details={"replica": self.replica_id, "reason": "rpc_timeout"},
            ) from None
        if kind == "handoff":
            yield from self._drain_adopted_stream(payload, wait,
                                                  stats_out, progress)
            return
        if kind == _F_ERR:
            raise _decode_exc(payload)
        yield from self._stream_frames(req_id, call, wait, stats_out,
                                       progress)

    def _stream_frames(self, req_id: int, call: _PendingCall, wait: float,
                       stats_out: Optional[dict],
                       progress: Optional[StreamProgress],
                       ) -> Iterator[str]:
        done = False
        emitted: list[int] = []
        try:
            while True:
                try:
                    kind, payload = call.q.get(timeout=wait)
                except _queue.Empty:
                    raise ReplicaUnavailable(
                        f"worker stream stalled for {wait:.0f}s",
                        retry_after_s=2.0,
                        details={"replica": self.replica_id,
                                 "reason": "rpc_timeout"},
                    ) from None
                if kind == "handoff":
                    # never-dispatched stream moved to a survivor before
                    # any token frame: nothing delivered, clean switch
                    done = True
                    yield from self._drain_adopted_stream(
                        payload, wait, stats_out, progress)
                    return
                if kind == _F_TOK:
                    piece, delta = payload
                    emitted.extend(delta)
                    if progress is not None:
                        # rebound BEFORE the yield, like the service's own
                        # mirror: a consumer observing this piece (or the
                        # death exception) reads the delivered prefix
                        progress.tokens = list(emitted)
                    yield piece
                elif kind == _F_END:
                    done = True
                    stats, final_toks = payload
                    if progress is not None and final_toks is not None:
                        progress.tokens = list(final_toks)
                    if stats_out is not None and isinstance(stats, dict):
                        stats["replica_id"] = self.replica_id
                        stats_out.update(stats)
                    return
                else:  # _F_ERR
                    done = True
                    raise _decode_exc(payload)
        finally:
            with self._mutex:
                self._calls.pop(req_id, None)
                self._shadow.pop(req_id, None)
                dead = self._dead
            if not done and not dead:
                # consumer abandoned mid-stream: tell the worker (it cancels
                # the ticket between token frames — chunk-granular)
                try:
                    self._send_frame((0, "stream_cancel",
                                      {"stream_id": req_id}))
                except (TransportClosed, BrokenPipeError, OSError):
                    pass

    def _drain_adopted_stream(self, ticket: _Ticket, wait: float,
                              stats_out: Optional[dict],
                              progress: Optional[StreamProgress],
                              ) -> Iterator[str]:
        """Consume a stream ticket a survivor adopted: the survivor's pump
        (thread mode) or this class's adopt dispatcher (process mode)
        feeds ``ticket.stream_q`` with the service queue vocabulary. Only
        never-dispatched tickets are handed off, so nothing was delivered
        yet and decoding starts clean — same UTF-8 withholding as the
        service's own stream impl."""
        tokenizer = self._tokenizer
        emitted: list[int] = []
        flushed = ""
        done = False
        try:
            while True:
                try:
                    kind, payload = ticket.stream_q.get(timeout=wait)
                except _queue.Empty:
                    raise ReplicaUnavailable(
                        f"handed-off stream stalled for {wait:.0f}s",
                        retry_after_s=2.0,
                        details={"replica": self.replica_id,
                                 "reason": "handoff_timeout"},
                    ) from None
                if kind == "err":
                    done = True
                    raise payload
                if kind == "toks":
                    emitted.extend(payload)
                else:  # "done"
                    done = True
                    result = payload
                    if result.finish_reason == "error":
                        raise ReplicaUnavailable(
                            "paged decode failed mid-stream",
                            retry_after_s=2.0,
                            details={"replica": self.replica_id,
                                     "reason": "mid_stream"},
                        )
                    emitted = list(result.tokens)
                    if stats_out is not None:
                        stats_out.update(result.stats_dict())
                if progress is not None:
                    progress.tokens = list(emitted)
                text = tokenizer.decode(emitted)
                if kind == "done":
                    if len(text) > len(flushed):
                        yield text[len(flushed):]
                    return
                safe = text[:-1] if text.endswith("�") else text
                if len(safe) > len(flushed):
                    yield safe[len(flushed):]
                    flushed = safe
        finally:
            # consumer abandoned: in thread mode the adopting service's
            # pump reads this flag at its next loop; in process mode the
            # adopting replica's dispatcher observes it at the next token
            # frame and forwards a chunk-granular stream_cancel to its
            # worker. An EXPIRED ticket is left for the deadline sweep,
            # which counts it as expired — marking it cancelled here
            # would misfile a deadline miss under caller-abandoned (same
            # rule as the service's own stream impl)
            if not done and not (
                ticket.deadline_ts is not None
                and time.perf_counter() >= ticket.deadline_ts
            ):
                ticket.cancelled = True

    def check_admission(self, deadline_ts: Optional[float] = None) -> None:
        self._call("check_admission", {
            "deadline_rel_s": self._rel_deadline(None, deadline_ts),
        }, timeout_s=10.0)

    def _peek_prefix(self, toks) -> int:
        """Routing probe; MUST never fail OR stall a request — unlike
        thread mode's in-memory radix read this is a pipe RPC, and it sits
        on every incoming request's routing path. A worker whose status
        frames have gone stale is slow or wedged, so skip the RPC entirely
        (reads as a cold cache and the router routes elsewhere); a healthy
        worker answers from a handler thread in milliseconds, so the short
        timeout bounds the set-wide routing cost of a not-yet-detected
        wedge instead of stacking multi-second waits per replica."""
        stale_after = max(10 * self.spec.status_interval_s, 0.5)
        if (self._status_ts <= 0.0
                or time.perf_counter() - self._status_ts > stale_after):
            return 0
        try:
            return int(self._call("peek_prefix", {"toks": list(toks)},
                                  timeout_s=0.5))
        except Exception:  # noqa: BLE001 — prefix peek is an optional admission hint
            return 0

    def warmup(self, max_new_tokens: int = 4) -> dict:
        return self._call("warmup", {"max_new_tokens": max_new_tokens},
                          timeout_s=self.build_timeout_s)

    def backlog(self) -> int:
        return int(self._status.get("backlog") or 0)

    def projected_wait(self) -> Optional[float]:
        return self._status.get("projected_wait")

    def heartbeat_age(self) -> Optional[float]:
        """Worker-reported pump heartbeat age plus the status frame's own
        staleness. A worker whose status frames STOPPED while RPCs are in
        flight is itself wedged — that staleness is the age (the router's
        watchdog must detect a dead worker-side loop exactly like a dead
        pump)."""
        with self._mutex:
            if self._dead:
                return None
            pending = len(self._calls)
        if self._status_ts <= 0.0:
            return None
        stale = time.perf_counter() - self._status_ts
        age = self._status.get("heartbeat_age")
        if age is not None:
            return float(age) + stale
        interval = max(self.spec.status_interval_s, 0.02)
        if pending > 0 and stale > max(10 * interval, 2.0):
            return stale
        return None

    def duty_cycle(self) -> dict:
        return self._status.get("duty_cycle") or {
            "host": 0.0, "device": 0.0, "idle": 1.0,
        }

    def reset_duty_cycle(self) -> None:
        try:
            self._call("reset_duty_cycle", {}, timeout_s=10.0)
        except Exception:  # noqa: BLE001 — telemetry re-basing, best-effort
            pass

    @property
    def broken(self) -> bool:
        with self._mutex:
            if self._dead:
                return True
        if self._proc is not None and not self._proc.is_alive():
            self._on_death(f"worker exited (code {self._proc.exitcode})")
            return True
        if (self._transport_mode == REPLICA_MODE_SOCKET
                and self.partition_timeout_s > 0 and self._status_ts > 0):
            # transport-liveness leg the pipe never needed: a half-open
            # partition delivers no EOF and no broken write on THIS side —
            # the only observable is the worker's status stream going
            # silent. Staleness past the budget latches the same typed
            # death the supervisor's quarantine machinery already handles;
            # the (possibly live) worker rejoins as a fresh incarnation.
            stale = time.perf_counter() - self._status_ts
            if stale > self.partition_timeout_s:
                self._on_death(
                    f"partition suspected: no worker frames for "
                    f"{stale:.1f}s (budget {self.partition_timeout_s:.1f}s)",
                    process_death=False, kind="partition",
                )
                return True
        return bool(self._status.get("broken"))

    @property
    def closed(self) -> bool:
        with self._mutex:
            if self._closed:
                return True
        return bool(self._status.get("closed"))

    @property
    def deregister_requested(self) -> Optional[str]:
        """Reason string of this worker's voluntary deregister frame, or
        None. The ReplicaSet supervisor polls it to trigger a graceful
        retire (GIL-atomic read of a single-writer attribute)."""
        return self._deregister_reason

    def request_leave(self, reason: str = "leave") -> None:
        """Ask the worker to emit its voluntary deregister frame (drills /
        operator scale-in through the worker): the worker keeps serving;
        the supervisor's retire pass does the drain + handoff + close."""
        self._call("leave", {"reason": reason}, timeout_s=10.0)

    @property
    def tick_failure_count(self) -> int:
        return int(self._status.get("tick_failure_count") or 0)

    @property
    def pump_leaked_count(self) -> int:
        return int(self._status.get("pump_leaked") or 0)

    @property
    def pid(self) -> Optional[int]:
        if self._proc is not None:
            return self._proc.pid
        # dialed remote worker: no local process handle — the worker
        # reported its pid in the handshake/status stream
        return self._status.get("pid")

    def _proc_alive(self) -> bool:
        """Best liveness guess for the WORKER (not the link): a local
        process handle answers exactly; a dialed remote worker is presumed
        alive until its link death says otherwise."""
        if self._proc is not None:
            return self._proc.is_alive()
        with self._mutex:
            return not self._dead

    def _transport_stats(self) -> dict:
        if self._transport_mode != REPLICA_MODE_SOCKET:
            return {}
        out = {"transport": "socket", "incarnation": self.epoch}
        if self._registry is not None:
            out["stale_frames"] = self._registry.stale_frames(
                self.replica_id)
        return out

    def stats(self) -> dict:
        try:
            self._last_stats = self._call("stats", {}, timeout_s=10.0)
        except Exception:  # noqa: BLE001 — dead replica: last known stats
            out = {**self._last_stats, **self._transport_stats(),
                   "replica": self.replica_id, "worker_dead": 1}
            # a dead/partitioned worker's last telemetry frame still holds
            # its cumulative phase ledger — fleet duty math keeps counting
            # the seconds it actually burned instead of zeroing them
            cached = (self._telemetry.get("stats")
                      if self._telemetry else None) or {}
            for key in ("phase_seconds", "duty_elapsed_s", "duty_cycle"):
                if key not in out and key in cached:
                    out[key] = cached[key]
            return out
        self._last_stats.update(self._transport_stats())
        self._last_stats.update(self._clock_stats())
        return self._last_stats

    # ------------------------------------------------ fleet telemetry plane

    def _ingest_telemetry(self, payload: dict, epoch: int) -> None:
        """Dispatcher-thread sink for unsolicited telemetry frames: merge
        the worker's cumulative series snapshot into the router collector
        (epoch-fenced there — a healed worker's pre-partition buffer must
        not double-count), then cache the frame for stats overlays and
        zero the telemetry-age clock."""
        from sentio_tpu.infra.metrics import get_metrics

        metrics = get_metrics()
        try:
            res = metrics.merge_worker_series(
                self.replica_id, payload.get("series") or {},
                epoch=epoch, pid=payload.get("pid"))
        except Exception:  # noqa: BLE001 — telemetry must not kill dispatch
            logger.debug("replica %d telemetry merge failed",
                         self.replica_id, exc_info=True)
            return
        if not res.get("accepted"):
            return
        self._telemetry = payload
        self._telemetry_ts = time.perf_counter()
        origin = payload.get("origin_s")
        if origin is not None:
            # baselined cross-thread-race: dispatcher (telemetry/pong) and
            # caller (fetch_flight) both stamp this; it is a last-write-wins
            # float consumed only for trace re-basing, where the freshest
            # origin is always acceptable and a torn update is impossible
            # (attribute stores are GIL-atomic)
            self._worker_origin_s = float(origin)
        try:
            metrics.record_telemetry_age(self.replica_id, 0.0)
            stats = payload.get("stats") or {}
            for key in ("pool_hbm_bytes", "free_pages", "active_slots",
                        "queued"):
                if stats.get(key) is not None:
                    metrics.set_replica_stat(self.replica_id, key,
                                             float(stats[key]))
        except Exception:  # noqa: BLE001 — gauges are best-effort
            pass

    def _ingest_pong(self, payload: dict) -> None:
        """Pong for a timestamped ping: one NTP-style clock sample.
        ``offset = t_worker − (t_tx + rtt/2)`` inside ClockSync; the
        worker's flight origin rides along for trace re-basing."""
        try:
            self._clock.add_sample(float(payload["t_tx"]),
                                   time.perf_counter(),
                                   float(payload["t_worker"]))
            origin = payload.get("origin_s")
            if origin is not None:
                self._worker_origin_s = float(origin)
        except (KeyError, TypeError, ValueError):
            pass

    def clock_sync(self) -> Optional[dict]:
        """Current clock-offset estimate (min-RTT sample) or None before
        the first pong/fetch round trip."""
        return self._clock.estimate()

    def telemetry_age(self) -> Optional[float]:
        """Seconds since the last ACCEPTED telemetry frame, or None if the
        worker never shipped one (telemetry off, or pre-first-frame)."""
        if self._telemetry_ts <= 0:
            return None
        return time.perf_counter() - self._telemetry_ts

    def _clock_stats(self) -> dict:
        out: dict = {}
        age = self.telemetry_age()
        if age is not None:
            out["telemetry_age_s"] = round(age, 3)
        est = self._clock.estimate()
        if est is not None:
            out["clock_offset_s"] = round(est["offset_s"], 6)
            out["clock_uncertainty_s"] = round(est["uncertainty_s"], 6)
        return out

    def fetch_flight(self, request_id: Optional[str] = None,
                     last: Optional[int] = None,
                     timeout_s: float = 5.0) -> dict:
        """Pull flight data from the worker on demand: one request's
        record (``request_id``) or the whole tick window + record table.
        The reply echoes our transmit stamp, so every fetch doubles as a
        clock sample — pipe mode (no ping loop) gets its alignment here.
        Raises the replica's typed death error when the worker is gone."""
        reply = self._call(
            "fetch_flight",
            {"request_id": request_id, "last": last,
             "t_tx": time.perf_counter()},
            timeout_s=timeout_s)
        t_rx = time.perf_counter()
        try:
            if reply.get("t_tx") is not None:
                self._clock.add_sample(float(reply["t_tx"]), t_rx,
                                       float(reply["t_worker"]))
            if reply.get("origin_s") is not None:
                self._worker_origin_s = float(reply["origin_s"])
        except (TypeError, ValueError, KeyError):
            pass
        reply["replica"] = self.replica_id
        reply["epoch"] = self.epoch
        reply["clock"] = self._clock.estimate()
        return reply

    def cached_flight_lane(self, router_origin_s: float,
                           status: str) -> dict:
        """Fleet-trace lane for THIS incarnation built from the cached
        last telemetry frame — used when the worker is DEAD or RETIRED
        and ``fetch_flight`` can no longer answer. The 1 Hz telemetry
        frame ships counters rather than tick tables, so the lane is
        usually name-only; the point is that the incarnation still
        appears on the fleet timeline, marked ``(retired)``/``(dead)``,
        instead of silently vanishing from history."""
        shift, bound = self.flight_shift_s(router_origin_s)
        flight = (self._telemetry or {}).get("flight")
        ticks: list = []
        records: list = []
        if isinstance(flight, dict):
            ticks = list(flight.get("ticks") or [])
            records = list(flight.get("records") or [])
        return {
            "replica": self.replica_id,
            "epoch": self.epoch,
            "shift_s": shift,
            "uncertainty_s": bound,
            "ticks": ticks,
            "records": records,
            "status": status,
        }

    def flight_shift_s(self, router_origin_s: float) -> tuple:
        """``(shift_s, uncertainty_s)`` mapping this worker's flight
        timeline onto the router's: ``t_router = t_worker_timeline +
        shift``. Both recorders stamp relative to their own perf_counter
        origin, so the shift is ``worker_origin − offset − router_origin``
        (offset = worker clock minus router clock). Same-host Linux
        processes share CLOCK_MONOTONIC, so offset ≈ 0 and the shift is
        dominated by the origin difference. Uncertainty is None until a
        clock sample exists (shift then assumes offset 0)."""
        if self._worker_origin_s is None:
            return 0.0, None
        est = self._clock.estimate()
        offset = est["offset_s"] if est else 0.0
        shift = self._worker_origin_s - offset - router_origin_s
        return shift, (est["uncertainty_s"] if est else None)

    # ------------------------------------------------ quarantine / handoff

    def enable_shadow_handoff(self) -> None:
        """Arm router-side ticket shadowing (module docstring). Called by a
        SUPERVISING ReplicaSet: without a supervisor nobody would ever
        extract the shadow queue, so the default stays passive and worker
        death keeps its fail-fast typed surface."""
        with self._mutex:
            self._handoff_enabled = True

    def _pop_shadow(self, ids: Optional[list] = None) -> list:
        """Remove shadowed tickets (all of them, or exactly ``ids``) for
        handoff, wake their callers with the ``("handoff", ticket)``
        sentinel, and drop their pending-call registrations so a straggler
        frame from the old worker cannot double-answer."""
        entries: list[tuple[_Ticket, _PendingCall]] = []
        with self._mutex:
            take = (list(self._shadow.keys()) if ids is None
                    else [i for i in ids if i in self._shadow])
            for rid in take:
                entries.append(self._shadow.pop(rid))
                self._calls.pop(rid, None)
        out = []
        for ticket, call in entries:
            call.q.put(("handoff", ticket))
            out.append(ticket)
        return out

    def _fail_shadow(self, exc: ReplicaUnavailable) -> None:
        """Terminal typed outcome for any shadow/adopted residue — close()
        safety net for a death that latched with the shadow kept but whose
        handoff never came."""
        with self._mutex:
            entries = list(self._shadow.values())
            self._shadow.clear()
            adopted = list(self._adopted.values())
            self._adopted.clear()
        payload = _encode_exc(exc)
        for ticket, call in entries:
            call.q.put((_F_ERR, payload))
            finish_ticket_error(ticket, exc, "failed_over")
        for state in adopted:
            finish_ticket_error(state["ticket"], exc, "failed_over")

    def abandon(self, reason: str) -> list:
        """Stall-quarantine surface: ask the worker (its RPC loop survives a
        wedged pump) to abandon — admitted tickets fail typed in-worker,
        which unblocks their router-side RPCs with the typed error, and the
        never-dispatched inbox tickets come back BY SHADOW ID for handoff —
        then latch dead locally so every later call fails fast. Remaining
        shadowed work (mid-decode on the wedged worker) keeps its normal
        typed-failover path."""
        with self._mutex:
            dead = self._dead
            enabled = self._handoff_enabled
        ids: Optional[list] = None
        if not dead:
            try:
                ids = self._call("abandon", {"reason": reason},
                                 timeout_s=10.0)
            except Exception as exc:  # noqa: BLE001 — latch + hand off below
                # a systematically failing abandon RPC must be diagnosable,
                # not silent: one WARNING naming the worker (satellite fix)
                logger.warning(
                    "replica %d worker abandon RPC failed (%s: %s); "
                    "latching dead and handing off every shadowed ticket",
                    self.replica_id, type(exc).__name__, exc,
                )
        # RPC failed or worker already dead: ids=None hands off EVERY
        # unanswered shadowed ticket (a dead worker cannot say which had
        # dispatched; re-executed generates are idempotent caller-side)
        tickets = self._pop_shadow(ids) if enabled else []
        alive = self._proc_alive()
        self._on_death(f"abandoned: {reason}", process_death=not alive,
                       keep_shadow=False)
        return tickets

    def extract_inbox(self) -> list:
        """Quarantine handoff surface. A LIVE worker answers a
        bounded-timeout ``extract_inbox`` RPC naming exactly its
        never-dispatched inbox tickets (mid-decode work keeps its typed
        failover path); a dead (or unresponsive) worker hands off every
        unanswered shadowed ticket wholesale — the module-docstring
        re-execution contract."""
        with self._mutex:
            enabled = self._handoff_enabled
            dead = self._dead
        if not enabled:
            return []
        alive = not dead and self._proc_alive()
        ids: Optional[list] = None
        if alive:
            try:
                ids = self._call("extract_inbox", {}, timeout_s=10.0)
            except Exception:  # noqa: BLE001 — unresponsive == dead here
                logger.warning(
                    "replica %d extract_inbox RPC failed; handing off "
                    "every shadowed ticket", self.replica_id,
                )
                ids = None
        return self._pop_shadow(ids)

    def adopt(self, ticket: _Ticket) -> None:
        """Admit a ticket handed off from a quarantined sibling replica:
        re-register it against THIS worker's pipe. The original caller
        still blocks on the ticket (event for generates, ``stream_q`` for
        streams); the adopt dispatcher finishes the ticket from the
        worker's answer frames — no failover budget spent caller-side.
        Typed sheds surface synchronously (the handoff layer turns them
        into the ticket's terminal outcome)."""
        # the worker's own admission rules, checked without reserving —
        # raises the same typed errors a fresh submit would
        self.check_admission(ticket.deadline_ts)
        streaming = ticket.stream_q is not None
        req = dict(
            prompt=ticket.prompt, max_new_tokens=ticket.max_new_tokens,
            temperature=ticket.temperature, top_k=ticket.top_k,
            timeout_s=None, request_id=ticket.request_id,
            deadline_s=self._rel_deadline(None, ticket.deadline_ts),
            tenant=ticket.tenant, priority=ticket.priority,
            cost_tokens=ticket.cost_tokens, seed=ticket.seed,
        )
        if streaming:
            req["prior_tokens"] = ticket.prior_tokens
        with self._mutex:
            if self._dead:
                raise self._death_error()
            req_id = self._next_id
            self._next_id += 1
            req["shadow_id"] = req_id
            self._adopted[req_id] = {
                "ticket": ticket, "emitted": [], "streaming": streaming,
                "req_id": req_id,
            }
        try:
            self._send_frame(
                (req_id, "stream_open" if streaming else "generate", req))
        except (TransportClosed, BrokenPipeError, OSError):
            with self._mutex:
                self._adopted.pop(req_id, None)
            self._on_death("worker pipe broken on adopt send")
            raise self._death_error() from None

    def _finish_adopted(self, state: dict, kind: str, payload) -> None:
        """Adopt-dispatcher leg of :meth:`adopt`: translate the worker's
        answer frames into the ticket's terminal state. Runs on the
        dispatcher thread; the ticket is exclusively this replica's (its
        old service is dead), so no lock applies."""
        ticket: _Ticket = state["ticket"]
        if kind == _F_OK:
            if state["streaming"]:
                return  # stream open ack: admission is still in flight
            result = payload
            result.replica_id = self.replica_id
            if ticket.event.is_set():
                return
            ticket.result = result
            ticket.event.set()
        elif kind == _F_TOK:
            _piece, delta = payload
            state["emitted"].extend(delta)
            if ticket.cancelled and not state.get("cancel_sent"):
                # the consumer abandoned the adopted stream: no pump on
                # THIS side ever reads ticket.cancelled (the flag is set
                # by the dead replica's drain loop), so forward the
                # worker's chunk-granular stream cancel — same frame a
                # directly-owned abandoned stream sends — instead of
                # decoding the rest of the budget for nobody
                state["cancel_sent"] = True
                try:
                    self._send_frame((0, "stream_cancel",
                                      {"stream_id": state["req_id"]}))
                except (TransportClosed, BrokenPipeError, OSError):
                    pass
            if ticket.stream_q is not None:
                ticket.stream_q.put(("toks", list(delta)))
        elif kind == _F_END:
            stats, final_toks = payload
            stats = stats if isinstance(stats, dict) else {}
            result = PagedResult(
                request_id=-1, text="",
                tokens=list(final_toks if final_toks is not None
                            else state["emitted"]),
                prompt_tokens=0,
                finish_reason=str(stats.get("finish_reason") or "stop"),
                logprob_sum=float(stats.get("logprob_sum") or 0.0),
                logprob_min=float(stats.get("logprob_min") or 0.0),
                logprob_count=int(stats.get("logprob_count") or 0),
                replica_id=self.replica_id,
            )
            if ticket.event.is_set():
                return
            ticket.result = result
            if ticket.stream_q is not None:
                ticket.stream_q.put(("done", result))
            ticket.event.set()
        else:  # _F_ERR
            exc = _decode_exc(payload)
            if not isinstance(exc, Exception):
                exc = RuntimeError(str(exc))
            finish_ticket_error(ticket, exc, "failed_over")

    # ------------------------------------------------------------ lifecycle

    def respawn(self) -> "ProcessReplica":
        """A fresh worker incarnation — the supervisor's rebuild path
        (``ReplicaSet._rebuild`` duck-types this instead of
        ``engine.spawn_fresh()``). Pipe mode always spawns a fresh
        process. Socket mode decides:

        * **heal** — the (possibly live, link-partitioned) worker already
          re-registered, or does so within ``heal_grace_s``: adopt the new
          connection + epoch and keep the process (its engine, radix
          cache, and warm compiles survive the partition);
        * **respawn** — no re-registration in time: reap the old process
          (SIGTERM→SIGKILL) and spawn a fresh one, which self-registers;
        * **reconnected** — a dialed ``REPLICA_WORKERS`` worker: the
          router cannot spawn remotely, so 'respawn' duck-types to
          redialing with backoff (re-registration from the router's
          side); a still-unreachable worker surfaces the typed error and
          rides the supervisor's existing rebuild backoff."""
        if self._transport_mode == REPLICA_MODE_SOCKET:
            fresh = self._respawn_socket()
        else:
            fresh = ProcessReplica(
                self.spec, self._tokenizer, replica_id=self.replica_id,
                build_timeout_s=self.build_timeout_s,
            )
        with self._mutex:
            enabled = self._handoff_enabled
        if enabled:
            # the supervising set armed shadowing at construction; the
            # respawned incarnation inherits it (the set only enables
            # replicas it was BUILT with)
            fresh.enable_shadow_handoff()
        return fresh

    def _respawn_socket(self) -> "ProcessReplica":
        common = dict(
            replica_id=self.replica_id,
            build_timeout_s=self.build_timeout_s,
            transport_mode=REPLICA_MODE_SOCKET,
            registry=self._registry,
            partition_timeout_s=self.partition_timeout_s,
            ping_interval_s=self.ping_interval_s,
            heal_grace_s=self.heal_grace_s,
        )
        if self._connect_addr is not None:
            if self._transport is not None:
                self._transport.close()  # the dead link's fd, if still open
            fresh = ProcessReplica(self.spec, self._tokenizer,
                                   connect_addr=self._connect_addr, **common)
            outcome = "reconnected"
        else:
            adopt = None
            if (self._proc is not None and self._proc.is_alive()
                    and self.heal_grace_s > 0):
                try:
                    transport, _hello, epoch = (
                        self._registry.await_registration(
                            self.replica_id, self.heal_grace_s))
                    adopt = {"proc": self._proc, "transport": transport,
                             "epoch": epoch}
                except ReplicaUnavailable:
                    adopt = None
            if adopt is not None:
                fresh = ProcessReplica(self.spec, self._tokenizer,
                                       _adopt_state=adopt, **common)
                outcome = "heal"
                logger.info(
                    "replica %d healed: worker pid %s re-registered at "
                    "epoch %d", self.replica_id, fresh.pid, fresh.epoch)
            else:
                # the heal never came: the old link is spent for good —
                # close it (a dispatcher wedged in a silent recv would
                # otherwise park the fd forever) and reap the process
                if self._transport is not None:
                    self._transport.close()
                self._reap(join_timeout_s=5.0)
                fresh = ProcessReplica(self.spec, self._tokenizer, **common)
                outcome = "respawn"
        try:
            from sentio_tpu.infra.metrics import get_metrics

            get_metrics().record_worker_reconnect(outcome)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass
        return fresh

    def _reap(self, join_timeout_s: float = 5.0) -> None:
        """Make sure the local worker process is gone: join a corpse,
        SIGTERM→SIGKILL a survivor. No-op for dialed remote workers."""
        proc = self._proc
        if proc is None:
            return
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=max(join_timeout_s, 0.5))
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=max(join_timeout_s, 0.5))
        if not proc.is_alive():
            proc.join(timeout=0.1)  # reap the zombie entry

    def kill(self) -> None:
        """SIGKILL the worker — the chaos drill's real replica death. The
        dispatcher observes the broken pipe and fails all in-flight RPCs
        typed; the supervisor sees ``broken`` and respawns."""
        if self._proc is not None and self._proc.pid:
            try:
                os.kill(self._proc.pid, signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass

    def inject_fault(self, point: str, **rule_kwargs) -> None:
        """Arm a fault rule INSIDE the worker process (its faults registry
        is process-private). ``kill_process=True`` at e.g. ``paged.step``
        makes the next decode tick a real SIGKILL mid-dispatch."""
        self._call("inject_fault", {"point": point, **rule_kwargs},
                   timeout_s=10.0)

    def reset_faults(self) -> None:
        try:
            self._call("reset_faults", {}, timeout_s=10.0)
        except Exception:  # noqa: BLE001 — the worker may already be dead
            pass

    def _heal_candidate(self) -> bool:
        """True when the right rebuild move is to AWAIT this live,
        link-partitioned worker's re-registration instead of reaping it:
        socket-spawned, reconnect-armed, died of a partition, and the
        process is demonstrably still alive."""
        with self._mutex:
            dead, kind = self._dead, self._death_kind
        return (self._transport_mode == REPLICA_MODE_SOCKET
                and self._connect_addr is None
                and self.spec.reconnect
                and dead and kind == "partition"
                and self._proc is not None and self._proc.is_alive())

    def drain(self, deadline_s: float = 30.0) -> dict:
        """Worker-side graceful drain, then local close. A dead worker
        drains vacuously (its backlog died with it). A PARTITIONED worker
        that may heal is special: no shutdown frame (the half-open link
        may still deliver it and kill a worker about to re-register), no
        reap, transport left open so the dispatcher can drain — and
        stale-count — the pre-partition frames when the link unwedges."""
        heal = self._heal_candidate()
        result = {"drained": False, "abandoned": 0}
        if not heal:
            try:
                result = self._call("drain", {"deadline_s": deadline_s},
                                    timeout_s=deadline_s + 30.0)
            except Exception:  # noqa: BLE001 — dead worker: nothing to drain
                pass
        self.close(join_timeout_s=max(deadline_s, 1.0), reap=not heal)
        return result

    def close(self, join_timeout_s: float = 10.0, reap: bool = True) -> None:
        """Shut the worker down and REAP it: graceful shutdown frame, then
        SIGTERM, then SIGKILL — close() never returns with the child still
        runnable, so a closed set cannot leak orphan processes. (Dialed
        remote workers have no local process: their shutdown frame closes
        the CONNECTION; ``worker_serve`` keeps the worker alive for its
        operator.)

        ``reap=False`` is the rebuild path's partition-heal window: the
        worker process stays alive to re-register, and the old transport
        stays open so buffered pre-partition frames drain into the stale-
        frame fence instead of vanishing. ``respawn()`` reaps if the heal
        never comes; a later full ``close()`` reaps regardless."""
        with self._mutex:
            self._closed = True
        proc = self._proc
        if reap:
            try:
                self._send_frame((0, "__shutdown__", {}))
            except (TransportClosed, BrokenPipeError, OSError):
                pass
            if proc is not None:
                proc.join(timeout=max(join_timeout_s, 0.5))
                if proc.is_alive():
                    proc.terminate()
                    proc.join(timeout=5.0)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5.0)
            if self._transport is not None:
                self._transport.close()
        self._on_death("closed", keep_shadow=False)
        # a death that latched EARLIER kept the shadow for a handoff that
        # never came — a closed replica can never hand off, so fail the
        # residue typed instead of leaving callers to their timeouts
        self._fail_shadow(ReplicaUnavailable(
            "replica worker closed before handoff",
            retry_after_s=2.0,
            details={"replica": self.replica_id, "reason": "closed"},
        ))
