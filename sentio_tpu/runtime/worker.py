"""Process-level replica workers: one engine+service+pump per OS process.

The thread-mode ReplicaSet (runtime/replica.py) made the replica a complete
*logical* failure domain — health state machine, breakers, watchdog, inbox
handoff — but all N pumps share one Python process, so a "replica kill" is
an injected exception and N dispatches contend for one GIL (BENCH_r08's GIL
probe measured a 0.978 scaling ratio at 1→2 in-process replicas). This
module promotes the replica to a real **OS-level** failure domain, the way
production inference stacks isolate engine crashes from the frontend
(vLLM's engine-per-process serving, Orca-style continuous-batching
workers):

* :func:`worker_main` runs in a child process (**spawn** start method —
  JAX is not fork-safe: a fork duplicates its runtime threads' locks in a
  held state and the child deadlocks on the first dispatch) and owns a
  private ``ContinuousBatchingEngine`` + ``PagedGenerationService`` +
  pump thread. It serves a small RPC protocol over the spawn pipe
  (``multiprocessing.Pipe`` — length-prefixed pickle frames) and pushes
  unsolicited **status frames** (heartbeat age, backlog, breaker signals)
  at a fixed cadence so the router's supervisor probes never pay an RPC
  round trip.
* :class:`ProcessReplica` is the router-side shim: it presents the same
  ``generate / generate_stream / check_admission / peek_prefix / warmup /
  drain / stats / close`` surface as a ``PagedGenerationService``, so
  ``ReplicaSet`` routing, WFQ, affinity, health supervision, and failover
  drive it **unchanged**. Streaming arrives as incremental token frames;
  worker death (``SIGKILL``, OOM-kill, crash) surfaces as broken-pipe /
  ``proc.is_alive()`` and every in-flight RPC fails with a typed
  :class:`ReplicaUnavailable` — callers spend their normal failover
  budget, exactly as if an in-process replica had latched broken.
* the supervisor rebuilds a dead replica by **respawning the process**
  (:meth:`ProcessReplica.respawn` — the ``ReplicaSet._rebuild`` path
  duck-types it), with the existing exponential backoff and rebuild
  worker pool carrying over.
* weights are mapped **once per host**: a checkpoint loaded with
  ``load_pytree(..., mmap=True)`` memory-maps the uncompressed ``.npy``
  members of ``arrays.npz`` in place, so N workers reading the same
  checkpoint share the page cache instead of holding N private host
  copies (runtime/checkpoint.py stores ``np.savez`` zips uncompressed
  precisely so this works).

Deliberate semantic deltas from thread mode, all documented here:

* **no cross-process inbox handoff** — a dead worker's never-dispatched
  tickets live in its process; their callers' blocked RPCs fail typed and
  ride the normal failover budget instead of the zero-cost handoff
  (:meth:`ProcessReplica.extract_inbox` returns ``[]``).
* **stream cancellation propagates at chunk granularity** — closing the
  router-side iterator sends a cancel frame; the worker notices between
  token frames, so an abandoned stream decodes at most one more chunk.
* **compile fences are per-process** — worker compiles never trip the
  router's fence; ``set_fence_exempt`` on the engine facade is a no-op.
"""

from __future__ import annotations

import logging
import os
import pickle
import queue as _queue
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from sentio_tpu.infra.exceptions import (
    DeadlineExceededError,
    ReplicaUnavailable,
    SentioError,
)

logger = logging.getLogger(__name__)

__all__ = [
    "WorkerSpec",
    "ProcessReplica",
    "worker_main",
    "default_service_factory",
    "REPLICA_MODE_THREAD",
    "REPLICA_MODE_PROCESS",
]

REPLICA_MODE_THREAD = "thread"
REPLICA_MODE_PROCESS = "process"

# worker → router frame kinds (req_id 0 is reserved for unsolicited frames)
_F_READY = "ready"
_F_STATUS = "status"
_F_OK = "ok"
_F_ERR = "err"
_F_TOK = "tok"
_F_END = "end"


@dataclass(frozen=True)
class WorkerSpec:
    """Everything a spawned worker needs to build its replica. Must be
    picklable: the spawn start method ships it through the process pipe.

    ``factory`` is a ``"module:function"`` path resolved **inside the
    worker** — it returns a ready ``PagedGenerationService``. The default
    (:func:`default_service_factory`) builds a llama/moe engine from a
    checkpoint path (mmap-shared across workers) or a seeded random init;
    tests point it at tiny configs through ``factory_kwargs``."""

    factory: str = "sentio_tpu.runtime.worker:default_service_factory"
    factory_kwargs: dict = field(default_factory=dict)
    # cadence of unsolicited status frames (the router-side supervisor's
    # probe source); also bounds how stale a liveness read can be
    status_interval_s: float = 0.1


def _resolve_factory(path: str):
    import importlib

    mod_name, _, fn_name = path.partition(":")
    if not fn_name:
        raise ValueError(f"factory {path!r} is not 'module:function'")
    return getattr(importlib.import_module(mod_name), fn_name)


def default_service_factory(
    model_family: str = "llama",
    model_config: Optional[dict] = None,
    checkpoint_path: str = "",
    tokenizer_path: str = "",
    draft_checkpoint_path: str = "",
    rng_seed: int = 0,
    engine_kwargs: Optional[dict] = None,
    service_kwargs: Optional[dict] = None,
    warm_prefix_text: str = "",
) -> Any:
    """Build the worker's engine+service. With a ``checkpoint_path`` the
    params are loaded **memory-mapped** so sibling workers on the same host
    share one page-cache copy; without one, a seeded random init keeps all
    replicas' weights identical (the test / offline-dev mode). A
    ``draft_checkpoint_path`` arms paged speculation inside the worker —
    the draft loads here, in the worker process, mmap-shared like the
    target weights."""
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine
    from sentio_tpu.runtime.service import PagedGenerationService

    params = tokenizer = None
    cfg = None
    if checkpoint_path:
        from sentio_tpu.runtime.weights import load_model

        params, cfg, tokenizer = load_model(
            checkpoint_path,
            expect_family=model_family,
            tokenizer_path=tokenizer_path,
            mmap=True,
        )
    elif model_config is not None:
        if model_family == "moe":
            from sentio_tpu.models.moe import MoeConfig

            cfg = MoeConfig(**model_config)
        else:
            from sentio_tpu.models.llama import LlamaConfig

            cfg = LlamaConfig(**model_config)
    engine_kwargs = dict(engine_kwargs or {})
    if draft_checkpoint_path:
        from sentio_tpu.runtime.weights import load_model

        draft_params, draft_cfg, _ = load_model(
            draft_checkpoint_path, expect_family="llama", mmap=True,
        )
        engine_kwargs.setdefault("draft_params", draft_params)
        engine_kwargs.setdefault("draft_config", draft_cfg)
    engine = ContinuousBatchingEngine(
        model_config=cfg,
        params=params,
        tokenizer=tokenizer,
        rng_seed=rng_seed,
        **engine_kwargs,
    )
    if warm_prefix_text:
        engine.warm_prefix(warm_prefix_text)
    return PagedGenerationService(engine, **(service_kwargs or {}))


# --------------------------------------------------------------------------
# exception codec: typed errors must survive the process boundary

def _encode_exc(exc: BaseException) -> dict:
    data = {
        "cls": type(exc).__name__,
        "module": type(exc).__module__,
        "message": str(exc),
    }
    if isinstance(exc, SentioError):
        data.update(
            status=exc.status,
            details=exc.details,
            retryable=exc.retryable,
            code=exc.code.value,
        )
    return data


def _decode_exc(data: dict) -> BaseException:
    """Rebuild the worker's exception router-side. SentioError subclasses
    reconstruct with their full wire surface (status / details /
    retry_after_s) so HTTP mapping and failover logic behave identically;
    the service's own GenerationTimeout and common builtins round-trip by
    name; anything else degrades to RuntimeError carrying the original
    type — a worker *bug* must not masquerade as a retryable 503."""
    from sentio_tpu.infra import exceptions as exc_mod
    from sentio_tpu.runtime.service import GenerationTimeout

    name, message = data.get("cls", ""), data.get("message", "")
    cls = getattr(exc_mod, name, None)
    if isinstance(cls, type) and issubclass(cls, exc_mod.SentioError):
        err = cls.__new__(cls)
        Exception.__init__(err, message)
        err.message = message
        err.status = data.get("status", 500)
        err.details = data.get("details") or {}
        err.retryable = bool(data.get("retryable", False))
        err.error_id = ""
        err.timestamp = 0.0
        try:
            err.code = exc_mod.ErrorCode(data.get("code", cls.code.value))
        except ValueError:
            pass
        return err
    if name == "GenerationTimeout":
        return GenerationTimeout(message)
    import builtins

    builtin = getattr(builtins, name, None)
    if isinstance(builtin, type) and issubclass(builtin, Exception):
        try:
            return builtin(message)
        except Exception:  # noqa: BLE001 — odd constructor signature
            pass
    return RuntimeError(f"worker raised {name}: {message}")


# --------------------------------------------------------------------------
# worker side

class _WorkerServer:
    """Runs inside the child process: one recv loop dispatching RPC frames
    to handler threads, a status thread pushing liveness, a send lock
    (Connection.send is not thread-safe)."""

    def __init__(self, conn, spec: WorkerSpec) -> None:
        self.conn = conn
        self.spec = spec
        self.svc = None
        self._send_lock = threading.Lock()
        self._stop = threading.Event()
        # stream cancellation flags by req_id (checked between token frames)
        self._cancelled: set[int] = set()
        self._cancel_lock = threading.Lock()

    def _send(self, req_id: int, kind: str, payload: Any) -> None:
        with self._send_lock:
            try:
                self.conn.send((req_id, kind, payload))
            except (BrokenPipeError, OSError):
                # router gone: nothing to report to; shut down
                self._stop.set()

    # ------------------------------------------------------------- handlers

    def _status_loop(self) -> None:
        interval = max(self.spec.status_interval_s, 0.02)
        while not self._stop.wait(interval):
            svc = self.svc
            if svc is None:
                continue
            try:
                status = {
                    "heartbeat_age": svc.heartbeat_age(),
                    "backlog": svc.backlog(),
                    "projected_wait": svc.projected_wait(),
                    "broken": svc.broken,
                    "closed": svc.closed,
                    "tick_failure_count": svc.tick_failure_count,
                    "pump_leaked": svc.pump_leaked_count,
                    "duty_cycle": svc.duty_cycle(),
                    "pid": os.getpid(),
                }
            except Exception:  # noqa: BLE001 — status is best-effort
                continue
            self._send(0, _F_STATUS, status)

    def _handle(self, req_id: int, method: str, kwargs: dict) -> None:
        svc = self.svc
        try:
            if method == "generate":
                self._send(req_id, _F_OK, svc.generate(**kwargs))
            elif method == "stream_open":
                self._handle_stream(req_id, kwargs)
            elif method == "check_admission":
                rel = kwargs.get("deadline_rel_s")
                svc.check_admission(
                    time.perf_counter() + rel if rel is not None else None
                )
                self._send(req_id, _F_OK, None)
            elif method == "peek_prefix":
                self._send(req_id, _F_OK,
                           svc.engine.peek_prefix(kwargs["toks"]))
            elif method == "stats":
                self._send(req_id, _F_OK, svc.stats())
            elif method == "warmup":
                self._send(req_id, _F_OK, svc.warmup(**kwargs))
            elif method == "drain":
                self._send(req_id, _F_OK, svc.drain(**kwargs))
            elif method == "abandon":
                svc.abandon(kwargs.get("reason", "abandoned by router"))
                self._send(req_id, _F_OK, None)
            elif method == "duty_cycle":
                self._send(req_id, _F_OK, svc.duty_cycle())
            elif method == "reset_duty_cycle":
                svc.reset_duty_cycle()
                self._send(req_id, _F_OK, None)
            elif method == "inject_fault":
                from sentio_tpu.infra import faults

                point = kwargs.pop("point")
                faults.arm(point, faults.FaultRule(**kwargs))
                self._send(req_id, _F_OK, None)
            elif method == "reset_faults":
                from sentio_tpu.infra import faults

                faults.reset()
                self._send(req_id, _F_OK, None)
            elif method == "ping":
                self._send(req_id, _F_OK, os.getpid())
            else:
                raise ValueError(f"unknown worker method {method!r}")
        except BaseException as exc:  # noqa: BLE001 — everything goes typed  # lint: allow(baseexception-swallow) — converted to a typed wire frame
            self._send(req_id, _F_ERR, _encode_exc(exc))

    def _handle_stream(self, req_id: int, kwargs: dict) -> None:
        """Token frames for one stream. The iterator is created (call-time
        validation) BEFORE the ok frame, so the router-side caller sees
        validation errors synchronously — the SSE pre-200 contract."""
        stats_out: dict = {}
        it = self.svc.generate_stream(stats_out=stats_out, **kwargs)
        self._send(req_id, _F_OK, None)
        try:
            for piece in it:
                with self._cancel_lock:
                    if req_id in self._cancelled:
                        self._cancelled.discard(req_id)
                        it.close()  # marks the ticket cancelled in finally
                        return
                self._send(req_id, _F_TOK, piece)
            self._send(req_id, _F_END, stats_out)
        except BaseException as exc:  # noqa: BLE001  # lint: allow(baseexception-swallow) — converted to a typed wire frame
            self._send(req_id, _F_ERR, _encode_exc(exc))
        finally:
            with self._cancel_lock:
                self._cancelled.discard(req_id)

    # ----------------------------------------------------------------- main

    def run(self) -> None:
        try:
            factory = _resolve_factory(self.spec.factory)
            self.svc = factory(**self.spec.factory_kwargs)
        except BaseException as exc:  # noqa: BLE001 — report, then die  # lint: allow(baseexception-swallow) — reported as a typed wire frame
            self._send(0, _F_ERR, _encode_exc(exc))
            return
        eng = self.svc.engine
        self._send(0, _F_READY, {
            "pid": os.getpid(),
            "page_size": eng.page_size,
            "max_slots": eng.max_slots,
            "max_queue": self.svc.max_queue,
            "default_timeout_s": self.svc.default_timeout_s,
            "default_deadline_s": self.svc.default_deadline_s,
            "retry_budget": self.svc.retry_budget,
            "tick_stall_budget_s": self.svc.tick_stall_budget_s,
        })
        status = threading.Thread(target=self._status_loop,
                                  name="worker-status", daemon=True)
        status.start()
        while not self._stop.is_set():
            try:
                frame = self.conn.recv()
            except (EOFError, OSError):
                break  # router died or closed: shut down with it
            except pickle.UnpicklingError:
                logger.exception("worker dropped an undecodable frame")
                continue
            req_id, method, kwargs = frame
            if method == "__shutdown__":
                break
            if method == "stream_cancel":
                with self._cancel_lock:
                    self._cancelled.add(int(kwargs["stream_id"]))
                continue
            threading.Thread(
                target=self._handle, args=(req_id, method, kwargs),
                name=f"worker-rpc-{req_id}", daemon=True,
            ).start()
        self._stop.set()
        try:
            self.svc.close()
        except Exception:  # noqa: BLE001 — exiting anyway
            logger.exception("worker service close failed")


def worker_main(conn, spec: WorkerSpec) -> None:
    """Child-process entry point (spawned by :class:`ProcessReplica`)."""
    # the worker must die with its router even when wedged in XLA: the
    # router holds the other pipe end, so a clean router close() still
    # reaches the recv loop; SIGTERM from terminate() gets a fast exit
    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))
    logging.basicConfig(level=logging.WARNING)
    _WorkerServer(conn, spec).run()
    # skip interpreter/static teardown: daemon threads (pump, RPC
    # handlers) may still sit inside XLA, and C++ static destructors
    # running under them abort with "terminate called without an active
    # exception" — the service already closed, nothing left to flush
    os._exit(0)


# --------------------------------------------------------------------------
# router side

class _PendingCall:
    __slots__ = ("q", "streaming")

    def __init__(self, streaming: bool = False) -> None:
        self.q: _queue.Queue = _queue.Queue()
        # a streaming call stays registered past its open ack (_F_OK): the
        # token frames that follow reuse the same req_id, and popping on
        # the ack would silently drop every one of them
        self.streaming = streaming


class _EngineFacade:
    """The slice of the engine surface ReplicaSet touches on a replica:
    routing probes and rebuild-warmup hooks. Compiles happen in the worker
    process, outside the router's compile fence, so the fence exemption is
    a no-op here."""

    def __init__(self, owner: "ProcessReplica", tokenizer,
                 page_size: int, max_slots: int) -> None:
        self._owner = owner
        self.tokenizer = tokenizer
        self.page_size = page_size
        self.max_slots = max_slots

    def peek_prefix(self, toks) -> int:
        return self._owner._peek_prefix(toks)

    def set_fence_exempt(self, exempt: bool) -> None:  # noqa: ARG002
        return None


class ProcessReplica:
    """Router-process shim over one worker process; presents the
    ``PagedGenerationService`` surface so ReplicaSet drives it unchanged.

    Liveness model: the worker pushes status frames at
    ``spec.status_interval_s``; every read-side probe (``backlog``,
    ``heartbeat_age``, ``broken``…) is served from the cached frame, so
    supervisor passes cost zero RPCs. Worker death is observed three ways,
    any of which flips :attr:`broken`: the dispatcher hits EOF/broken pipe,
    ``proc.is_alive()`` goes false, or the worker itself reports a latched
    ``broken``. All pending RPCs then fail with typed
    :class:`ReplicaUnavailable` — the same caller surface as an in-process
    replica whose engine latched broken."""

    def __init__(
        self,
        spec: WorkerSpec,
        tokenizer,
        replica_id: int = 0,
        build_timeout_s: float = 600.0,
    ) -> None:
        import multiprocessing

        self.spec = spec
        self.replica_id = replica_id
        self.build_timeout_s = build_timeout_s
        self._tokenizer = tokenizer
        # JAX is not fork-safe (see module docstring): the worker MUST come
        # up via spawn so its runtime initializes in a clean interpreter
        self._ctx = multiprocessing.get_context("spawn")
        self._conn, child_conn = self._ctx.Pipe()
        self._proc = self._ctx.Process(  # lint: allow(no-fork) — spawn context
            target=worker_main, args=(child_conn, spec),
            name=f"sentio-replica-worker-{replica_id}", daemon=True,
        )
        self._mutex = threading.Lock()
        # Connection.send is not thread-safe (a >16KB frame goes out as
        # separate header+body writes, and partial writes loop): concurrent
        # router threads would interleave bytes and desync the pipe, making
        # a healthy worker look dead. Mirrors the worker-side _send_lock.
        self._send_lock = threading.Lock()
        self._calls: dict[int, _PendingCall] = {}  # guarded-by: _mutex
        self._next_id = 1  # guarded-by: _mutex
        self._dead = False  # guarded-by: _mutex
        self._death_reason = ""  # guarded-by: _mutex
        self._closed = False  # guarded-by: _mutex
        self._status: dict = {}
        self._status_ts = 0.0
        self._last_stats: dict = {}
        self._proc.start()
        child_conn.close()  # the parent's copy; the worker holds its own
        # the handshake call is registered BEFORE the dispatcher starts: a
        # factory that fails instantly would otherwise race its err frame
        # past an unregistered req_id 0 and the build would time out instead
        # of surfacing the real error
        ready_call = _PendingCall()
        self._calls[0] = ready_call
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name=f"replica-worker-rx-{replica_id}", daemon=True,
        )
        self._dispatcher.start()
        ready = self._wait_ready(ready_call, build_timeout_s)
        self.engine = _EngineFacade(self, tokenizer,
                                    ready["page_size"], ready["max_slots"])
        self.max_queue = ready["max_queue"]
        self.default_timeout_s = ready["default_timeout_s"]
        self.default_deadline_s = ready["default_deadline_s"]
        self.retry_budget = ready["retry_budget"]
        self.tick_stall_budget_s = ready["tick_stall_budget_s"]

    # ------------------------------------------------------------- plumbing

    def _wait_ready(self, call: "_PendingCall", timeout_s: float) -> dict:
        try:
            kind, payload = call.q.get(timeout=timeout_s)
        except _queue.Empty:
            self.close()
            raise ReplicaUnavailable(
                f"worker did not come up within {timeout_s:.0f}s",
                retryable=False,
            ) from None
        if kind == _F_ERR:
            self.close()
            raise _decode_exc(payload)
        if kind != _F_READY:
            self.close()
            raise ReplicaUnavailable(
                f"worker handshake sent {kind!r} before ready",
                retryable=False,
            )
        return payload

    def _dispatch_loop(self) -> None:
        while True:
            try:
                frame = self._conn.recv()
            except (EOFError, OSError, pickle.UnpicklingError):
                self._on_death("worker connection lost")
                return
            req_id, kind, payload = frame
            if kind == _F_STATUS:
                # plain attribute writes: GIL-atomic snapshot for probes
                self._status = payload
                self._status_ts = time.perf_counter()
                continue
            with self._mutex:
                call = self._calls.get(req_id)
                if call is not None and (
                    kind in (_F_ERR, _F_END, _F_READY)
                    or (kind == _F_OK and not call.streaming)
                ):
                    self._calls.pop(req_id, None)
            if call is not None:
                call.q.put((kind, payload))

    def _on_death(self, reason: str, *, process_death: bool = True) -> None:
        with self._mutex:
            if self._dead:
                return
            self._dead = True
            self._death_reason = reason
            pending = list(self._calls.values())
            self._calls.clear()
            closed = self._closed
        exc = self._death_error()
        for call in pending:
            call.q.put((_F_ERR, _encode_exc(exc)))
        if not closed:
            logger.warning("replica %d worker died: %s", self.replica_id,
                           reason)
            if process_death:
                # the worker_deaths counter feeds the respawn-loop alert
                # (SentioTpuReplicaWorkerDead) — only actual process deaths
                # count; a stall-quarantine abandon of a live worker is the
                # stall watchdog's story, not a death
                try:
                    from sentio_tpu.infra.metrics import get_metrics

                    get_metrics().record_worker_death(self.replica_id)
                except Exception:  # noqa: BLE001 — telemetry is best-effort
                    pass

    def _death_error(self) -> ReplicaUnavailable:
        # _death_reason is written exactly once (under _mutex, before _dead
        # latches true) and only read after; the lock-free read is a
        # GIL-atomic str fetch
        reason = self._death_reason or "killed"  # lint: allow(lock-discipline) — GIL-atomic read after latch
        return ReplicaUnavailable(
            f"replica worker process died: {reason}",
            retry_after_s=2.0,
            details={"replica": self.replica_id, "reason": "worker_dead"},
        )

    def _send_frame(self, frame: tuple) -> None:
        with self._send_lock:
            self._conn.send(frame)

    def _call(self, method: str, kwargs: dict,
              timeout_s: Optional[float]) -> Any:
        """One blocking RPC. A dead worker — before or during the call —
        raises the typed death error; an unresponsive worker past
        ``timeout_s`` does too (a wedged RPC loop is indistinguishable
        from a dead one, and both are replica failures the caller should
        fail over from)."""
        call = _PendingCall()
        with self._mutex:
            if self._dead:
                raise self._death_error()
            req_id = self._next_id
            self._next_id += 1
            self._calls[req_id] = call
        try:
            self._send_frame((req_id, method, kwargs))
        except (BrokenPipeError, OSError):
            self._on_death("worker pipe broken on send")
            raise self._death_error() from None
        try:
            kind, payload = call.q.get(
                timeout=timeout_s if timeout_s and timeout_s > 0 else None)
        except _queue.Empty:
            with self._mutex:
                self._calls.pop(req_id, None)
            raise ReplicaUnavailable(
                f"worker RPC {method!r} unanswered after {timeout_s:.0f}s",
                retry_after_s=2.0,
                details={"replica": self.replica_id, "reason": "rpc_timeout"},
            ) from None
        if kind == _F_ERR:
            raise _decode_exc(payload)
        return payload

    @staticmethod
    def _rel_deadline(deadline_s: Optional[float],
                      deadline_ts: Optional[float]) -> Optional[float]:
        """perf_counter clocks do not compare across processes: absolute
        router deadlines cross the boundary as remaining seconds. An
        ALREADY-expired deadline raises here, router-side — shipping a
        non-positive remainder would read as ``deadline_s=0``, the
        explicit no-deadline opt-out, and silently un-expire the
        request (thread mode sheds it typed at admission)."""
        if deadline_ts is not None:
            rel = deadline_ts - time.perf_counter()
            if rel <= 0:
                raise DeadlineExceededError("deadline expired before submit")
            return rel
        return deadline_s

    # ------------------------------------------------------------------ api

    def generate(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        cost_tokens: int = 0,
    ):
        wait = (timeout_s or self.default_timeout_s) + 30.0
        result = self._call("generate", dict(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, timeout_s=timeout_s,
            request_id=request_id,
            deadline_s=self._rel_deadline(deadline_s, deadline_ts),
            top_k=top_k, tenant=tenant, priority=priority,
            cost_tokens=cost_tokens,
        ), timeout_s=wait)
        result.replica_id = self.replica_id
        return result

    def generate_stream(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        cost_tokens: int = 0,
        stats_out: Optional[dict] = None,
    ) -> Iterator[str]:
        """Lazy, matching thread mode: the ``stream_open`` RPC — which
        admits AND starts decoding in the worker — defers to the first
        ``next()``. ``ReplicaSet._stream_impl`` discards and re-creates
        not-yet-started iterators (WFQ overflow re-bucketing, failover) on
        the promise that doing so costs nothing; an eager open here would
        leak a phantom decode per discarded iterator. The process-mode
        delta: thread mode's CALL-time validation (top_k vs speculation)
        also moves to the first ``next()`` — the SSE handler's admission
        pre-check still runs before its 200, and a validation error past
        that surfaces as the typed mid-stream error."""
        wait = (timeout_s or self.default_timeout_s) + 30.0
        return self._stream_open_and_pump(dict(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, timeout_s=timeout_s,
            request_id=request_id,
            deadline_s=deadline_s, deadline_ts=deadline_ts,
            top_k=top_k, tenant=tenant, priority=priority,
            cost_tokens=cost_tokens,
        ), wait, stats_out)

    def _stream_open_and_pump(self, req: dict, wait: float,
                              stats_out: Optional[dict]) -> Iterator[str]:
        # generator body: nothing below runs until the first next()
        req["deadline_s"] = self._rel_deadline(
            req.pop("deadline_s"), req.pop("deadline_ts"))
        call = _PendingCall(streaming=True)
        with self._mutex:
            if self._dead:
                raise self._death_error()
            req_id = self._next_id
            self._next_id += 1
            self._calls[req_id] = call
        try:
            self._send_frame((req_id, "stream_open", req))
        except (BrokenPipeError, OSError):
            self._on_death("worker pipe broken on send")
            raise self._death_error() from None
        try:
            kind, payload = call.q.get(timeout=wait)
        except _queue.Empty:
            with self._mutex:
                self._calls.pop(req_id, None)
            raise ReplicaUnavailable(
                f"worker stream open unanswered after {wait:.0f}s",
                retry_after_s=2.0,
                details={"replica": self.replica_id, "reason": "rpc_timeout"},
            ) from None
        if kind == _F_ERR:
            raise _decode_exc(payload)
        yield from self._stream_frames(req_id, call, wait, stats_out)

    def _stream_frames(self, req_id: int, call: _PendingCall, wait: float,
                       stats_out: Optional[dict]) -> Iterator[str]:
        done = False
        try:
            while True:
                try:
                    kind, payload = call.q.get(timeout=wait)
                except _queue.Empty:
                    raise ReplicaUnavailable(
                        f"worker stream stalled for {wait:.0f}s",
                        retry_after_s=2.0,
                        details={"replica": self.replica_id,
                                 "reason": "rpc_timeout"},
                    ) from None
                if kind == _F_TOK:
                    yield payload
                elif kind == _F_END:
                    done = True
                    if stats_out is not None and isinstance(payload, dict):
                        payload["replica_id"] = self.replica_id
                        stats_out.update(payload)
                    return
                else:  # _F_ERR
                    done = True
                    raise _decode_exc(payload)
        finally:
            with self._mutex:
                self._calls.pop(req_id, None)
                dead = self._dead
            if not done and not dead:
                # consumer abandoned mid-stream: tell the worker (it cancels
                # the ticket between token frames — chunk-granular)
                try:
                    self._send_frame((0, "stream_cancel",
                                      {"stream_id": req_id}))
                except (BrokenPipeError, OSError):
                    pass

    def check_admission(self, deadline_ts: Optional[float] = None) -> None:
        self._call("check_admission", {
            "deadline_rel_s": self._rel_deadline(None, deadline_ts),
        }, timeout_s=10.0)

    def _peek_prefix(self, toks) -> int:
        """Routing probe; MUST never fail OR stall a request — unlike
        thread mode's in-memory radix read this is a pipe RPC, and it sits
        on every incoming request's routing path. A worker whose status
        frames have gone stale is slow or wedged, so skip the RPC entirely
        (reads as a cold cache and the router routes elsewhere); a healthy
        worker answers from a handler thread in milliseconds, so the short
        timeout bounds the set-wide routing cost of a not-yet-detected
        wedge instead of stacking multi-second waits per replica."""
        stale_after = max(10 * self.spec.status_interval_s, 0.5)
        if (self._status_ts <= 0.0
                or time.perf_counter() - self._status_ts > stale_after):
            return 0
        try:
            return int(self._call("peek_prefix", {"toks": list(toks)},
                                  timeout_s=0.5))
        except Exception:  # noqa: BLE001
            return 0

    def warmup(self, max_new_tokens: int = 4) -> dict:
        return self._call("warmup", {"max_new_tokens": max_new_tokens},
                          timeout_s=self.build_timeout_s)

    def backlog(self) -> int:
        return int(self._status.get("backlog") or 0)

    def projected_wait(self) -> Optional[float]:
        return self._status.get("projected_wait")

    def heartbeat_age(self) -> Optional[float]:
        """Worker-reported pump heartbeat age plus the status frame's own
        staleness. A worker whose status frames STOPPED while RPCs are in
        flight is itself wedged — that staleness is the age (the router's
        watchdog must detect a dead worker-side loop exactly like a dead
        pump)."""
        with self._mutex:
            if self._dead:
                return None
            pending = len(self._calls)
        if self._status_ts <= 0.0:
            return None
        stale = time.perf_counter() - self._status_ts
        age = self._status.get("heartbeat_age")
        if age is not None:
            return float(age) + stale
        interval = max(self.spec.status_interval_s, 0.02)
        if pending > 0 and stale > max(10 * interval, 2.0):
            return stale
        return None

    def duty_cycle(self) -> dict:
        return self._status.get("duty_cycle") or {
            "host": 0.0, "device": 0.0, "idle": 1.0,
        }

    def reset_duty_cycle(self) -> None:
        try:
            self._call("reset_duty_cycle", {}, timeout_s=10.0)
        except Exception:  # noqa: BLE001 — telemetry re-basing, best-effort
            pass

    @property
    def broken(self) -> bool:
        with self._mutex:
            if self._dead:
                return True
        if self._proc is not None and not self._proc.is_alive():
            self._on_death(f"worker exited (code {self._proc.exitcode})")
            return True
        return bool(self._status.get("broken"))

    @property
    def closed(self) -> bool:
        with self._mutex:
            if self._closed:
                return True
        return bool(self._status.get("closed"))

    @property
    def tick_failure_count(self) -> int:
        return int(self._status.get("tick_failure_count") or 0)

    @property
    def pump_leaked_count(self) -> int:
        return int(self._status.get("pump_leaked") or 0)

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    def stats(self) -> dict:
        try:
            self._last_stats = self._call("stats", {}, timeout_s=10.0)
        except Exception:  # noqa: BLE001 — dead replica: last known stats
            return {**self._last_stats, "replica": self.replica_id,
                    "worker_dead": 1}
        return self._last_stats

    # ------------------------------------------------ quarantine / handoff

    def abandon(self, reason: str) -> list:
        """Stall-quarantine surface: ask the worker (its RPC loop survives a
        wedged pump) to abandon — admitted tickets fail typed in-worker,
        which unblocks their router-side RPCs with the typed error — then
        latch dead locally so every later call fails fast. No cross-process
        inbox handoff: the returned list is empty and those callers spend
        normal failover budget (module docstring)."""
        try:
            self._call("abandon", {"reason": reason}, timeout_s=10.0)
        except Exception:  # noqa: BLE001 — wedged/dead worker: kill below
            pass
        alive = self._proc is not None and self._proc.is_alive()
        self._on_death(f"abandoned: {reason}", process_death=not alive)
        return []

    def extract_inbox(self) -> list:
        """Never-dispatched tickets live in the worker process; they cannot
        move across the boundary (their callers block on THIS replica's
        RPC frames). Quarantine fails them typed via the worker instead."""
        return []

    def adopt(self, ticket) -> None:  # noqa: ARG002
        raise ReplicaUnavailable(
            "process-mode replicas cannot adopt cross-process tickets",
            retryable=False,
            details={"replica": self.replica_id, "reason": "process_mode"},
        )

    # ------------------------------------------------------------ lifecycle

    def respawn(self) -> "ProcessReplica":
        """A fresh worker process from the same spec — the supervisor's
        rebuild path (``ReplicaSet._rebuild`` duck-types this instead of
        ``engine.spawn_fresh()``)."""
        return ProcessReplica(
            self.spec, self._tokenizer, replica_id=self.replica_id,
            build_timeout_s=self.build_timeout_s,
        )

    def kill(self) -> None:
        """SIGKILL the worker — the chaos drill's real replica death. The
        dispatcher observes the broken pipe and fails all in-flight RPCs
        typed; the supervisor sees ``broken`` and respawns."""
        if self._proc is not None and self._proc.pid:
            try:
                os.kill(self._proc.pid, signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass

    def inject_fault(self, point: str, **rule_kwargs) -> None:
        """Arm a fault rule INSIDE the worker process (its faults registry
        is process-private). ``kill_process=True`` at e.g. ``paged.step``
        makes the next decode tick a real SIGKILL mid-dispatch."""
        self._call("inject_fault", {"point": point, **rule_kwargs},
                   timeout_s=10.0)

    def reset_faults(self) -> None:
        try:
            self._call("reset_faults", {}, timeout_s=10.0)
        except Exception:  # noqa: BLE001 — the worker may already be dead
            pass

    def drain(self, deadline_s: float = 30.0) -> dict:
        """Worker-side graceful drain, then local close. A dead worker
        drains vacuously (its backlog died with it)."""
        result = {"drained": False, "abandoned": 0}
        try:
            result = self._call("drain", {"deadline_s": deadline_s},
                                timeout_s=deadline_s + 30.0)
        except Exception:  # noqa: BLE001 — dead worker: nothing to drain
            pass
        self.close(join_timeout_s=max(deadline_s, 1.0))
        return result

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Shut the worker down and REAP it: graceful shutdown frame, then
        SIGTERM, then SIGKILL — close() never returns with the child still
        runnable, so a closed set cannot leak orphan processes."""
        with self._mutex:
            self._closed = True
        proc = self._proc
        if proc is None:
            return
        try:
            self._send_frame((0, "__shutdown__", {}))
        except (BrokenPipeError, OSError):
            pass
        proc.join(timeout=max(join_timeout_s, 0.5))
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=5.0)
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=5.0)
        try:
            self._conn.close()
        except OSError:
            pass
        self._on_death("closed")
