"""PagedGenerationService: continuous batching as the live decode path.

Bridges the synchronous serving pipeline (graph nodes run on worker
threads, one per in-flight ``/chat``) onto ONE shared
:class:`~sentio_tpu.runtime.paged.ContinuousBatchingEngine`: every caller's
``generate`` drops its request into an inbox and blocks on its own event; a
single pump thread owns the engine outright — drain inbox → admit → fused
decode step → retire — for as long as any slot is live. Staggered requests
therefore share decode ticks (the whole point of continuous batching):
request B joins the compiled decode program at whatever step request A has
reached, no recompilation, no waiting for A to finish.

This replaces the reference's one-request-per-HTTP-call generation
(/root/reference/src/api/handlers/chat.py:148 — each graph.ainvoke owns its
LLM call end to end) and closes the round-1 gap where the paged engine
existed but nothing in the serving path used it.

Thread-safety: the engine is single-threaded by design and is touched ONLY
by the pump thread (no lock held across device ticks — an engine-wide lock
would let the pump starve submitters, since a hot loop reacquires an
uncontended lock before waiters wake). Submitters and the pump meet at
``_mutex``, held only for quick inbox/bookkeeping operations.

Overload & failure semantics (the request-lifecycle robustness layer):

* **admission control** — the inbox + admitted set is bounded by
  ``max_queue``; a submit over the bound (or while draining, or whose
  deadline the projected wait already exceeds) raises a typed
  :class:`~sentio_tpu.infra.exceptions.ServiceOverloaded` that the HTTP
  layer maps to 429/503 + ``Retry-After`` — shed fast, don't time out slow;
* **deadlines** — a per-request absolute deadline rides the ticket and the
  engine ``_Request``; the pump drops expired tickets before admission and
  cancels expired in-flight slots every tick, so the fused decode batch
  never spends sub-steps on a caller that already gave up;
* **crash containment** — a failed decode tick resets the engine and, when
  the reset succeeds, REQUEUES innocent waiters (each ticket carries a
  retry budget) instead of failing all of them; only exhausted-budget
  tickets see an error result, and ``_broken`` still latches when the
  reset itself fails;
* **graceful drain** — :meth:`drain` stops admitting, lets in-flight slots
  finish within a deadline, then closes (the serve app's shutdown hook).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

from sentio_tpu.analysis.sanitizer import (
    assert_held,
    bind_engine_owner,
    guard_locksets,
    make_lock,
)
from sentio_tpu.infra.exceptions import (
    DeadlineExceededError,
    ReplicaUnavailable,
    ServiceOverloaded,
)
from sentio_tpu.infra.flight import get_flight_recorder
from sentio_tpu.infra.metrics import get_metrics
from sentio_tpu.infra.phases import TICK_PHASES, duty_fractions, phases_to_ms
from sentio_tpu.runtime.paged import ContinuousBatchingEngine, PagedResult

logger = logging.getLogger(__name__)

__all__ = [
    "PagedGenerationService",
    "StreamProgress",
    "GenerationTimeout",
    "ServiceOverloaded",
    "DeadlineExceededError",
    "ReplicaUnavailable",
]


class GenerationTimeout(Exception):
    pass


class StreamProgress:
    """Delivered-state mirror for ONE streaming request: the exact token
    ids behind every text piece the iterator has yielded so far.

    The stream iterator REBINDS ``tokens`` right before each yield (and to
    the authoritative ``result.tokens`` at completion), so a consumer that
    observes a yield — or catches the iterator's mid-stream exception —
    reads the precise delivered prefix. That prefix is what the resume-by-
    replay path (ReplicaSet._stream_impl, runtime/replica.py) re-admits on
    a surviving replica as a prior context suffix after the prompt: the
    splice point for a mid-flight failover with zero duplicated and zero
    missing tokens. Single-threaded by contract: the producer (the stream
    iterator) and the consumer run on the SAME caller thread, interleaved
    by the yields themselves — no lock needed or taken."""

    __slots__ = ("tokens",)

    def __init__(self) -> None:
        self.tokens: list[int] = []

    def reset(self) -> None:
        self.tokens = []


def finish_ticket_error(ticket: "_Ticket", exc: Exception,
                        finish_reason: str) -> None:
    """THE terminal typed-error sequence for a ticket, shared by every
    path that ends one: result-free error, flight-record close, stream
    ``("err", exc)``, event set — exactly once (the event guard makes it
    idempotent). Caller must own the ticket: either hold the owning
    service's ``_mutex`` (``_finish_error_locked``) or hold it exclusively
    off any service's books (the ReplicaSet's quarantine handoff)."""
    if ticket.event.is_set():
        return
    ticket.error = exc
    if ticket.request_id:
        get_flight_recorder().finish_engine(
            ticket.request_id, finish_reason=finish_reason, error=str(exc)
        )
    if ticket.stream_q is not None:
        ticket.stream_q.put(("err", exc))
    ticket.event.set()


@dataclass
class _Ticket:
    prompt: str
    max_new_tokens: int
    temperature: float
    # per-request top-k (0 = off) — traced data on the fused decode
    # dispatch, so any k shares the engine's one compiled tick program
    top_k: int = 0
    event: threading.Event = field(default_factory=threading.Event)
    result: Optional[PagedResult] = None
    # terminal typed failure (deadline expiry, shed) — raised to the caller
    # instead of a result; exactly one of result/error is set at event time
    error: Optional[Exception] = None
    # streaming callers: the pump pushes ("toks", [ids...]) deltas after each
    # tick, ("done", result) at retirement, and ("err", exc) on a typed
    # failure; None for plain generate()
    stream_q: Optional[_queue.Queue] = None
    sent_tokens: int = 0  # how many emitted tokens were already pushed
    # caller abandoned (timeout / disconnected stream): the pump cancels the
    # engine request instead of decoding to max_new for nobody
    cancelled: bool = False
    # absolute time.perf_counter() deadline: expired tickets are dropped
    # before admission and cancelled mid-decode (None = no deadline)
    deadline_ts: Optional[float] = None
    # crash-containment budget: how many more times this ticket may be
    # requeued after a failed tick (with a successful engine reset) before
    # it gets the error result instead
    retries_left: int = 0
    # flight-recorder trace id (the serving layer's query_id) — None for
    # untraced callers; telemetry is still recorded to /metrics either way
    request_id: Optional[str] = None
    # submit / first-token wall clocks for TTFT+TPOT (0.0 = not yet seen)
    t_submit: float = 0.0
    t_first: float = 0.0
    # tokens already host-visible when t_first was stamped: TPOT divides the
    # post-first-tick interval by the tokens produced IN that interval (a
    # fused tick emits up to steps_per_tick tokens at once)
    tokens_first: int = 0
    # opaque fair-queueing metadata stamped by a fronting ReplicaSet
    # (runtime/replica.py): the service itself never reads these — they ride
    # the ticket so a quarantine-time inbox handoff can release/re-charge
    # the owning tenant's WFQ reservation on the surviving replica
    tenant: Optional[str] = None
    priority: Optional[str] = None
    cost_tokens: int = 0
    # resume-by-replay (runtime/replica.py): token ids spliced in as a
    # prior context suffix AFTER the tokenized prompt — the delivered
    # prefix of a stream that died mid-flight on a sibling replica. The
    # engine prefills (or radix-matches) prompt + prior and decode
    # continues from the splice point; emitted tokens are post-splice only
    prior_tokens: Optional[list] = None
    # sampling seed stamped at call time (None = engine RNG stream as-is):
    # folded once into the engine's SHARED RNG at admission — best-effort
    # reproducibility for a lone sampled request, not a per-request pinned
    # stream (a resumed sampled continuation is distribution-correct by
    # conditioning on the replayed prefix, with or without the seed)
    seed: Optional[int] = None
    # process-mode shadow key (runtime/worker.py): the router-side RPC id
    # this ticket is mirrored under, so a worker-side extract_inbox can
    # name its never-dispatched tickets back to the router's shadow queue
    shadow_id: Optional[int] = None

    @property
    def path(self) -> str:
        """Metric label for the TTFT/TPOT series: blocking vs streaming."""
        return "stream" if self.stream_q is not None else "paged"


@guard_locksets
class PagedGenerationService:
    """Thread-safe submit/wait facade + pump thread over the paged engine."""

    def __init__(
        self,
        engine: ContinuousBatchingEngine,
        default_timeout_s: float = 600.0,
        max_queue: Optional[int] = None,
        default_deadline_s: Optional[float] = None,
        retry_budget: int = 1,
        replica_id: int = 0,
        tick_stall_budget_s: float = 120.0,
        warmup_budget_s: float = 600.0,
    ) -> None:
        self.engine = engine
        self.default_timeout_s = default_timeout_s
        # position of this service in a ReplicaSet (runtime/replica.py) —
        # stamped onto flight-recorder tick events and engine records so
        # per-replica behavior is attributable; 0 for a standalone service
        self.replica_id = int(replica_id)
        # admission bound on waiting work (inbox + admitted, not yet done);
        # a submit past it sheds with 429 instead of queueing unboundedly.
        # The default is deliberately deep (8x slot depth): shedding is tail
        # protection against pathological pileups, not routine backpressure
        self.max_queue = (
            int(max_queue) if max_queue is not None
            else max(8 * engine.max_slots, 64)
        )
        # deadline applied to requests that carry none of their own
        # (None = requests without a deadline never expire)
        self.default_deadline_s = default_deadline_s
        # crash containment: requeues granted per ticket across failed ticks
        self.retry_budget = max(int(retry_budget), 0)
        # wall-clock budget one pump loop iteration may take before a
        # watchdog (ReplicaSet._supervise_once) declares the replica
        # STALLED: a tick blocked inside a wedged device dispatch raises
        # nothing, so heartbeat age is the only observable. Must comfortably
        # exceed the slowest legitimate tick INCLUDING a cold XLA compile;
        # 0 disables stall detection for this service.
        self.tick_stall_budget_s = max(float(tick_stall_budget_s), 0.0)
        # watchdog stand-down bound for WARMING: warmup ticks legitimately
        # run cold XLA compiles far past any sane stall budget, so the
        # heartbeat watchdog is exempted while ``_warming`` — but the
        # exemption EXPIRES after this many seconds, or a wedge DURING
        # warmup would only ever be caught by caller timeouts and hang the
        # spawn/rebuild path for minutes. Must comfortably exceed the
        # slowest legitimate full warmup sweep; 0 = exempt forever (the
        # pre-budget behavior).
        self.warmup_budget_s = max(float(warmup_budget_s), 0.0)
        # inbox + bookkeeping ONLY, never device work
        self._mutex = make_lock("PagedGenerationService._mutex")
        self._inbox: list[_Ticket] = []  # guarded-by: _mutex
        self._tickets: dict[int, _Ticket] = {}  # guarded-by: _mutex
        self._pump: Optional[threading.Thread] = None  # guarded-by: _mutex
        self._pump_running = False  # guarded-by: _mutex
        self._closed = False  # guarded-by: _mutex
        self._broken = False  # guarded-by: _mutex
        self._draining = False  # guarded-by: _mutex
        # overload/robustness telemetry (lifetime totals; /metrics publishes
        # them via stats() and the pump stamps them onto tick events)
        self._shed = 0  # guarded-by: _mutex
        self._expired = 0  # guarded-by: _mutex
        self._cancelled = 0  # guarded-by: _mutex
        self._requeued = 0  # guarded-by: _mutex
        self._tick_failures = 0  # guarded-by: _mutex
        self._pump_leaked = 0  # guarded-by: _mutex
        # stamped by the pump each loop iteration (perf_counter); 0.0 until
        # the first pump starts. The watchdog reads it through
        # heartbeat_age(): a running pump with pending work whose stamp
        # goes stale is wedged inside a dispatch — no exception to catch
        self._heartbeat_ts = 0.0  # guarded-by: _mutex
        # latched by abandon(): the replica layer gave up on a wedged pump
        self._abandoned = False  # guarded-by: _mutex
        # warmup in progress: ticks legitimately run cold XLA compiles far
        # past any sane stall budget, so the watchdog stands down — until
        # warmup_budget_s expires (see above)
        self._warming = False  # guarded-by: _mutex
        self._warming_since = 0.0  # guarded-by: _mutex
        # EMA of recent TTFT seconds, updated by the pump — the projected-
        # wait estimate admission control weighs against a deadline
        self._ttft_ema = 0.0  # guarded-by: _mutex
        # occupancy telemetry (the serving-path answer to BatcherStats):
        # ticks with >1 active slot are decode steps shared across requests
        self._ticks = 0  # guarded-by: _mutex
        self._active_sum = 0  # guarded-by: _mutex
        self._max_active = 0  # guarded-by: _mutex
        self._completed = 0  # guarded-by: _mutex
        # tick-phase attribution (infra/phases.py): cumulative seconds per
        # phase across every pump iteration, single-writer (the pump);
        # readers (stats/duty_cycle, any thread) take GIL-atomic snapshots
        # of float values — slight skew between keys is acceptable for a
        # duty-cycle gauge, and a mutex here would put a lock acquisition
        # on every pump iteration for telemetry's sake
        self._phase_totals = dict.fromkeys(TICK_PHASES, 0.0)  # guarded-by: pump-thread
        # duty-cycle wall-clock origin; reset_duty_cycle() re-bases it so
        # bench windows exclude warmup compiles
        self._duty_t0 = time.perf_counter()  # guarded-by: pump-thread

    # ------------------------------------------------------------------ api

    def generate(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        cost_tokens: int = 0,
        seed: Optional[int] = None,
        shadow_id: Optional[int] = None,
    ) -> PagedResult:
        """Submit one request and block until its tokens are done. Safe to
        call from any number of threads concurrently — that concurrency IS
        the batch. A ``request_id`` ties this generation into the flight
        recorder's per-request trace (TTFT/TPOT + its decode-tick window).

        ``deadline_ts`` (absolute ``time.perf_counter()``) or ``deadline_s``
        (relative) bound how long the caller will wait: admission sheds when
        the deadline is unmeetable, and the pump cancels the request the
        tick its deadline passes. Raises :class:`ServiceOverloaded` (shed),
        :class:`DeadlineExceededError` (expired), or
        :class:`GenerationTimeout` (no deadline, plain timeout).

        ``tenant``/``priority``/``cost_tokens`` are opaque WFQ metadata a
        fronting ReplicaSet stamps for quarantine-time inbox handoff; a
        bare service ignores them."""
        self._check_top_k(top_k)
        deadline_ts = self._resolve_deadline(deadline_s, deadline_ts)
        ticket = _Ticket(prompt, max_new_tokens, temperature, top_k=top_k,
                         request_id=request_id, t_submit=time.perf_counter(),
                         deadline_ts=deadline_ts,
                         retries_left=self.retry_budget,
                         tenant=tenant, priority=priority,
                         cost_tokens=int(cost_tokens),
                         seed=seed, shadow_id=shadow_id)
        if request_id:
            get_flight_recorder().note_engine_submit(
                request_id, replica_id=self.replica_id)
        try:
            with self._mutex:
                self._admit_ticket_locked(ticket)
        except Exception:
            # note_engine_submit already opened the tick window — close it,
            # or the record absorbs every unrelated future tick
            if request_id:
                get_flight_recorder().finish_engine(
                    request_id, finish_reason="rejected")
            raise
        wait_s = self._wait_budget(timeout_s, deadline_ts)
        if not ticket.event.wait(wait_s):
            # completion happens under _mutex, so deciding under the same
            # mutex is race-free: an event set between wait()'s timeout and
            # this check means the work FINISHED — return it instead of
            # raising a timeout that cancels completed work
            expired = (deadline_ts is not None
                       and time.perf_counter() >= deadline_ts)
            with self._mutex:
                finished = ticket.event.is_set()
                # an expired ticket is left for the pump's deadline sweep
                # (which cancels it AND counts it as expired); marking it
                # cancelled here would misfile it under caller-abandoned
                if not finished and not expired:
                    ticket.cancelled = True  # pump frees the slot next loop
            if not finished:
                if expired:
                    raise DeadlineExceededError(
                        "deadline expired before the result was ready"
                    )
                raise GenerationTimeout(
                    f"generation did not finish within {wait_s:.0f}s"
                )
        if ticket.error is not None:
            raise ticket.error
        assert ticket.result is not None
        return ticket.result

    def generate_stream(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        cost_tokens: int = 0,
        stats_out: Optional[dict] = None,
        prior_tokens: Optional[list] = None,
        seed: Optional[int] = None,
        shadow_id: Optional[int] = None,
        progress: Optional[StreamProgress] = None,
    ) -> Iterator[str]:
        """Streaming variant: yields decoded text increments as the shared
        decode batch produces them (chunks of up to steps_per_tick tokens —
        the streaming request STAYS in the continuous batch instead of
        monopolizing a contiguous-cache engine). UTF-8 safe: bytes buffer
        until they decode cleanly. Deadline semantics match
        :meth:`generate`; a deadline that passes mid-stream raises
        :class:`DeadlineExceededError` from the iterator.

        ``stats_out``: optional caller-owned dict filled with the finished
        request's logprob accumulators (logprob_mean/min/count, tokens)
        right before the final yield — a text iterator cannot return the
        PagedResult, and the confidence gate needs the numbers after the
        stream drains.

        ``prior_tokens``: resume-by-replay splice (ReplicaSet failover of a
        delivered-token stream): these token ids are admitted as a prior
        context suffix after the prompt, and the stream yields ONLY the
        post-splice continuation. ``progress``: caller-owned
        :class:`StreamProgress` mirroring the token ids behind every yield
        — the delivered state a router needs to build the NEXT splice."""
        # validated HERE, not in the generator body: a generator function
        # defers its body to the first next(), which would surface this
        # after an SSE handler already committed its 200
        self._check_top_k(top_k)
        return self._generate_stream_impl(
            prompt, max_new_tokens, temperature, timeout_s, request_id,
            deadline_s, deadline_ts, top_k, tenant, priority, cost_tokens,
            stats_out, prior_tokens, seed, shadow_id, progress,
        )

    def _generate_stream_impl(
        self,
        prompt: str,
        max_new_tokens: int,
        temperature: float,
        timeout_s: Optional[float],
        request_id: Optional[str],
        deadline_s: Optional[float],
        deadline_ts: Optional[float],
        top_k: int,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        cost_tokens: int = 0,
        stats_out: Optional[dict] = None,
        prior_tokens: Optional[list] = None,
        seed: Optional[int] = None,
        shadow_id: Optional[int] = None,
        progress: Optional[StreamProgress] = None,
    ) -> Iterator[str]:
        # NB: admission below is still deferred to the first next() (the
        # long-standing stream contract — SSE handlers pre-check via
        # check_admission before committing their 200)
        deadline_ts = self._resolve_deadline(deadline_s, deadline_ts)
        ticket = _Ticket(prompt, max_new_tokens, temperature, top_k=top_k,
                         stream_q=_queue.Queue(),
                         request_id=request_id, t_submit=time.perf_counter(),
                         deadline_ts=deadline_ts,
                         retries_left=self.retry_budget,
                         tenant=tenant, priority=priority,
                         cost_tokens=int(cost_tokens),
                         prior_tokens=(list(prior_tokens)
                                       if prior_tokens else None),
                         seed=seed, shadow_id=shadow_id)
        if request_id:
            get_flight_recorder().note_engine_submit(
                request_id, replica_id=self.replica_id)
        try:
            with self._mutex:
                self._admit_ticket_locked(ticket)
        except Exception:
            if request_id:
                get_flight_recorder().finish_engine(
                    request_id, finish_reason="rejected")
            raise

        tokenizer = self.engine.tokenizer
        deadline = self._wait_budget(timeout_s, deadline_ts)
        emitted: list[int] = []
        flushed = ""
        try:
            while True:
                try:
                    kind, payload = ticket.stream_q.get(timeout=deadline)
                except _queue.Empty:
                    if (ticket.deadline_ts is not None
                            and time.perf_counter() >= ticket.deadline_ts):
                        raise DeadlineExceededError(
                            "deadline expired before the stream produced "
                            "anything"
                        ) from None
                    raise GenerationTimeout(
                        f"stream produced nothing for {deadline:.0f}s"
                    ) from None
                if kind == "err":
                    # typed terminal failure (deadline expiry, shed at
                    # requeue time) — surface it as the iterator's exception
                    raise payload
                if kind == "toks":
                    emitted.extend(payload)
                else:  # "done"
                    result: PagedResult = payload
                    if result.finish_reason == "error":
                        # typed mid-stream death: THIS service cannot
                        # restart a delivered-token stream without
                        # duplicating output, but a fronting ReplicaSet can
                        # resume it on a sibling by replay-prefilling the
                        # delivered prefix (progress carries the splice)
                        raise ReplicaUnavailable(
                            "paged decode failed mid-stream", retry_after_s=2.0,
                            details={"replica": self.replica_id,
                                     "reason": "mid_stream"},
                        )
                    emitted = list(result.tokens)  # authoritative final sequence
                    if stats_out is not None:
                        # filled BEFORE the final yield so the consumer sees
                        # the numbers as soon as the iterator is exhausted
                        stats_out.update(result.stats_dict())
                if progress is not None:
                    # delivered-state mirror, rebound BEFORE the yield so a
                    # consumer observing this piece (or this iteration's
                    # exception) reads exactly the tokens behind it
                    progress.tokens = emitted
                text = tokenizer.decode(emitted)
                if kind == "done":
                    # final flush is unconditional: the finished answer may
                    # genuinely end in a replacement char
                    if len(text) > len(flushed):
                        yield text[len(flushed):]
                    return
                # mid-stream: withhold AT MOST the final char — a trailing
                # '�' may be an incomplete UTF-8 sequence that the next token
                # resolves (a genuine replacement char flushes next round;
                # holding the whole tail would stall streams whose chunks
                # keep ending in replacement chars)
                safe = text[:-1] if text.endswith("�") else text
                if len(safe) > len(flushed):
                    yield safe[len(flushed):]
                    flushed = safe
        finally:
            # abandoned mid-decode (timeout, consumer disconnect → generator
            # close): tell the pump to cancel instead of decoding for nobody.
            # An EXPIRED stream is left for the pump's deadline sweep, which
            # counts it as expired — marking it cancelled here would misfile
            # a deadline miss under caller-abandoned (same rule as generate)
            if ticket.result is None and ticket.error is None and not (
                ticket.deadline_ts is not None
                and time.perf_counter() >= ticket.deadline_ts
            ):
                ticket.cancelled = True

    # ------------------------------------------------------------ admission

    def _check_top_k(self, top_k: int) -> None:
        """Mirror of the engine's submit-time rule (same ``top_k > 0``
        condition — k <= 0 means off everywhere), raised at the service API
        instead of inside the pump loop."""
        if top_k > 0 and getattr(self.engine, "_spec_tick", None) is not None:
            raise ValueError(
                "top_k sampling is not supported with paged speculation "
                "(the spec tick's accept/correct rule is temperature-only)"
            )

    def _resolve_deadline(
        self, deadline_s: Optional[float], deadline_ts: Optional[float]
    ) -> Optional[float]:
        """Absolute perf_counter deadline from the caller's absolute or
        relative form, falling back to the service default (None = none)."""
        if deadline_ts is not None:
            return deadline_ts
        rel = deadline_s if deadline_s is not None else self.default_deadline_s
        if rel is None or rel <= 0:
            return None
        return time.perf_counter() + rel

    def _wait_budget(
        self, timeout_s: Optional[float], deadline_ts: Optional[float]
    ) -> float:
        """How long the caller blocks: its timeout, capped near the deadline
        (+ grace for the pump to deliver the typed deadline error rather
        than a generic timeout racing it)."""
        wait = timeout_s or self.default_timeout_s
        if deadline_ts is not None:
            wait = min(wait, max(deadline_ts - time.perf_counter(), 0.0) + 5.0)
        return wait

    def backlog(self) -> int:
        """Requests waiting on this replica (inbox + admitted, not yet
        done) — the router's load signal."""
        with self._mutex:
            return len(self._inbox) + len(self._tickets)

    def projected_wait(self) -> Optional[float]:
        """Projected first-token wait for a request submitted NOW (TTFT-EMA
        scaled by backlog; None while cold) — the router's least-loaded
        key, the same estimate admission control weighs against deadlines."""
        with self._mutex:
            return self._projected_wait_locked(
                len(self._inbox) + len(self._tickets)
            )

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the pump last completed a loop iteration, or None
        when there is nothing to detect: no pump running, or no pending
        work (an idle service is never stalled). A non-None age past
        ``tick_stall_budget_s`` means the pump is wedged inside a dispatch
        that raises nothing — the watchdog's only observable for the hang
        fault class."""
        with self._mutex:
            if not self._pump_running or self._abandoned:
                return None
            if self._warming:
                # warmup stand-down — bounded by warmup_budget_s: past the
                # budget a stale heartbeat with pending work reads as a
                # stalled WARMUP, the blind spot the budget exists to close
                # (without it, a wedge during warmup hangs the spawn or
                # rebuild path until caller timeouts fire)
                over_budget = (
                    self.warmup_budget_s > 0
                    and self._warming_since > 0.0
                    and time.perf_counter() - self._warming_since
                    > self.warmup_budget_s
                )
                if not over_budget:
                    return None
            if not self._inbox and not self._tickets:
                return None
            if self._heartbeat_ts <= 0.0:
                return None
            return max(time.perf_counter() - self._heartbeat_ts, 0.0)

    def extract_inbox(self) -> list[_Ticket]:
        """Remove and return every never-dispatched inbox ticket (the
        quarantine handoff: these hold NO engine or KV state, so a
        surviving replica can adopt them wholesale). Cancelled/expired
        stragglers are closed out here rather than handed off. Safe against
        a wedged pump — it blocks OUTSIDE ``_mutex``, inside the device
        dispatch."""
        now = time.perf_counter()
        out: list[_Ticket] = []
        with self._mutex:
            for ticket in self._inbox:
                if ticket.event.is_set():
                    continue
                if ticket.cancelled:
                    self._close_cancelled_locked(ticket)
                    continue
                if ticket.deadline_ts is not None and now >= ticket.deadline_ts:
                    self._expired += 1
                    get_metrics().record_shed("expired")
                    self._finish_error_locked(
                        ticket,
                        DeadlineExceededError(
                            "deadline expired before admission"),
                        "expired",
                    )
                    continue
                out.append(ticket)
            self._inbox.clear()
        return out

    def adopt(self, ticket: _Ticket) -> None:
        """Admit a ticket object handed off from a quarantined sibling
        replica. Runs the normal admission checks (closed/broken/queue
        bound/deadline projection) — raises the same typed errors a fresh
        submit would, which the handoff layer turns into the ticket's
        terminal outcome."""
        with self._mutex:
            self._admit_ticket_locked(ticket)

    def abandon(self, reason: str) -> list[_Ticket]:
        """Give up on this service because its pump is wedged inside a
        device dispatch (stall-quarantine). A thread blocked in XLA cannot
        be killed, so recovery is abandonment: latch ``_broken`` (typed 503
        admissions from now on), fail every ADMITTED ticket with a typed
        :class:`ReplicaUnavailable` (their KV state dies with the wedged
        engine — generate callers fail over, delivered-token streams get
        the typed mid-stream error), and return the never-dispatched inbox
        tickets for handoff. Never joins the pump — ``close()`` does the
        bounded join and accounts the leak in ``pump_leaked``."""
        exc = ReplicaUnavailable(
            f"replica abandoned: {reason}", retry_after_s=2.0,
            details={"replica": self.replica_id, "reason": "stalled"},
        )
        with self._mutex:
            self._abandoned = True
            self._broken = True
            for ticket in list(self._tickets.values()):
                self._finish_error_locked(ticket, exc, "stalled")
            self._tickets.clear()
        return self.extract_inbox()

    @property
    def broken(self) -> bool:
        """Latched after a failed tick whose ``engine.reset()`` ALSO failed
        (or after :meth:`abandon` gave up on a wedged pump): the engine's
        device state is unrecoverable in place. A ReplicaSet supervisor
        reads this as the trip-immediately breaker signal."""
        with self._mutex:
            return self._broken

    @property
    def closed(self) -> bool:
        with self._mutex:
            return self._closed

    @property
    def tick_failure_count(self) -> int:
        """Lifetime failed decode ticks — the ReplicaSet supervisor's burst
        breaker polls this (cheaper than a full stats() snapshot)."""
        with self._mutex:
            return self._tick_failures

    @property
    def pump_leaked_count(self) -> int:
        """Pumps that outlived their close() join (usually a wedged device
        dispatch). A rebuild reads this off the incarnation it replaces so
        the ReplicaSet's summed count survives the swap."""
        with self._mutex:
            return self._pump_leaked

    def check_admission(self, deadline_ts: Optional[float] = None) -> None:
        """Raise the shed error a submit right now would raise, WITHOUT
        enqueuing. The SSE path calls this before committing a 200 status
        line — after ``response.prepare`` a shed can only degrade, not 429."""
        with self._mutex:
            self._check_available_locked()
            self._check_admission_locked(deadline_ts)

    def _check_available_locked(self) -> None:  # lock-held: _mutex
        """Closed / broken-engine admissions raise a TYPED 503 + Retry-After
        (ReplicaUnavailable) instead of the old bare RuntimeError → 500: a
        supervised replica rebuilds in place, so the honest answer to a
        caller is \"retry shortly\", not \"internal error\"."""
        assert_held(self._mutex)
        if self._closed:
            raise ReplicaUnavailable(
                "generation service is closed", retry_after_s=5.0,
                details={"replica": self.replica_id, "reason": "closed"},
            )
        if self._broken:
            raise ReplicaUnavailable(
                "paged decode engine is down (reset failed; awaiting "
                "supervised rebuild)", retry_after_s=5.0,
                details={"replica": self.replica_id, "reason": "broken"},
            )

    def _admit_ticket_locked(self, ticket: _Ticket) -> None:  # lock-held: _mutex
        assert_held(self._mutex)
        self._check_available_locked()
        self._check_admission_locked(ticket.deadline_ts)
        self._inbox.append(ticket)
        self._ensure_pump()

    def _check_admission_locked(
        self, deadline_ts: Optional[float]
    ) -> None:  # lock-held: _mutex
        """Admission control: shed (typed, fast) instead of queueing work
        the service cannot finish. Counts every rejection."""
        assert_held(self._mutex)
        now = time.perf_counter()
        if self._draining:
            self._shed += 1
            get_metrics().record_shed("draining")
            raise ServiceOverloaded(
                "generation service is draining", status=503,
                retry_after_s=5.0,
            )
        pending = len(self._inbox) + len(self._tickets)
        if pending >= self.max_queue:
            self._shed += 1
            get_metrics().record_shed("queue_full")
            raise ServiceOverloaded(
                f"decode queue full ({pending}/{self.max_queue} waiting)",
                status=429,
                retry_after_s=max(self._projected_wait_locked(pending) or 0.0, 1.0),
            )
        if deadline_ts is not None:
            remaining = deadline_ts - now
            if remaining <= 0:
                self._shed += 1
                get_metrics().record_shed("deadline")
                raise DeadlineExceededError("deadline expired before submit")
            projected = self._projected_wait_locked(pending)
            if projected is not None and projected > remaining:
                self._shed += 1
                get_metrics().record_shed("deadline")
                raise ServiceOverloaded(
                    f"projected wait {projected:.2f}s exceeds remaining "
                    f"deadline budget {remaining:.2f}s",
                    status=503, retry_after_s=1.0,
                )

    def _projected_wait_locked(
        self, pending: int
    ) -> Optional[float]:  # lock-held: _mutex
        """Crude first-token wait estimate: recent TTFT (EMA, pump-updated)
        scaled by backlog depth relative to the slot count. None until the
        first completion — a cold service never sheds on projection."""
        assert_held(self._mutex)
        if self._ttft_ema <= 0.0:
            return None
        return self._ttft_ema * (1.0 + pending / max(self.engine.max_slots, 1))

    # ------------------------------------------------------------ lifecycle

    def drain(self, deadline_s: float = 30.0) -> dict:
        """Graceful shutdown: stop admitting (new submits shed with 503),
        let in-flight and queued work finish for up to ``deadline_s``, then
        close. Waiters still pending at the deadline get the closed-service
        error result from the exiting pump. The final pump join inside
        ``close()`` is bounded by whatever remains of THIS deadline — a
        pump wedged in a device dispatch must not stretch a 5s drain into
        5s + a hardcoded join window. Returns what happened."""
        with self._mutex:
            self._draining = True
        t_end = time.perf_counter() + max(deadline_s, 0.0)
        pending = 0
        while True:
            with self._mutex:
                pending = len(self._inbox) + len(self._tickets)
            if pending == 0 or time.perf_counter() >= t_end:
                break
            time.sleep(0.02)
        # the join budget is the drain deadline's remainder (floor 1s so a
        # fully-consumed window still gives a HEALTHY exiting pump one
        # beat to fail its waiters and die instead of being miscounted as
        # leaked on a busy scheduler)
        self.close(join_timeout_s=max(t_end - time.perf_counter(), 1.0))
        return {"drained": pending == 0, "abandoned": pending}

    def close(self, join_timeout_s: float = 10.0) -> None:
        with self._mutex:
            self._closed = True
            pump = self._pump
        # join OUTSIDE the mutex: the exiting pump needs it to fail waiters
        if pump is None:
            return
        pump.join(timeout=max(join_timeout_s, 0.0))
        if pump.is_alive():
            # a pump that won't die is a leaked thread pinning the engine —
            # surface it (stats()['pump_leaked']) instead of silently
            # dropping the reference like the join's return value invites
            logger.warning(
                "paged decode pump %r did not exit within %.1fs "
                "(alive=%s, daemon=%s); thread leaked — see stats()",
                pump.name, join_timeout_s, pump.is_alive(), pump.daemon,
            )
            with self._mutex:
                self._pump_leaked += 1
        # drop the ref either way: close() is called twice on shutdown
        # (drain, then container cleanup) — re-joining a leaked (possibly
        # wedged) pump would stall another join window and double-count
        # the same leak; it is counted and logged exactly once above
        with self._mutex:
            if self._pump is pump:
                self._pump = None

    def duty_cycle(self) -> dict:
        """host/device/idle fractions of wall time since construction (or
        the last :meth:`reset_duty_cycle`), summing to 1. ``host`` is every
        phase that burns the pump thread — with N replicas in one process,
        host-fraction x N is the direct GIL ceiling ROADMAP item 1 argues
        from. Reads the pump-thread-owned totals GIL-atomically; per-key
        skew of at most one in-flight tick is acceptable for a gauge."""
        totals = dict(self._phase_totals)
        return duty_fractions(totals, time.perf_counter() - self._duty_t0)

    def reset_duty_cycle(self) -> None:
        """Re-base the duty-cycle window (e.g. after warmup, whose
        compile-dominated ticks would otherwise swamp the host fraction).
        Telemetry-grade: a tick racing the reset may leak one iteration's
        phases into the new window."""
        for key in list(self._phase_totals):
            self._phase_totals[key] = 0.0
        self._duty_t0 = time.perf_counter()

    def stats(self) -> dict:
        # engine fields are read without a lock: the pump owns the engine,
        # and these are GIL-atomic reads of ints/lists used for telemetry
        engine_stats = self.engine.stats()
        # phase totals are pump-thread-owned (see duty_cycle): snapshot
        # outside the mutex like the engine fields
        phase_seconds = {k: round(v, 6) for k, v in self._phase_totals.items()}
        duty = self.duty_cycle()
        duty_elapsed = round(time.perf_counter() - self._duty_t0, 6)
        with self._mutex:
            return {
                **engine_stats,
                "replica": self.replica_id,
                "queued_inbox": len(self._inbox),
                "ticks": self._ticks,
                "completed": self._completed,
                "avg_active_slots": (
                    round(self._active_sum / self._ticks, 3) if self._ticks else 0.0
                ),
                "max_active_slots": self._max_active,
                # overload / robustness surface
                "max_queue": self.max_queue,
                "draining": int(self._draining),
                "shed": self._shed,
                "expired": self._expired,
                "cancelled": self._cancelled,
                "requeued": self._requeued,
                "tick_failures": self._tick_failures,
                "pump_leaked": self._pump_leaked,
                "abandoned": int(self._abandoned),
                "tick_stall_budget_s": self.tick_stall_budget_s,
                "warmup_budget_s": self.warmup_budget_s,
                # tick-phase attribution: cumulative seconds per phase and
                # the host/device/idle duty cycle over the current window
                # (bench diffs phase_seconds snapshots for per-level duty)
                "phase_seconds": phase_seconds,
                "duty_elapsed_s": duty_elapsed,
                "duty_cycle": duty,
            }

    def warmup(self, max_new_tokens: int = 4) -> dict:
        """Compile the paged serving families before traffic (and before
        the compile fence arms — serve startup and bench call this under
        ``SENTIO_COMPILE_FENCE=1``). Coverage, all through the normal
        submit path so the pump keeps sole engine ownership:

        * one cold admission per achievable prefill-width bucket;
        * a radix head chain, then one admission per feasible
          (prior-bucket x suffix-width) pair sharing exactly that many
          pages with the head — any later request's radix hit lands on a
          compiled ``prior_prefill_scatter`` variant;
        * every tick-ladder rung, pinned deterministically via the
          engine's ``force_tick_steps`` hint (one short generation per
          rung);
        * a concurrent short-prompt burst sized to fill the multi-row
          admission buckets (best-effort: row grouping depends on drain
          timing).

        The full declared variant space remains the compile manifest's
        job (``sentio audit``); a fence error after this warmup names the
        residual variant to add here. Returns the prompt count and the
        XLA compiles the burst triggered."""
        with self._mutex:
            # stall watchdog stands down for the duration: warmup ticks
            # include multi-second cold compiles that would otherwise read
            # as a wedged pump (heartbeat stale + pending work). The
            # stand-down expires at warmup_budget_s (heartbeat_age) so a
            # wedge DURING warmup still quarantines instead of hanging the
            # spawn/rebuild path.
            self._warming = True
            self._warming_since = time.perf_counter()
        try:
            return self._warmup_impl(max_new_tokens)
        finally:
            with self._mutex:
                self._warming = False
                self._warming_since = 0.0

    def _warmup_impl(self, max_new_tokens: int) -> dict:
        import threading

        from sentio_tpu.analysis.audit import fence

        eng = self.engine
        before = fence.compiles_total()
        page = eng.page_size
        window = eng.max_pages_per_seq * page
        reserve = max_new_tokens + 2  # admission keeps this much headroom
        space = eng.compile_variant_space()
        widths = sorted({d["width"] for d in space["paged.prefill_scatter"]})
        pnbs = sorted({d["pnb"]
                       for d in space.get("paged.prior_prefill_scatter", [])
                       if d.get("pnb")})
        prompts = 0

        def run(text: str) -> None:
            nonlocal prompts
            # deadline_s=0 opts OUT of the service default deadline: warmup
            # generations include multi-second cold compiles, and expiring
            # them would abort startup (and leave fence variants uncompiled)
            self.generate(text, max_new_tokens=max_new_tokens,
                          temperature=0.0, deadline_s=0)
            prompts += 1

        # ByteTokenizer: 1 char = 1 token, +1 for BOS — a (w - 1)-char
        # prompt admits at exactly width bucket w. Each width uses a
        # DISTINCT digit: same-char prompts would radix-match the previous
        # width's inserted pages and take the prior path, leaving the cold
        # prefill_scatter variant uncompiled.
        for i, width in enumerate(widths):
            n = min(width, window - reserve) - 1
            if n >= 1:
                run(str(i % 10) * n)
        if pnbs:
            head_chars = min(window - reserve, max(pnbs) * page + 2) - 1
            if head_chars >= page:
                head = "h" * head_chars
                run(head)  # seeds the radix chain the combos match into
                run(head)  # full-match re-admission: deepest-prior variant
                combo = 0
                for pnb in pnbs:
                    # share exactly pnb pages with the head (BOS + chars),
                    # then diverge into a width-bucket suffix; the cycled
                    # suffix char (never 'h') keeps combos from matching
                    # EACH OTHER deeper than the intended prior
                    keep = pnb * page - 1
                    if keep < 1 or keep > len(head):
                        continue
                    for width in widths:
                        if pnb * page + width > window - reserve:
                            continue
                        fill = "abcdefgijklmnopqrstuvwxyz"[combo % 25]
                        run(head[:keep] + fill * width)
                        combo += 1
        # every declared fused-scan length, pinned via force_tick_steps so
        # rung coverage never races backlog timing (each rung decodes at
        # least max_new_tokens steps only if the rung allows — one short
        # generation per rung suffices to compile it)
        n_short = max(min(widths[0], window - reserve) - 1, 1)
        try:
            for rung in eng.tick_step_sizes():
                eng.force_tick_steps = rung
                run("r" * n_short)
        finally:
            eng.force_tick_steps = None
        # concurrent burst for the >1-row admission buckets; capped — row
        # grouping needs only max(ADMIT_BUCKETS)-deep backlog, not one
        # thread per production slot (run() is not used here — the count
        # is added after the join, avoiding a cross-thread race)
        burst_n = min(3 * eng.max_slots, 4 * max(eng.ADMIT_BUCKETS))
        threads = [
            threading.Thread(
                target=self.generate, args=("b" * n_short,),
                kwargs={"max_new_tokens": max_new_tokens,
                        "temperature": 0.0, "deadline_s": 0},
                name=f"paged-warmup-{k}", daemon=True,
            )
            for k in range(burst_n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            # each burst generate bounds itself at default_timeout_s; the
            # join only outwaits that, never blocks forever on a wedged pump
            t.join(timeout=self.default_timeout_s + 60.0)
        prompts += len(threads)
        with self._mutex:
            # warmup TTFTs are compile-dominated — seeding the admission
            # EMA with them would shed the first real deadline-carrying
            # requests on a wildly inflated projected wait
            self._ttft_ema = 0.0
        return {"prompts": prompts,
                "xla_compiles": fence.compiles_total() - before}

    # ----------------------------------------------------------------- pump

    def _ensure_pump(self) -> None:  # lock-held: _mutex
        assert_held(self._mutex)
        if not self._pump_running:
            self._pump_running = True
            # fresh burst, fresh liveness: without this stamp the watchdog
            # would read the PREVIOUS burst's last heartbeat against the
            # new burst's pending work and false-positive a stall in the
            # spawn window
            self._heartbeat_ts = time.perf_counter()
            self._pump = threading.Thread(
                target=self._run, name="paged-decode-pump", daemon=True
            )
            self._pump.start()

    def _run(self) -> None:
        # sanitizer: pump threads are born per burst — each new pump is an
        # authorized ownership transfer of the single-driver engine
        bind_engine_owner(self.engine)
        # short ticks while callers wait in OUR inbox, not just the engine
        # queue (len() reads are GIL-atomic; this is a hint, not a lock)
        # depth, not a bool: the engine scales its tick size by backlog
        self.engine.pressure_hint = lambda: len(self._inbox)  # lint: allow(lock-discipline)
        recorder = get_flight_recorder()
        metrics = get_metrics()
        # tracing manager resolved ONCE per pump: when tracing is off
        # (default) the per-tick cost is a single bool test — no span
        # objects, no context managers on the hot path
        from sentio_tpu.infra.tracing import get_tracing

        tracing = get_tracing()
        # baselines for diffing the engine's lifetime counters into per-tick
        # attributions (pump-local: a restarted pump re-baselines, so the
        # first tick of a new burst never inherits the previous burst's work)
        from sentio_tpu.analysis.audit import fence

        def paged_compiles() -> int:
            # per-ENGINE attribution: sum the cache-miss counts of this
            # engine's own FamilyFn instances (their `_seen` fields) — a
            # concurrent contiguous-engine compile, train step, or a
            # second paged service in the same process must not be pinned
            # on an innocent tick of THIS pump
            total = 0
            for attr in ContinuousBatchingEngine.FAMILY_ATTRS:
                fn = getattr(self.engine, attr, None)
                total += getattr(fn, "_seen", 0) or 0
            return total

        last_prefill = self.engine.prefill_tokens_total
        last_decode = self.engine.decode_tokens_total
        last_spec = self.engine.spec_emitted_total
        last_compiles = paged_compiles()
        fence.drain_events()  # events before this burst belong to no tick
        last_hit_toks = self.engine.prefix_hit_tokens_total
        last_miss_toks = self.engine.prefix_miss_tokens_total
        while True:
            t_iter = now = time.perf_counter()
            with self._mutex:
                # heartbeat: the watchdog's liveness signal. Stamped at the
                # top of EVERY loop iteration, so a tick wedged inside the
                # device dispatch below leaves the stamp aging while the
                # backlog grows — exactly the stall signature
                self._heartbeat_ts = now
                for ticket in self._inbox:
                    if ticket.cancelled:
                        # abandoned before admission
                        self._close_cancelled_locked(ticket)
                        continue
                    if (ticket.deadline_ts is not None
                            and now >= ticket.deadline_ts):
                        # expired before admission: never pay prefill for a
                        # caller that already gave up
                        self._expired += 1
                        metrics.record_shed("expired")
                        self._finish_error_locked(
                            ticket,
                            DeadlineExceededError(
                                "deadline expired before admission"),
                            "expired",
                        )
                        continue
                    rid = self.engine.submit(
                        ticket.prompt,
                        max_new_tokens=ticket.max_new_tokens,
                        temperature=ticket.temperature,
                        deadline_ts=ticket.deadline_ts,
                        top_k=ticket.top_k,
                        prior_tokens=ticket.prior_tokens,
                        seed=ticket.seed,
                    )
                    self._tickets[rid] = ticket
                self._inbox.clear()
                # abandoned or expired callers: stop decoding for nobody,
                # free the slot for live traffic
                for rid, ticket in list(self._tickets.items()):
                    if ticket.cancelled:
                        self.engine.cancel(rid)
                        self._tickets.pop(rid, None)
                        self._close_cancelled_locked(ticket)
                    elif (ticket.deadline_ts is not None
                          and now >= ticket.deadline_ts):
                        self.engine.cancel(rid)
                        self._tickets.pop(rid, None)
                        self._expired += 1
                        metrics.record_shed("expired")
                        self._finish_error_locked(
                            ticket,
                            DeadlineExceededError(
                                "deadline expired mid-decode; request "
                                "cancelled"),
                            "expired",
                        )
                if self._closed or not self.engine.has_work:
                    # flag flips inside the mutex: a racing submit either
                    # lands in the inbox before this check (we continue) or
                    # sees _pump_running=False and starts a fresh pump
                    self._pump_running = False
                    if self._closed:
                        self._fail_all_locked("service closed")
                    return
            # device work runs WITHOUT any lock: the pump is the engine's
            # only driver, and submitters must never wait on a decode tick
            t_drain = time.perf_counter()
            try:
                if tracing.enabled:
                    # StepTraceAnnotation around the tick: an armed XLA
                    # profiler window (/debug/profile) lines its device
                    # traces up with flight ticks by step number
                    with tracing.profile_step(
                        "decode_tick",
                        step=self._ticks + 1,  # lint: allow(lock-discipline) — GIL-atomic read
                    ):
                        finished = self.engine.step()
                else:
                    finished = self.engine.step()
                tick_dur_s = time.perf_counter() - t_drain
            except Exception:
                t_fail = time.perf_counter()
                logger.exception(
                    "paged decode tick failed; attempting crash containment")
                # flush the FAILED iteration's partial phase snapshot
                # (residual folded into "other"): the success path's
                # record/amend never runs on this branch, and without the
                # flush a chaos round's Perfetto trace holes every failed
                # tick and the duty-cycle gauge under-counts host time.
                # sum(phase_ms) == pump_ms holds here too, by construction.
                try:
                    # full bounded key shape (zeros included): the tier-1
                    # conservation gate pins phase_ms records to exactly
                    # TICK_PHASES, failed ticks included
                    phase_s = dict.fromkeys(TICK_PHASES, 0.0)
                    partial = getattr(
                        self.engine, "partial_step_phases", dict)() or {}
                    for key, val in partial.items():
                        if key in phase_s:
                            phase_s[key] = val
                    phase_s["inbox_drain"] = t_drain - t_iter
                    pump_s = t_fail - t_iter
                    phase_s["other"] = phase_s.get("other", 0.0) + max(
                        pump_s - sum(phase_s.values()), 0.0
                    )
                    recorder.record_tick(
                        event="tick_failure", replica=self.replica_id,
                        dur_ms=round((t_fail - t_drain) * 1e3, 3),
                        pump_ms=round(pump_s * 1e3, 3),
                        phase_ms=phases_to_ms(phase_s),
                    )
                    metrics.record_tick_phases(phase_s)
                    for key, val in phase_s.items():
                        self._phase_totals[key] = (
                            self._phase_totals.get(key, 0.0) + val
                        )
                except Exception:  # noqa: BLE001 — telemetry best-effort
                    logger.debug("failed-tick phase telemetry failed",
                                 exc_info=True)
                # the failed dispatch may have consumed the donated pool
                # buffers and left slots half-admitted — rebuild the decode
                # state so the NEXT request gets a working engine instead of
                # a permanently poisoned one. Reset runs BEFORE waiters are
                # touched and before _pump_running flips: this pump still
                # exclusively owns the engine, so a retrying caller cannot
                # start a new pump that races the reset.
                reset_ok = True
                try:
                    self.engine.reset()
                except Exception:
                    logger.exception("paged engine reset failed; paged path disabled")
                    reset_ok = False
                casualties: list[_Ticket] = []
                with self._mutex:
                    self._tick_failures += 1
                    if not reset_ok:
                        self._pump_running = False
                        self._broken = True
                        self._fail_all_locked(
                            "decode tick failed; engine reset failed")
                        return
                    # crash containment: the reset brought the engine back —
                    # requeue innocent waiters instead of failing every one
                    # of them. ADMITTED tickets were part of the failed tick
                    # and burn one retry; inbox tickets never dispatched, so
                    # they requeue for free (charging them would let a
                    # request exhaust its budget with zero execution
                    # attempts). Only exhausted-budget tickets — or streams
                    # that already delivered tokens, which cannot restart
                    # without duplicating output — get the error result.
                    survivors: list[_Ticket] = []
                    requeued = 0
                    for ticket in self._tickets.values():
                        if ticket.event.is_set():
                            continue
                        if ticket.cancelled:
                            # abandoned caller swept up in the crash
                            self._close_cancelled_locked(ticket)
                            continue
                        resumable = (
                            ticket.stream_q is None or ticket.sent_tokens == 0
                        )
                        if resumable and ticket.retries_left > 0:
                            ticket.retries_left -= 1
                            requeued += 1
                            survivors.append(ticket)
                        else:
                            casualties.append(ticket)
                    for ticket in self._inbox:
                        if ticket.event.is_set():
                            continue
                        if ticket.cancelled:
                            self._close_cancelled_locked(ticket)
                            continue
                        survivors.append(ticket)  # free: never dispatched
                    self._tickets.clear()
                    self._inbox.clear()
                    self._inbox.extend(survivors)
                    self._requeued += requeued
                    for ticket in casualties:
                        self._fail_ticket_locked(ticket, "decode tick failed")
                    if casualties:
                        # counted BEFORE the early returns below, or pump
                        # exits (no survivors / closed) would drop exactly
                        # the sheds where waiters actually failed
                        metrics.record_shed("crash", len(casualties))
                    if self._closed:
                        self._pump_running = False
                        self._fail_all_locked("service closed")
                        return
                    if not self._inbox:
                        self._pump_running = False
                        return
                # requeued tickets resubmit at the top of the loop; THIS
                # pump keeps engine ownership across the reset (no handoff)
                continue
            # in-tick occupancy from the engine: rows that shared the fused
            # decode dispatch (post-tick slot counts would miss requests that
            # retired inside the tick)
            active = getattr(self.engine, "last_tick_active", None)
            if active is None:
                active = sum(s.active for s in self.engine.slots)
            t_step_end = time.perf_counter()
            # flight-recorder tick event BEFORE delivery: finish_engine in
            # the deliver section stamps tick_last from the recorder's
            # sequence, and the request-window filter (first < tick <=
            # last) must include the tick a request FINISHED in — recording
            # after delivery would silently drop every request's final tick
            # from /debug/flight. The completed phase decomposition cannot
            # exist yet (delivery hasn't happened); it is AMENDED onto this
            # event below. Telemetry is strictly best-effort — an exception
            # here must never kill the pump (waiters would hang).
            tick_seq = None
            try:
                engine = self.engine
                queued = len(engine._queue)
                inbox = len(self._inbox)  # lint: allow(lock-discipline) — GIL-atomic depth hint
                free = engine.allocator.free_pages
                radix = getattr(engine, "_radix", None)
                # XLA compiles this tick triggered (jit-family cache growth,
                # analysis/audit/fence.py) — steady-state serving should
                # record 0 here; the event list names the offending family
                # and abstract signature when it does not
                compiles_now = paged_compiles()
                compile_fields: dict = {
                    "xla_compiles": compiles_now - last_compiles,
                }
                if compiles_now != last_compiles:
                    # the event ring is process-global and drained
                    # destructively — with several engines alive the
                    # family filter keeps foreign events off this tick,
                    # but a second paged pump may consume events first
                    # (counts above stay exact either way)
                    compile_fields["compile_events"] = [
                        e for e in fence.drain_events()
                        if e["family"].startswith(("paged.", "paged_spec."))
                    ]
                last_compiles = compiles_now
                tick_seq = recorder.record_tick(
                    **compile_fields,
                    replica=self.replica_id,
                    dur_ms=round(tick_dur_s * 1e3, 3),
                    active_slots=int(active),
                    queue_depth=queued,
                    inbox_depth=inbox,
                    prefill_tokens=engine.prefill_tokens_total - last_prefill,
                    decode_tokens=engine.decode_tokens_total - last_decode,
                    spec_accepted=engine.spec_emitted_total - last_spec,
                    # prompt tokens this tick served read-only from the radix
                    # prefix cache vs actually forwarded, plus the cache's
                    # page occupancy — the per-tick evidence of prefill
                    # skipped (replaces the old boolean hit/miss counts)
                    prefix_hit_tokens=(
                        engine.prefix_hit_tokens_total - last_hit_toks),
                    prefix_miss_tokens=(
                        engine.prefix_miss_tokens_total - last_miss_toks),
                    prefix_cache_pages=(radix.pages_held if radix else 0),
                    free_pages=free,
                    used_pages=engine.allocator.num_pages - 1 - free,
                    # overload counters (lifetime totals — diffs between
                    # consecutive ticks attribute sheds to a tick window)
                    shed_total=self._shed,  # lint: allow(lock-discipline) — GIL-atomic total
                    expired_total=self._expired,  # lint: allow(lock-discipline) — GIL-atomic total
                    cancelled_total=self._cancelled,  # lint: allow(lock-discipline) — GIL-atomic total
                )
                last_prefill = engine.prefill_tokens_total
                last_decode = engine.decode_tokens_total
                last_spec = engine.spec_emitted_total
                last_hit_toks = engine.prefix_hit_tokens_total
                last_miss_toks = engine.prefix_miss_tokens_total
                metrics.record_tick(tick_dur_s, int(active), queued + inbox)
            except Exception:  # noqa: BLE001
                logger.debug("tick telemetry failed", exc_info=True)
            t_deliver_start = time.perf_counter()
            now = t_deliver_start
            with self._mutex:
                self._heartbeat_ts = now  # tick survived: fresh liveness
                self._ticks += 1
                self._active_sum += active
                self._max_active = max(self._max_active, active)
                # push newly emitted tokens to streaming tickets still in
                # flight (the engine's slot.emitted grows by up to
                # steps_per_tick per tick)
                for slot in self.engine.slots:
                    if not slot.active:
                        continue
                    ticket = self._tickets.get(slot.request_id)
                    if ticket is None:
                        continue
                    # TTFT: first tick where this sequence's sampled tokens
                    # became host-visible (finish-inside-first-tick requests
                    # are stamped at completion below instead)
                    if slot.emitted and ticket.t_first == 0.0:
                        ticket.t_first = now
                        ticket.tokens_first = len(slot.emitted)
                        metrics.record_ttft(now - ticket.t_submit,
                                            path=ticket.path)
                        self._note_ttft_locked(now - ticket.t_submit)
                    if ticket.stream_q is None:
                        continue
                    if len(slot.emitted) > ticket.sent_tokens:
                        ticket.stream_q.put(
                            ("toks", list(slot.emitted[ticket.sent_tokens:]))
                        )
                        ticket.sent_tokens = len(slot.emitted)
                for result in finished:
                    # which replica produced this result, for stats sinks
                    # and tracing spans downstream (PagedResult defaults -1)
                    result.replica_id = self.replica_id
                    ticket = self._tickets.pop(result.request_id, None)
                    if ticket is None:
                        continue
                    if result.finish_reason == "expired":
                        # the ENGINE dropped it (deadline passed while in
                        # its queue) — same typed error as a pump-side drop
                        self._expired += 1
                        metrics.record_shed("expired")
                        self._finish_error_locked(
                            ticket,
                            DeadlineExceededError(
                                "deadline expired while queued for a slot"),
                            "expired",
                        )
                        continue
                    self._completed += 1
                    if ticket.t_first == 0.0:
                        # finished inside its first tick: _note_finished will
                        # stamp TTFT=now − submit; fold the same sample into
                        # the admission-control EMA here (mutex held)
                        self._note_ttft_locked(now - ticket.t_submit)
                    self._note_finished(ticket, result, now, metrics, recorder)
                    ticket.result = result
                    if ticket.stream_q is not None:
                        ticket.stream_q.put(("done", result))
                    ticket.event.set()
            t_deliver_end = time.perf_counter()
            # tick-phase decomposition (infra/phases.py): the engine's own
            # section timings plus this pump's inbox_drain/deliver spans.
            # Residual (the telemetry block above, mutex waits, call
            # overhead) folds into "other", so sum(phase_ms) == pump_ms
            # holds by CONSTRUCTION — the tier-1 conservation test pins it,
            # and Perfetto slices built from phase_ms nest exactly inside
            # their tick. The dict is AMENDED onto the already-recorded
            # tick event (amend_tick restamps t_s to this span's end, the
            # convention the Chrome exporter subtracts pump_ms from).
            phase_s = dict(self.engine.last_step_phases)
            phase_s["inbox_drain"] = t_drain - t_iter
            phase_s["deliver"] = t_deliver_end - t_deliver_start
            pump_s = t_deliver_end - t_iter
            phase_s["other"] = phase_s.get("other", 0.0) + max(
                pump_s - sum(phase_s.values()), 0.0
            )
            try:
                if tick_seq is not None:
                    recorder.amend_tick(
                        tick_seq,
                        pump_ms=round(pump_s * 1e3, 3),
                        phase_ms=phases_to_ms(phase_s),
                    )
                metrics.record_tick_phases(phase_s)
            except Exception:  # noqa: BLE001
                logger.debug("phase telemetry failed", exc_info=True)
            # the amend/metrics cost itself rides the duty-cycle totals as
            # "other" (it cannot ride the record it just amended). Totals
            # are pump-thread-owned floats; readers snapshot them
            # GIL-atomically (see duty_cycle()).
            phase_s["other"] += time.perf_counter() - t_deliver_end
            for key, val in phase_s.items():
                self._phase_totals[key] = self._phase_totals.get(key, 0.0) + val

    def _note_ttft_locked(self, ttft_s: float) -> None:  # lock-held: _mutex
        """Fold one observed TTFT into the EMA admission control projects
        queue wait from (alpha 0.2: smooth, still tracks load shifts)."""
        assert_held(self._mutex)
        if self._ttft_ema <= 0.0:
            self._ttft_ema = ttft_s
        else:
            self._ttft_ema = 0.8 * self._ttft_ema + 0.2 * ttft_s

    @staticmethod
    def _note_finished(ticket: _Ticket, result: PagedResult, now: float,
                       metrics, recorder) -> None:
        """Per-sequence completion telemetry: TTFT (if the whole generation
        fit inside one tick), TPOT over the post-first-tick tokens, and the
        flight record's engine section. Best-effort — never raises."""
        try:
            n = len(result.tokens)
            if ticket.t_first == 0.0:
                # whole generation finished inside its first tick: TTFT is
                # real, but there is no post-first-token interval to divide
                # — recording tpot=0.0 here would drag the histogram's p50
                # toward zero and fake a throughput the engine doesn't have
                ticket.t_first = now
                ticket.tokens_first = n
                metrics.record_ttft(now - ticket.t_submit, path=ticket.path)
            tail = n - ticket.tokens_first
            tpot_s = (now - ticket.t_first) / tail if tail > 0 else None
            if tpot_s is not None:
                metrics.record_tpot(tpot_s, path=ticket.path)
            if ticket.request_id:
                recorder.finish_engine(
                    ticket.request_id,
                    ttft_ms=round((ticket.t_first - ticket.t_submit) * 1e3, 2),
                    tpot_ms=(round(tpot_s * 1e3, 3)
                             if tpot_s is not None else None),
                    tokens=n,
                    prompt_tokens=result.prompt_tokens,
                    prefill_tokens=result.prefill_tokens,
                    prefix_hit_tokens=result.prefix_hit_tokens,
                    finish_reason=result.finish_reason,
                )
        except Exception:  # noqa: BLE001
            logger.debug("completion telemetry failed", exc_info=True)

    def _close_cancelled_locked(self, ticket: _Ticket) -> None:  # lock-held: _mutex
        """Account one abandoned (caller-cancelled) ticket and pin the end
        of its flight-record tick window — an open engine section would keep
        absorbing unrelated future ticks into the request's /debug/flight
        view. ONE implementation for the inbox sweep, the admitted sweep,
        and both crash-containment paths."""
        assert_held(self._mutex)
        self._cancelled += 1
        if ticket.request_id:
            get_flight_recorder().finish_engine(
                ticket.request_id, finish_reason="cancelled"
            )

    def _finish_error_locked(
        self, ticket: _Ticket, exc: Exception, finish_reason: str
    ) -> None:  # lock-held: _mutex
        """Terminate a ticket with a TYPED error the caller re-raises
        (deadline expiry, shed-at-requeue) instead of a result."""
        assert_held(self._mutex)
        finish_ticket_error(ticket, exc, finish_reason)

    def _fail_ticket_locked(self, ticket: _Ticket, reason: str) -> None:  # lock-held: _mutex
        """Terminate a ticket with the finish_reason='error' result (the
        legacy decode-failure surface callers already handle)."""
        assert_held(self._mutex)
        if ticket.event.is_set():
            return
        ticket.result = PagedResult(
            request_id=-1, text="", tokens=[],
            prompt_tokens=0, finish_reason="error",
        )
        if ticket.request_id:
            get_flight_recorder().finish_engine(
                ticket.request_id, finish_reason="error", error=reason
            )
        if ticket.stream_q is not None:
            ticket.stream_q.put(("done", ticket.result))
        ticket.event.set()

    def _fail_all_locked(self, reason: str) -> None:  # lock-held: _mutex
        """A dying pump must not leave callers hanging forever."""
        assert_held(self._mutex)
        for ticket in list(self._tickets.values()) + self._inbox:
            self._fail_ticket_locked(ticket, reason)
        self._tickets.clear()
        self._inbox.clear()
