"""PagedGenerationService: continuous batching as the live decode path.

Bridges the synchronous serving pipeline (graph nodes run on worker
threads, one per in-flight ``/chat``) onto ONE shared
:class:`~sentio_tpu.runtime.paged.ContinuousBatchingEngine`: every caller's
``generate`` drops its request into an inbox and blocks on its own event; a
single pump thread owns the engine outright — drain inbox → admit → fused
decode step → retire — for as long as any slot is live. Staggered requests
therefore share decode ticks (the whole point of continuous batching):
request B joins the compiled decode program at whatever step request A has
reached, no recompilation, no waiting for A to finish.

This replaces the reference's one-request-per-HTTP-call generation
(/root/reference/src/api/handlers/chat.py:148 — each graph.ainvoke owns its
LLM call end to end) and closes the round-1 gap where the paged engine
existed but nothing in the serving path used it.

Thread-safety: the engine is single-threaded by design and is touched ONLY
by the pump thread (no lock held across device ticks — an engine-wide lock
would let the pump starve submitters, since a hot loop reacquires an
uncontended lock before waiters wake). Submitters and the pump meet at
``_mutex``, held only for quick inbox/bookkeeping operations.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
from dataclasses import dataclass, field
from typing import Iterator, Optional

from sentio_tpu.runtime.paged import ContinuousBatchingEngine, PagedResult

logger = logging.getLogger(__name__)

__all__ = ["PagedGenerationService", "GenerationTimeout"]


class GenerationTimeout(Exception):
    pass


@dataclass
class _Ticket:
    prompt: str
    max_new_tokens: int
    temperature: float
    event: threading.Event = field(default_factory=threading.Event)
    result: Optional[PagedResult] = None
    # streaming callers: the pump pushes ("toks", [ids...]) deltas after each
    # tick and ("done", result) at retirement; None for plain generate()
    stream_q: Optional[_queue.Queue] = None
    sent_tokens: int = 0  # how many emitted tokens were already pushed
    # caller abandoned (timeout / disconnected stream): the pump cancels the
    # engine request instead of decoding to max_new for nobody
    cancelled: bool = False


class PagedGenerationService:
    """Thread-safe submit/wait facade + pump thread over the paged engine."""

    def __init__(
        self,
        engine: ContinuousBatchingEngine,
        default_timeout_s: float = 600.0,
    ) -> None:
        self.engine = engine
        self.default_timeout_s = default_timeout_s
        self._mutex = threading.Lock()  # inbox + bookkeeping ONLY, never device work
        self._inbox: list[_Ticket] = []
        self._tickets: dict[int, _Ticket] = {}  # rid -> ticket, post-admission
        self._pump: Optional[threading.Thread] = None
        self._pump_running = False
        self._closed = False
        self._broken = False  # reset failed: paged path permanently down
        # occupancy telemetry (the serving-path answer to BatcherStats):
        # ticks with >1 active slot are decode steps shared across requests
        self._ticks = 0
        self._active_sum = 0
        self._max_active = 0
        self._completed = 0

    # ------------------------------------------------------------------ api

    def generate(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
    ) -> PagedResult:
        """Submit one request and block until its tokens are done. Safe to
        call from any number of threads concurrently — that concurrency IS
        the batch."""
        ticket = _Ticket(prompt, max_new_tokens, temperature)
        with self._mutex:
            if self._closed:
                raise RuntimeError("generation service is closed")
            if self._broken:
                raise RuntimeError("paged decode engine is down (reset failed)")
            self._inbox.append(ticket)
            self._ensure_pump()
        if not ticket.event.wait(timeout_s or self.default_timeout_s):
            ticket.cancelled = True  # pump frees the slot on its next loop
            raise GenerationTimeout(
                f"generation did not finish within "
                f"{timeout_s or self.default_timeout_s:.0f}s"
            )
        assert ticket.result is not None
        return ticket.result

    def generate_stream(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
    ) -> Iterator[str]:
        """Streaming variant: yields decoded text increments as the shared
        decode batch produces them (chunks of up to steps_per_tick tokens —
        the streaming request STAYS in the continuous batch instead of
        monopolizing a contiguous-cache engine). UTF-8 safe: bytes buffer
        until they decode cleanly."""
        ticket = _Ticket(prompt, max_new_tokens, temperature, stream_q=_queue.Queue())
        with self._mutex:
            if self._closed:
                raise RuntimeError("generation service is closed")
            if self._broken:
                raise RuntimeError("paged decode engine is down (reset failed)")
            self._inbox.append(ticket)
            self._ensure_pump()

        tokenizer = self.engine.tokenizer
        deadline = timeout_s or self.default_timeout_s
        emitted: list[int] = []
        flushed = ""
        try:
            while True:
                try:
                    kind, payload = ticket.stream_q.get(timeout=deadline)
                except _queue.Empty:
                    raise GenerationTimeout(
                        f"stream produced nothing for {deadline:.0f}s"
                    ) from None
                if kind == "toks":
                    emitted.extend(payload)
                else:  # "done"
                    result: PagedResult = payload
                    if result.finish_reason == "error":
                        raise RuntimeError("paged decode failed mid-stream")
                    emitted = list(result.tokens)  # authoritative final sequence
                text = tokenizer.decode(emitted)
                if kind == "done":
                    # final flush is unconditional: the finished answer may
                    # genuinely end in a replacement char
                    if len(text) > len(flushed):
                        yield text[len(flushed):]
                    return
                # mid-stream: withhold AT MOST the final char — a trailing
                # '�' may be an incomplete UTF-8 sequence that the next token
                # resolves (a genuine replacement char flushes next round;
                # holding the whole tail would stall streams whose chunks
                # keep ending in replacement chars)
                safe = text[:-1] if text.endswith("�") else text
                if len(safe) > len(flushed):
                    yield safe[len(flushed):]
                    flushed = safe
        finally:
            # abandoned mid-decode (timeout, consumer disconnect → generator
            # close): tell the pump to cancel instead of decoding for nobody
            if ticket.result is None:
                ticket.cancelled = True

    def close(self) -> None:
        with self._mutex:
            self._closed = True
        if self._pump is not None:
            self._pump.join(timeout=10.0)
            self._pump = None

    def stats(self) -> dict:
        # engine fields are read without a lock: the pump owns the engine,
        # and these are GIL-atomic reads of ints/lists used for telemetry
        engine_stats = self.engine.stats()
        with self._mutex:
            return {
                **engine_stats,
                "queued_inbox": len(self._inbox),
                "ticks": self._ticks,
                "completed": self._completed,
                "avg_active_slots": (
                    round(self._active_sum / self._ticks, 3) if self._ticks else 0.0
                ),
                "max_active_slots": self._max_active,
            }

    # ----------------------------------------------------------------- pump

    def _ensure_pump(self) -> None:  # _mutex held
        if not self._pump_running:
            self._pump_running = True
            self._pump = threading.Thread(
                target=self._run, name="paged-decode-pump", daemon=True
            )
            self._pump.start()

    def _run(self) -> None:
        # short ticks while callers wait in OUR inbox, not just the engine
        # queue (len() reads are GIL-atomic; this is a hint, not a lock)
        # depth, not a bool: the engine scales its tick size by backlog
        self.engine.pressure_hint = lambda: len(self._inbox)
        while True:
            with self._mutex:
                for ticket in self._inbox:
                    if ticket.cancelled:
                        continue
                    rid = self.engine.submit(
                        ticket.prompt,
                        max_new_tokens=ticket.max_new_tokens,
                        temperature=ticket.temperature,
                    )
                    self._tickets[rid] = ticket
                self._inbox.clear()
                # abandoned callers: stop decoding for nobody, free the slot
                for rid, ticket in list(self._tickets.items()):
                    if ticket.cancelled:
                        self.engine.cancel(rid)
                        self._tickets.pop(rid, None)
                if self._closed or not self.engine.has_work:
                    # flag flips inside the mutex: a racing submit either
                    # lands in the inbox before this check (we continue) or
                    # sees _pump_running=False and starts a fresh pump
                    self._pump_running = False
                    if self._closed:
                        self._fail_all_locked("service closed")
                    return
            # device work runs WITHOUT any lock: the pump is the engine's
            # only driver, and submitters must never wait on a decode tick
            try:
                finished = self.engine.step()
            except Exception:
                logger.exception("paged decode tick failed; failing waiters")
                # the failed dispatch may have consumed the donated pool
                # buffers and left slots half-admitted — rebuild the decode
                # state so the NEXT request gets a working engine instead of
                # a permanently poisoned one. Reset runs BEFORE waiters are
                # failed and before _pump_running flips: this pump still
                # exclusively owns the engine, so a retrying caller cannot
                # start a new pump that races the reset.
                reset_ok = True
                try:
                    self.engine.reset()
                except Exception:
                    logger.exception("paged engine reset failed; paged path disabled")
                    reset_ok = False
                with self._mutex:
                    self._pump_running = False
                    self._broken = self._broken or not reset_ok
                    self._fail_all_locked("decode tick failed")
                return
            # in-tick occupancy from the engine: rows that shared the fused
            # decode dispatch (post-tick slot counts would miss requests that
            # retired inside the tick)
            active = getattr(self.engine, "last_tick_active", None)
            if active is None:
                active = sum(s.active for s in self.engine.slots)
            with self._mutex:
                self._ticks += 1
                self._active_sum += active
                self._max_active = max(self._max_active, active)
                # push newly emitted tokens to streaming tickets still in
                # flight (the engine's slot.emitted grows by up to
                # steps_per_tick per tick)
                for slot in self.engine.slots:
                    if not slot.active:
                        continue
                    ticket = self._tickets.get(slot.request_id)
                    if ticket is None or ticket.stream_q is None:
                        continue
                    if len(slot.emitted) > ticket.sent_tokens:
                        ticket.stream_q.put(
                            ("toks", list(slot.emitted[ticket.sent_tokens:]))
                        )
                        ticket.sent_tokens = len(slot.emitted)
                for result in finished:
                    self._completed += 1
                    ticket = self._tickets.pop(result.request_id, None)
                    if ticket is not None:
                        ticket.result = result
                        if ticket.stream_q is not None:
                            ticket.stream_q.put(("done", result))
                        ticket.event.set()

    def _fail_all_locked(self, reason: str) -> None:  # _mutex held
        """A dying pump must not leave callers hanging forever."""
        for ticket in list(self._tickets.values()) + self._inbox:
            if not ticket.event.is_set():
                ticket.result = PagedResult(
                    request_id=-1, text="", tokens=[],
                    prompt_tokens=0, finish_reason="error",
                )
                if ticket.stream_q is not None:
                    ticket.stream_q.put(("done", ticket.result))
                ticket.event.set()
        self._tickets.clear()
        self._inbox.clear()
