"""PagedGenerationService: continuous batching as the live decode path.

Bridges the synchronous serving pipeline (graph nodes run on worker
threads, one per in-flight ``/chat``) onto ONE shared
:class:`~sentio_tpu.runtime.paged.ContinuousBatchingEngine`: every caller's
``generate`` drops its request into an inbox and blocks on its own event; a
single pump thread owns the engine outright — drain inbox → admit → fused
decode step → retire — for as long as any slot is live. Staggered requests
therefore share decode ticks (the whole point of continuous batching):
request B joins the compiled decode program at whatever step request A has
reached, no recompilation, no waiting for A to finish.

This replaces the reference's one-request-per-HTTP-call generation
(/root/reference/src/api/handlers/chat.py:148 — each graph.ainvoke owns its
LLM call end to end) and closes the round-1 gap where the paged engine
existed but nothing in the serving path used it.

Thread-safety: the engine is single-threaded by design and is touched ONLY
by the pump thread (no lock held across device ticks — an engine-wide lock
would let the pump starve submitters, since a hot loop reacquires an
uncontended lock before waiters wake). Submitters and the pump meet at
``_mutex``, held only for quick inbox/bookkeeping operations.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

from sentio_tpu.analysis.sanitizer import (
    assert_held,
    bind_engine_owner,
    make_lock,
)
from sentio_tpu.infra.flight import get_flight_recorder
from sentio_tpu.infra.metrics import get_metrics
from sentio_tpu.runtime.paged import ContinuousBatchingEngine, PagedResult

logger = logging.getLogger(__name__)

__all__ = ["PagedGenerationService", "GenerationTimeout"]


class GenerationTimeout(Exception):
    pass


@dataclass
class _Ticket:
    prompt: str
    max_new_tokens: int
    temperature: float
    event: threading.Event = field(default_factory=threading.Event)
    result: Optional[PagedResult] = None
    # streaming callers: the pump pushes ("toks", [ids...]) deltas after each
    # tick and ("done", result) at retirement; None for plain generate()
    stream_q: Optional[_queue.Queue] = None
    sent_tokens: int = 0  # how many emitted tokens were already pushed
    # caller abandoned (timeout / disconnected stream): the pump cancels the
    # engine request instead of decoding to max_new for nobody
    cancelled: bool = False
    # flight-recorder trace id (the serving layer's query_id) — None for
    # untraced callers; telemetry is still recorded to /metrics either way
    request_id: Optional[str] = None
    # submit / first-token wall clocks for TTFT+TPOT (0.0 = not yet seen)
    t_submit: float = 0.0
    t_first: float = 0.0
    # tokens already host-visible when t_first was stamped: TPOT divides the
    # post-first-tick interval by the tokens produced IN that interval (a
    # fused tick emits up to steps_per_tick tokens at once)
    tokens_first: int = 0

    @property
    def path(self) -> str:
        """Metric label for the TTFT/TPOT series: blocking vs streaming."""
        return "stream" if self.stream_q is not None else "paged"


class PagedGenerationService:
    """Thread-safe submit/wait facade + pump thread over the paged engine."""

    def __init__(
        self,
        engine: ContinuousBatchingEngine,
        default_timeout_s: float = 600.0,
    ) -> None:
        self.engine = engine
        self.default_timeout_s = default_timeout_s
        # inbox + bookkeeping ONLY, never device work
        self._mutex = make_lock("PagedGenerationService._mutex")
        self._inbox: list[_Ticket] = []  # guarded-by: _mutex
        self._tickets: dict[int, _Ticket] = {}  # guarded-by: _mutex
        self._pump: Optional[threading.Thread] = None  # guarded-by: _mutex
        self._pump_running = False  # guarded-by: _mutex
        self._closed = False  # guarded-by: _mutex
        self._broken = False  # guarded-by: _mutex
        # occupancy telemetry (the serving-path answer to BatcherStats):
        # ticks with >1 active slot are decode steps shared across requests
        self._ticks = 0  # guarded-by: _mutex
        self._active_sum = 0  # guarded-by: _mutex
        self._max_active = 0  # guarded-by: _mutex
        self._completed = 0  # guarded-by: _mutex

    # ------------------------------------------------------------------ api

    def generate(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> PagedResult:
        """Submit one request and block until its tokens are done. Safe to
        call from any number of threads concurrently — that concurrency IS
        the batch. A ``request_id`` ties this generation into the flight
        recorder's per-request trace (TTFT/TPOT + its decode-tick window)."""
        ticket = _Ticket(prompt, max_new_tokens, temperature,
                         request_id=request_id, t_submit=time.perf_counter())
        if request_id:
            get_flight_recorder().note_engine_submit(request_id)
        try:
            with self._mutex:
                if self._closed:
                    raise RuntimeError("generation service is closed")
                if self._broken:
                    raise RuntimeError("paged decode engine is down (reset failed)")
                self._inbox.append(ticket)
                self._ensure_pump()
        except Exception:
            # note_engine_submit already opened the tick window — close it,
            # or the record absorbs every unrelated future tick
            if request_id:
                get_flight_recorder().finish_engine(
                    request_id, finish_reason="rejected")
            raise
        if not ticket.event.wait(timeout_s or self.default_timeout_s):
            ticket.cancelled = True  # pump frees the slot on its next loop
            raise GenerationTimeout(
                f"generation did not finish within "
                f"{timeout_s or self.default_timeout_s:.0f}s"
            )
        assert ticket.result is not None
        return ticket.result

    def generate_stream(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> Iterator[str]:
        """Streaming variant: yields decoded text increments as the shared
        decode batch produces them (chunks of up to steps_per_tick tokens —
        the streaming request STAYS in the continuous batch instead of
        monopolizing a contiguous-cache engine). UTF-8 safe: bytes buffer
        until they decode cleanly."""
        ticket = _Ticket(prompt, max_new_tokens, temperature, stream_q=_queue.Queue(),
                         request_id=request_id, t_submit=time.perf_counter())
        if request_id:
            get_flight_recorder().note_engine_submit(request_id)
        try:
            with self._mutex:
                if self._closed:
                    raise RuntimeError("generation service is closed")
                if self._broken:
                    raise RuntimeError("paged decode engine is down (reset failed)")
                self._inbox.append(ticket)
                self._ensure_pump()
        except Exception:
            if request_id:
                get_flight_recorder().finish_engine(
                    request_id, finish_reason="rejected")
            raise

        tokenizer = self.engine.tokenizer
        deadline = timeout_s or self.default_timeout_s
        emitted: list[int] = []
        flushed = ""
        try:
            while True:
                try:
                    kind, payload = ticket.stream_q.get(timeout=deadline)
                except _queue.Empty:
                    raise GenerationTimeout(
                        f"stream produced nothing for {deadline:.0f}s"
                    ) from None
                if kind == "toks":
                    emitted.extend(payload)
                else:  # "done"
                    result: PagedResult = payload
                    if result.finish_reason == "error":
                        raise RuntimeError("paged decode failed mid-stream")
                    emitted = list(result.tokens)  # authoritative final sequence
                text = tokenizer.decode(emitted)
                if kind == "done":
                    # final flush is unconditional: the finished answer may
                    # genuinely end in a replacement char
                    if len(text) > len(flushed):
                        yield text[len(flushed):]
                    return
                # mid-stream: withhold AT MOST the final char — a trailing
                # '�' may be an incomplete UTF-8 sequence that the next token
                # resolves (a genuine replacement char flushes next round;
                # holding the whole tail would stall streams whose chunks
                # keep ending in replacement chars)
                safe = text[:-1] if text.endswith("�") else text
                if len(safe) > len(flushed):
                    yield safe[len(flushed):]
                    flushed = safe
        finally:
            # abandoned mid-decode (timeout, consumer disconnect → generator
            # close): tell the pump to cancel instead of decoding for nobody
            if ticket.result is None:
                ticket.cancelled = True

    def close(self) -> None:
        with self._mutex:
            self._closed = True
            pump, self._pump = self._pump, None
        # join OUTSIDE the mutex: the exiting pump needs it to fail waiters
        if pump is not None:
            pump.join(timeout=10.0)

    def stats(self) -> dict:
        # engine fields are read without a lock: the pump owns the engine,
        # and these are GIL-atomic reads of ints/lists used for telemetry
        engine_stats = self.engine.stats()
        with self._mutex:
            return {
                **engine_stats,
                "queued_inbox": len(self._inbox),
                "ticks": self._ticks,
                "completed": self._completed,
                "avg_active_slots": (
                    round(self._active_sum / self._ticks, 3) if self._ticks else 0.0
                ),
                "max_active_slots": self._max_active,
            }

    def warmup(self, max_new_tokens: int = 4) -> dict:
        """Compile the paged serving families before traffic (and before
        the compile fence arms — serve startup and bench call this under
        ``SENTIO_COMPILE_FENCE=1``). Coverage, all through the normal
        submit path so the pump keeps sole engine ownership:

        * one cold admission per achievable prefill-width bucket;
        * a radix head chain, then one admission per feasible
          (prior-bucket x suffix-width) pair sharing exactly that many
          pages with the head — any later request's radix hit lands on a
          compiled ``prior_prefill_scatter`` variant;
        * every tick-ladder rung, pinned deterministically via the
          engine's ``force_tick_steps`` hint (one short generation per
          rung);
        * a concurrent short-prompt burst sized to fill the multi-row
          admission buckets (best-effort: row grouping depends on drain
          timing).

        The full declared variant space remains the compile manifest's
        job (``sentio audit``); a fence error after this warmup names the
        residual variant to add here. Returns the prompt count and the
        XLA compiles the burst triggered."""
        import threading

        from sentio_tpu.analysis.audit import fence

        eng = self.engine
        before = fence.compiles_total()
        page = eng.page_size
        window = eng.max_pages_per_seq * page
        reserve = max_new_tokens + 2  # admission keeps this much headroom
        space = eng.compile_variant_space()
        widths = sorted({d["width"] for d in space["paged.prefill_scatter"]})
        pnbs = sorted({d["pnb"]
                       for d in space.get("paged.prior_prefill_scatter", [])
                       if d.get("pnb")})
        prompts = 0

        def run(text: str) -> None:
            nonlocal prompts
            self.generate(text, max_new_tokens=max_new_tokens,
                          temperature=0.0)
            prompts += 1

        # ByteTokenizer: 1 char = 1 token, +1 for BOS — a (w - 1)-char
        # prompt admits at exactly width bucket w. Each width uses a
        # DISTINCT digit: same-char prompts would radix-match the previous
        # width's inserted pages and take the prior path, leaving the cold
        # prefill_scatter variant uncompiled.
        for i, width in enumerate(widths):
            n = min(width, window - reserve) - 1
            if n >= 1:
                run(str(i % 10) * n)
        if pnbs:
            head_chars = min(window - reserve, max(pnbs) * page + 2) - 1
            if head_chars >= page:
                head = "h" * head_chars
                run(head)  # seeds the radix chain the combos match into
                run(head)  # full-match re-admission: deepest-prior variant
                combo = 0
                for pnb in pnbs:
                    # share exactly pnb pages with the head (BOS + chars),
                    # then diverge into a width-bucket suffix; the cycled
                    # suffix char (never 'h') keeps combos from matching
                    # EACH OTHER deeper than the intended prior
                    keep = pnb * page - 1
                    if keep < 1 or keep > len(head):
                        continue
                    for width in widths:
                        if pnb * page + width > window - reserve:
                            continue
                        fill = "abcdefgijklmnopqrstuvwxyz"[combo % 25]
                        run(head[:keep] + fill * width)
                        combo += 1
        # every declared fused-scan length, pinned via force_tick_steps so
        # rung coverage never races backlog timing (each rung decodes at
        # least max_new_tokens steps only if the rung allows — one short
        # generation per rung suffices to compile it)
        n_short = max(min(widths[0], window - reserve) - 1, 1)
        try:
            for rung in eng.tick_step_sizes():
                eng.force_tick_steps = rung
                run("r" * n_short)
        finally:
            eng.force_tick_steps = None
        # concurrent burst for the >1-row admission buckets; capped — row
        # grouping needs only max(ADMIT_BUCKETS)-deep backlog, not one
        # thread per production slot (run() is not used here — the count
        # is added after the join, avoiding a cross-thread race)
        burst_n = min(3 * eng.max_slots, 4 * max(eng.ADMIT_BUCKETS))
        threads = [
            threading.Thread(
                target=self.generate, args=("b" * n_short,),
                kwargs={"max_new_tokens": max_new_tokens,
                        "temperature": 0.0},
                daemon=True,
            )
            for _ in range(burst_n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        prompts += len(threads)
        return {"prompts": prompts,
                "xla_compiles": fence.compiles_total() - before}

    # ----------------------------------------------------------------- pump

    def _ensure_pump(self) -> None:  # lock-held: _mutex
        assert_held(self._mutex)
        if not self._pump_running:
            self._pump_running = True
            self._pump = threading.Thread(
                target=self._run, name="paged-decode-pump", daemon=True
            )
            self._pump.start()

    def _run(self) -> None:
        # sanitizer: pump threads are born per burst — each new pump is an
        # authorized ownership transfer of the single-driver engine
        bind_engine_owner(self.engine)
        # short ticks while callers wait in OUR inbox, not just the engine
        # queue (len() reads are GIL-atomic; this is a hint, not a lock)
        # depth, not a bool: the engine scales its tick size by backlog
        self.engine.pressure_hint = lambda: len(self._inbox)  # lint: allow(lock-discipline)
        recorder = get_flight_recorder()
        metrics = get_metrics()
        # baselines for diffing the engine's lifetime counters into per-tick
        # attributions (pump-local: a restarted pump re-baselines, so the
        # first tick of a new burst never inherits the previous burst's work)
        from sentio_tpu.analysis.audit import fence

        def paged_compiles() -> int:
            # per-ENGINE attribution: sum the cache-miss counts of this
            # engine's own FamilyFn instances (their `_seen` fields) — a
            # concurrent contiguous-engine compile, train step, or a
            # second paged service in the same process must not be pinned
            # on an innocent tick of THIS pump
            total = 0
            for attr in ("_step_n", "_merge_admitted", "_prefill_scatter",
                         "_prior_prefill_scatter", "_draft_prefill",
                         "_spec_tick"):
                fn = getattr(self.engine, attr, None)
                total += getattr(fn, "_seen", 0) or 0
            return total

        last_prefill = self.engine.prefill_tokens_total
        last_decode = self.engine.decode_tokens_total
        last_spec = self.engine.spec_emitted_total
        last_compiles = paged_compiles()
        fence.drain_events()  # events before this burst belong to no tick
        last_hit_toks = self.engine.prefix_hit_tokens_total
        last_miss_toks = self.engine.prefix_miss_tokens_total
        while True:
            with self._mutex:
                for ticket in self._inbox:
                    if ticket.cancelled:
                        # abandoned before admission: close the tick window
                        # note_engine_submit opened, same as the admitted-
                        # cancel path below
                        if ticket.request_id:
                            recorder.finish_engine(
                                ticket.request_id, finish_reason="cancelled"
                            )
                        continue
                    rid = self.engine.submit(
                        ticket.prompt,
                        max_new_tokens=ticket.max_new_tokens,
                        temperature=ticket.temperature,
                    )
                    self._tickets[rid] = ticket
                self._inbox.clear()
                # abandoned callers: stop decoding for nobody, free the slot
                for rid, ticket in list(self._tickets.items()):
                    if ticket.cancelled:
                        self.engine.cancel(rid)
                        self._tickets.pop(rid, None)
                        if ticket.request_id:
                            # pin tick_last NOW — an open engine section
                            # would keep absorbing unrelated future ticks
                            # into this request's /debug/flight window
                            recorder.finish_engine(
                                ticket.request_id, finish_reason="cancelled"
                            )
                if self._closed or not self.engine.has_work:
                    # flag flips inside the mutex: a racing submit either
                    # lands in the inbox before this check (we continue) or
                    # sees _pump_running=False and starts a fresh pump
                    self._pump_running = False
                    if self._closed:
                        self._fail_all_locked("service closed")
                    return
            # device work runs WITHOUT any lock: the pump is the engine's
            # only driver, and submitters must never wait on a decode tick
            try:
                t_tick = time.perf_counter()
                finished = self.engine.step()
                tick_dur_s = time.perf_counter() - t_tick
            except Exception:
                logger.exception("paged decode tick failed; failing waiters")
                # the failed dispatch may have consumed the donated pool
                # buffers and left slots half-admitted — rebuild the decode
                # state so the NEXT request gets a working engine instead of
                # a permanently poisoned one. Reset runs BEFORE waiters are
                # failed and before _pump_running flips: this pump still
                # exclusively owns the engine, so a retrying caller cannot
                # start a new pump that races the reset.
                reset_ok = True
                try:
                    self.engine.reset()
                except Exception:
                    logger.exception("paged engine reset failed; paged path disabled")
                    reset_ok = False
                with self._mutex:
                    self._pump_running = False
                    self._broken = self._broken or not reset_ok
                    self._fail_all_locked("decode tick failed")
                return
            # in-tick occupancy from the engine: rows that shared the fused
            # decode dispatch (post-tick slot counts would miss requests that
            # retired inside the tick)
            active = getattr(self.engine, "last_tick_active", None)
            if active is None:
                active = sum(s.active for s in self.engine.slots)
            # flight-recorder tick event: what THIS fused dispatch did.
            # Telemetry is strictly best-effort — an exception here must
            # never kill the pump (waiters would hang on a dead thread).
            try:
                engine = self.engine
                queued = len(engine._queue)
                inbox = len(self._inbox)  # lint: allow(lock-discipline) — GIL-atomic depth hint
                free = engine.allocator.free_pages
                radix = getattr(engine, "_radix", None)
                # XLA compiles this tick triggered (jit-family cache growth,
                # analysis/audit/fence.py) — steady-state serving should
                # record 0 here; the event list names the offending family
                # and abstract signature when it does not
                compiles_now = paged_compiles()
                compile_fields: dict = {
                    "xla_compiles": compiles_now - last_compiles,
                }
                if compiles_now != last_compiles:
                    # the event ring is process-global and drained
                    # destructively — with several engines alive the
                    # family filter keeps foreign events off this tick,
                    # but a second paged pump may consume events first
                    # (counts above stay exact either way)
                    compile_fields["compile_events"] = [
                        e for e in fence.drain_events()
                        if e["family"].startswith(("paged.", "paged_spec."))
                    ]
                last_compiles = compiles_now
                recorder.record_tick(
                    **compile_fields,
                    dur_ms=round(tick_dur_s * 1e3, 3),
                    active_slots=int(active),
                    queue_depth=queued,
                    inbox_depth=inbox,
                    prefill_tokens=engine.prefill_tokens_total - last_prefill,
                    decode_tokens=engine.decode_tokens_total - last_decode,
                    spec_accepted=engine.spec_emitted_total - last_spec,
                    # prompt tokens this tick served read-only from the radix
                    # prefix cache vs actually forwarded, plus the cache's
                    # page occupancy — the per-tick evidence of prefill
                    # skipped (replaces the old boolean hit/miss counts)
                    prefix_hit_tokens=(
                        engine.prefix_hit_tokens_total - last_hit_toks),
                    prefix_miss_tokens=(
                        engine.prefix_miss_tokens_total - last_miss_toks),
                    prefix_cache_pages=(radix.pages_held if radix else 0),
                    free_pages=free,
                    used_pages=engine.allocator.num_pages - 1 - free,
                )
                last_prefill = engine.prefill_tokens_total
                last_decode = engine.decode_tokens_total
                last_spec = engine.spec_emitted_total
                last_hit_toks = engine.prefix_hit_tokens_total
                last_miss_toks = engine.prefix_miss_tokens_total
                metrics.record_tick(tick_dur_s, int(active), queued + inbox)
            except Exception:  # noqa: BLE001
                logger.debug("tick telemetry failed", exc_info=True)
            now = time.perf_counter()
            with self._mutex:
                self._ticks += 1
                self._active_sum += active
                self._max_active = max(self._max_active, active)
                # push newly emitted tokens to streaming tickets still in
                # flight (the engine's slot.emitted grows by up to
                # steps_per_tick per tick)
                for slot in self.engine.slots:
                    if not slot.active:
                        continue
                    ticket = self._tickets.get(slot.request_id)
                    if ticket is None:
                        continue
                    # TTFT: first tick where this sequence's sampled tokens
                    # became host-visible (finish-inside-first-tick requests
                    # are stamped at completion below instead)
                    if slot.emitted and ticket.t_first == 0.0:
                        ticket.t_first = now
                        ticket.tokens_first = len(slot.emitted)
                        metrics.record_ttft(now - ticket.t_submit,
                                            path=ticket.path)
                    if ticket.stream_q is None:
                        continue
                    if len(slot.emitted) > ticket.sent_tokens:
                        ticket.stream_q.put(
                            ("toks", list(slot.emitted[ticket.sent_tokens:]))
                        )
                        ticket.sent_tokens = len(slot.emitted)
                for result in finished:
                    self._completed += 1
                    ticket = self._tickets.pop(result.request_id, None)
                    if ticket is not None:
                        self._note_finished(ticket, result, now, metrics, recorder)
                        ticket.result = result
                        if ticket.stream_q is not None:
                            ticket.stream_q.put(("done", result))
                        ticket.event.set()

    @staticmethod
    def _note_finished(ticket: _Ticket, result: PagedResult, now: float,
                       metrics, recorder) -> None:
        """Per-sequence completion telemetry: TTFT (if the whole generation
        fit inside one tick), TPOT over the post-first-tick tokens, and the
        flight record's engine section. Best-effort — never raises."""
        try:
            n = len(result.tokens)
            if ticket.t_first == 0.0:
                # whole generation finished inside its first tick: TTFT is
                # real, but there is no post-first-token interval to divide
                # — recording tpot=0.0 here would drag the histogram's p50
                # toward zero and fake a throughput the engine doesn't have
                ticket.t_first = now
                ticket.tokens_first = n
                metrics.record_ttft(now - ticket.t_submit, path=ticket.path)
            tail = n - ticket.tokens_first
            tpot_s = (now - ticket.t_first) / tail if tail > 0 else None
            if tpot_s is not None:
                metrics.record_tpot(tpot_s, path=ticket.path)
            if ticket.request_id:
                recorder.finish_engine(
                    ticket.request_id,
                    ttft_ms=round((ticket.t_first - ticket.t_submit) * 1e3, 2),
                    tpot_ms=(round(tpot_s * 1e3, 3)
                             if tpot_s is not None else None),
                    tokens=n,
                    prompt_tokens=result.prompt_tokens,
                    prefill_tokens=result.prefill_tokens,
                    prefix_hit_tokens=result.prefix_hit_tokens,
                    finish_reason=result.finish_reason,
                )
        except Exception:  # noqa: BLE001
            logger.debug("completion telemetry failed", exc_info=True)

    def _fail_all_locked(self, reason: str) -> None:  # lock-held: _mutex
        """A dying pump must not leave callers hanging forever."""
        assert_held(self._mutex)
        for ticket in list(self._tickets.values()) + self._inbox:
            if not ticket.event.is_set():
                ticket.result = PagedResult(
                    request_id=-1, text="", tokens=[],
                    prompt_tokens=0, finish_reason="error",
                )
                if ticket.request_id:
                    get_flight_recorder().finish_engine(
                        ticket.request_id, finish_reason="error", error=reason
                    )
                if ticket.stream_q is not None:
                    ticket.stream_q.put(("done", ticket.result))
                ticket.event.set()
        self._tickets.clear()
        self._inbox.clear()
