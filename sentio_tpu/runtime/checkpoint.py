"""Checkpoint / resume: versioned, atomic persistence for every piece of
restartable state the framework owns.

The reference needs almost none of this — its durable state lives in external
services, with only a BM25 pickle (reference src/core/retrievers/sparse.py:
102-157) and a fallback-response JSON on disk (resilience/fallbacks.py:32-50).
A TPU-native deployment owns real state: model param pytrees (8B-class),
corpus embedding shards for the dense index, and the serving engine's KV
page tables. SURVEY.md §5 calls for exactly this subsystem.

Design:

* **Format** — one ``arrays.npz`` (zip of raw ``.npy`` members, no pickle)
  plus a ``manifest.json`` describing the tree structure and user metadata.
  Loading is therefore safe on untrusted files (numpy refuses object arrays
  with ``allow_pickle=False``) and zero-copy-mmap-able for big checkpoints.
* **Atomicity** — writes land in a ``.tmp-*`` sibling and ``os.replace`` /
  ``rename`` into place, so a killed process never leaves a half checkpoint
  visible; readers only ever see complete step directories.
* **Versioning** — ``step_%08d`` directories under a base dir with retention
  (``keep`` newest), mirroring orbax's CheckpointManager layout without its
  tensorstore dependency surface.
* **Sharding-aware restore** — ``load_pytree(shardings=...)`` device_puts
  each leaf through its ``NamedSharding``, so an 8B param tree restores
  directly into the TP layout (parallel/sharding.py) without a host-side
  full copy per device.

bfloat16 leaves round-trip losslessly: npz cannot store bf16, so they are
bit-cast to uint16 and the manifest records the true dtype.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
import zipfile
from pathlib import Path
from typing import Any, Mapping, Optional

import numpy as np

FORMAT_VERSION = 1
_SEP = "/"


class CheckpointError(Exception):
    pass


# ------------------------------------------------------------- tree <-> flat


_TUPLE_TAG = "__tuple__"


def _flatten(tree: Any, prefix: str = "") -> dict[str, np.ndarray]:
    flat: dict[str, np.ndarray] = {}
    if isinstance(tree, Mapping):
        for k in sorted(tree):
            if not isinstance(k, str):
                raise CheckpointError(
                    f"dict key {k!r} is not a string — non-str keys would not "
                    "round-trip through the JSON manifest"
                )
            if _SEP in k or k == _TUPLE_TAG:
                raise CheckpointError(f"reserved key {k!r}")
            flat.update(_flatten(tree[k], f"{prefix}{k}{_SEP}"))
        return flat
    if isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            flat.update(_flatten(v, f"{prefix}{i}{_SEP}"))
        return flat
    flat[prefix.rstrip(_SEP)] = np.asarray(tree)
    return flat


def _unflatten(flat: Mapping[str, np.ndarray], structure: Any) -> Any:
    """Rebuild using the manifest's structure spec: leaf = key string,
    list = list, ``{"__tuple__": [...]}`` = tuple, other dict = dict."""
    if isinstance(structure, str):
        return flat[structure]
    if isinstance(structure, list):
        return [_unflatten(flat, s) for s in structure]
    if set(structure) == {_TUPLE_TAG}:
        return tuple(_unflatten(flat, s) for s in structure[_TUPLE_TAG])
    return {k: _unflatten(flat, s) for k, s in structure.items()}


def _structure_of(tree: Any, prefix: str = "") -> Any:
    """Structure skeleton for the manifest. Tuples are tagged so they rebuild
    as tuples (optax states are tuple pytrees — a list would change the
    treedef and break shardings= restore). NamedTuples degrade to plain
    tuples; restore into richer treedefs via the returned leaves if needed."""
    if isinstance(tree, Mapping):
        return {k: _structure_of(tree[k], f"{prefix}{k}{_SEP}") for k in sorted(tree)}
    if isinstance(tree, tuple):
        return {_TUPLE_TAG: [_structure_of(v, f"{prefix}{i}{_SEP}") for i, v in enumerate(tree)]}
    if isinstance(tree, list):
        return [_structure_of(v, f"{prefix}{i}{_SEP}") for i, v in enumerate(tree)]
    return prefix.rstrip(_SEP)


# --------------------------------------------------------------- save / load


def save_pytree(path: str | Path, tree: Any, meta: Optional[dict] = None) -> Path:
    """Write ``tree`` (nested dict/list of arrays) atomically to directory
    ``path``. Device arrays are pulled to host; bf16 is bit-cast to uint16."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    flat = _flatten(tree)

    arrays: dict[str, np.ndarray] = {}
    dtypes: dict[str, str] = {}
    for i, (key, arr) in enumerate(flat.items()):
        arr = np.asarray(arr)  # devices -> host
        dtypes[key] = str(arr.dtype)
        if str(arr.dtype) == "bfloat16":
            arr = arr.view(np.uint16)
        if arr.dtype == object:
            raise CheckpointError(f"object leaf at {key!r} is not checkpointable")
        arrays[f"a{i}"] = arr

    manifest = {
        "format_version": FORMAT_VERSION,
        "created_unix": time.time(),  # wall-clock: persisted manifest timestamp
        "structure": _structure_of(tree),
        "keys": {f"a{i}": k for i, k in enumerate(flat)},
        "dtypes": dtypes,
        "meta": meta or {},
    }

    tmp = Path(tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=str(path.parent)))
    try:
        with open(tmp / "arrays.npz", "wb") as f:
            np.savez(f, **arrays)
        (tmp / "manifest.json").write_text(json.dumps(manifest, indent=1))
        _replace_dir(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return path


def _replace_dir(src: Path, dst: Path) -> None:
    """Swap ``src`` into ``dst``'s place without a window where ``dst`` is
    absent: an existing ``dst`` is renamed aside first (rename is atomic;
    a crash leaves either the old or the new checkpoint visible, never
    neither), then the displaced old version is deleted."""
    old: Optional[Path] = None
    if dst.exists():
        old = dst.parent / f".old-{dst.name}-{os.getpid()}"
        if old.exists():
            shutil.rmtree(old)
        os.replace(dst, old)
    try:
        os.replace(src, dst)
    except BaseException:
        if old is not None and not dst.exists():
            os.replace(old, dst)  # roll back
        raise
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def sweep_stale_tmp(base: Path) -> None:
    """Remove leftover ``.tmp-*`` / ``.old-*`` dirs from crashed writers."""
    for p in base.glob(".tmp-*"):
        shutil.rmtree(p, ignore_errors=True)
    for p in base.glob(".old-*"):
        shutil.rmtree(p, ignore_errors=True)


def load_pytree(
    path: str | Path, shardings: Any = None, mmap: bool = False
) -> tuple[Any, dict]:
    """Read a checkpoint directory → (tree, meta).

    ``shardings``: optional pytree matching ``tree``'s structure whose leaves
    are ``jax.sharding.Sharding``s (or None); matching leaves are device_put
    through their sharding so restore lands directly in the distributed
    layout.

    ``mmap=True`` memory-maps each leaf **in place inside arrays.npz**
    instead of copying it onto the heap: ``np.savez`` stores members
    uncompressed (ZIP_STORED), so every ``.npy`` payload is a contiguous
    byte range of the zip that ``np.memmap`` can map read-only. N replica
    worker processes loading the same checkpoint then share ONE page-cache
    copy of the weights per host (runtime/worker.py's weight-sharing
    model) rather than N private heap copies. Falls back to the copying
    path for any member that is not plainly mappable.
    """
    path = Path(path)
    mf_path = path / "manifest.json"
    if not mf_path.exists():
        raise CheckpointError(f"no checkpoint at {path}")
    manifest = json.loads(mf_path.read_text())
    if manifest.get("format_version") != FORMAT_VERSION:
        raise CheckpointError(
            f"unsupported checkpoint format {manifest.get('format_version')}"
        )
    npz = path / "arrays.npz"
    flat: dict[str, np.ndarray] = {}
    mapped: dict[str, np.ndarray] = _mmap_npz_members(npz) if mmap else {}
    with np.load(npz, allow_pickle=False) as z:
        for slot, key in manifest["keys"].items():
            arr = mapped.get(slot)
            if arr is None:
                arr = z[slot]
            true_dtype = manifest["dtypes"][key]
            if true_dtype == "bfloat16" and arr.dtype == np.uint16:
                import ml_dtypes

                arr = arr.view(ml_dtypes.bfloat16)
            flat[key] = arr

    tree = _unflatten(flat, manifest["structure"])
    if shardings is not None:
        tree = _apply_shardings(tree, shardings)
    return tree, manifest.get("meta", {})


def _mmap_npz_members(npz_path: Path) -> dict[str, np.ndarray]:
    """Read-only ``np.memmap`` views over the uncompressed ``.npy`` members
    of an npz: {slot: array}. Each member's payload offset comes from its
    LOCAL zip header (the central directory's extra field can differ), and
    its shape/dtype from the standard npy header. Members that are
    compressed, fortran-ordered, or otherwise surprising are simply
    omitted — the caller copy-loads those."""
    out: dict[str, np.ndarray] = {}
    try:
        with zipfile.ZipFile(npz_path) as zf, open(npz_path, "rb") as f:
            for info in zf.infolist():
                if info.compress_type != zipfile.ZIP_STORED:
                    continue
                f.seek(info.header_offset)
                hdr = f.read(30)
                if len(hdr) < 30 or hdr[:4] != b"PK\x03\x04":
                    continue
                name_len = int.from_bytes(hdr[26:28], "little")
                extra_len = int.from_bytes(hdr[28:30], "little")
                payload_off = info.header_offset + 30 + name_len + extra_len
                f.seek(payload_off)
                try:
                    # _read_array_header is numpy-private: a release that
                    # renames it must degrade to the copy-load path
                    # (AttributeError), not fail every worker's spawn
                    version = np.lib.format.read_magic(f)
                    shape, fortran, dtype = \
                        np.lib.format._read_array_header(f, version)
                except (ValueError, OSError, AttributeError):
                    continue
                if fortran or dtype.hasobject:
                    continue
                data_off = f.tell()
                slot = info.filename[:-4] if info.filename.endswith(".npy") \
                    else info.filename
                out[slot] = np.memmap(npz_path, dtype=dtype, mode="r",
                                      offset=data_off, shape=shape)
    except (OSError, zipfile.BadZipFile):
        return {}
    return out


def _apply_shardings(tree: Any, shardings: Any) -> Any:
    import jax

    def put(leaf, sh):
        return jax.device_put(leaf, sh) if sh is not None else leaf

    return jax.tree.map(put, tree, shardings)


# --------------------------------------------------------------- manager


class CheckpointManager:
    """Versioned checkpoints: ``base/step_00000042/{name}/…`` with retention.

    One step saves several named trees (e.g. ``params``, ``opt_state``,
    ``index``) that restore together — the serving equivalent of a training
    step checkpoint. Partial step dirs are invisible (atomic rename of the
    whole step directory), and ``restore`` falls back through older steps if
    the newest is unreadable.
    """

    def __init__(self, base_dir: str | Path, keep: int = 3) -> None:
        self.base = Path(base_dir)
        self.keep = keep
        self.base.mkdir(parents=True, exist_ok=True)
        sweep_stale_tmp(self.base)

    @staticmethod
    def _step_name(step: int) -> str:
        return f"step_{step:08d}"

    def all_steps(self) -> list[int]:
        steps = []
        for p in self.base.glob("step_*"):
            if p.is_dir() and (p / ".complete").exists():
                try:
                    steps.append(int(p.name.split("_")[1]))
                except (IndexError, ValueError):
                    continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, trees: Mapping[str, Any], meta: Optional[dict] = None) -> Path:
        tmp = Path(tempfile.mkdtemp(prefix=".tmp-step-", dir=str(self.base)))
        final = self.base / self._step_name(step)
        try:
            for name, tree in trees.items():
                save_pytree(tmp / name, tree, meta=meta)
            (tmp / ".complete").write_text(str(time.time()))  # wall-clock: persisted completion stamp
            _replace_dir(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._gc()
        return final

    def restore(
        self,
        step: Optional[int] = None,
        shardings: Optional[Mapping[str, Any]] = None,
    ) -> tuple[int, dict[str, Any], dict[str, dict]]:
        """→ (step, {name: tree}, {name: meta}). Newest step when ``step``
        is None; corrupt newest steps are skipped with older ones tried in
        order. Metas are per-tree — a step assembled from separate
        ``save_pytree`` calls can carry a different meta per tree."""
        candidates = [step] if step is not None else list(reversed(self.all_steps()))
        last_err: Optional[Exception] = None
        for s in candidates:
            d = self.base / self._step_name(s)
            try:
                trees: dict[str, Any] = {}
                metas: dict[str, dict] = {}
                names = sorted(
                    p.name for p in d.iterdir() if p.is_dir() and not p.name.startswith(".")
                )
                if not names:
                    raise CheckpointError(f"empty checkpoint step {s}")
                for name in names:
                    sh = (shardings or {}).get(name)
                    trees[name], metas[name] = load_pytree(d / name, shardings=sh)
                return s, trees, metas
            except (CheckpointError, OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
                # BadZipFile: power loss can truncate arrays.npz (save does
                # not fsync); fall back to the previous step
                last_err = e
                continue
        raise CheckpointError(f"no restorable checkpoint under {self.base}: {last_err}")

    def _gc(self) -> None:
        steps = self.all_steps()
        for s in steps[: max(len(steps) - self.keep, 0)]:
            shutil.rmtree(self.base / self._step_name(s), ignore_errors=True)
