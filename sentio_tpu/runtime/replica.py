"""Multi-replica serving tier: radix-affinity routing + weighted fair queueing.

One ``PagedGenerationService`` is the hard throughput ceiling no matter how
fast a tick is — one pump thread, one engine, one page pool. This module
scales the serving path out data-parallel, following the continuous-batching
replica model of Orca (Yu et al., OSDI '22) and the prefix-affinity
scheduling idea of SGLang's RadixAttention (Zheng et al., 2024):

* a :class:`ReplicaSet` owns N fully independent engine+service replicas
  (private page pool, radix tree, and pump thread each — replicas share
  only the immutable weights and tokenizer). On real hardware each replica
  maps onto a slice of the mesh's ``dp`` axis
  (:func:`sentio_tpu.parallel.mesh.split_mesh_dp`); in-process CPU replicas
  are the N=1-compatible first rung.
* **two-stage routing** — (1) *radix-prefix affinity*: the router tokenizes
  the prompt head and asks every replica's radix cache, via the read-only
  ``peek_prefix`` probe, for its longest cached prefix; the best hit wins
  unless that replica's backlog exceeds a stickiness bound, because a
  session's follow-up landing on the replica that already holds its KV
  turns a cross-replica cache miss into a suffix-only prefill. (2)
  *least-loaded* by projected wait (each replica's TTFT-EMA scaled by its
  backlog — the same estimate admission control uses against deadlines).
* **weighted fair queueing** — in front of the replicas, the single global
  FIFO admission bound generalizes to per-tenant fairness
  (:class:`TenantFairQueue`): requests carry a tenant key (auth principal
  or ``X-Tenant`` header; default one shared tenant), each tenant gets a
  weight-proportional quota of the set's total queue capacity (with a
  reserved headroom so a flooding tenant can never consume the last slots
  a new tenant's first request needs), optional token-weighted deficit
  counters rate-limit contended tenants DRR-style, and a ``batch``
  priority tier sheds earlier than ``interactive`` under load. Overload
  answers stay typed ``ServiceOverloaded`` → 429/503 + Retry-After, now
  per tenant.

The set exposes the same ``generate / generate_stream / check_admission /
warmup / drain / stats / close`` surface as one service, so the serving
container, graph nodes, and eval swap only the constructor. N=1 with the
default single tenant degenerates to (almost) today's behavior — the one
deliberate difference is the WFQ headroom, which sheds a lone flooding
tenant slightly before the absolute queue bound so fairness is available
the instant a second tenant shows up.

Threading: routing probes (``peek_prefix``, ``backlog``, ``projected_wait``)
are advisory reads against live replicas; all ReplicaSet/TenantFairQueue
mutable state sits behind one mutex held only for quick bookkeeping — never
across a generate call or a device tick.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Iterator, Optional, Sequence

from sentio_tpu.analysis.sanitizer import assert_held, make_lock
from sentio_tpu.infra.exceptions import ServiceOverloaded
from sentio_tpu.infra.metrics import get_metrics
from sentio_tpu.runtime.service import PagedGenerationService

logger = logging.getLogger(__name__)

__all__ = [
    "ReplicaSet",
    "TenantFairQueue",
    "DEFAULT_TENANT",
    "PRIORITY_INTERACTIVE",
    "PRIORITY_BATCH",
]

DEFAULT_TENANT = "shared"
PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BATCH = "batch"


@dataclass
class _TenantState:
    """Book-keeping for one tenant. All fields guarded by the queue's
    mutex (the dataclass itself never escapes the lock)."""

    weight: float = 1.0
    pending: int = 0          # requests admitted and not yet released
    deficit: float = 0.0      # DRR token credit (refill-rate mode only)
    last_refill: float = 0.0  # perf_counter of the last deficit refill
    admitted: int = 0
    shed: int = 0
    tokens: int = 0           # actual tokens consumed (prompt + generated)


class TenantFairQueue:
    """Weighted fair admission across tenants over a shared queue capacity.

    Three independent rules, every rejection typed and counted per tenant:

    * **quota** — tenant ``t`` may hold at most
      ``max(min_quota, (capacity - headroom) * w_t / Σ w_active)`` pending
      requests, where the active set is every tenant with pending work plus
      the requester. With one active tenant the quota is the whole capacity
      minus the reserved headroom — the slack that guarantees a second
      tenant's FIRST request always finds room (without it, a flood fills
      every replica inbox and fairness can never begin).
    * **deficit** (off by default, ``refill_tokens_per_s > 0`` arms it) —
      token-weighted deficit-round-robin: each tenant's credit refills at
      ``rate x weight`` tokens/s (capped at ``burst x weight``), admission
      under contention (other tenants have pending work) requires a
      non-negative credit, and each admission debits its token cost
      (corrected to actual consumption at release). A lone tenant is never
      deficit-limited — idle capacity is not rationed.
    * **priority tiers** — ``batch`` requests shed once total pending
      crosses ``batch_shed_fraction x capacity``; ``interactive`` requests
      may use the full capacity. Two tiers, shed-earlier semantics: batch
      traffic yields headroom to interactive traffic under load.
    """

    # label-cardinality bound for /metrics: beyond this many distinct
    # tenant keys, new ones share one overflow bucket (a client minting
    # random tenant headers must not grow the metric space unboundedly)
    MAX_TRACKED = 256
    OVERFLOW_TENANT = "overflow"

    def __init__(
        self,
        capacity: int,
        weights: Optional[dict[str, float]] = None,
        default_weight: float = 1.0,
        refill_tokens_per_s: float = 0.0,
        burst_tokens: int = 8192,
        batch_shed_fraction: float = 0.8,
        headroom: Optional[int] = None,
        min_quota: int = 1,
    ) -> None:
        self.capacity = max(int(capacity), 1)
        self.default_weight = max(float(default_weight), 1e-3)
        self.refill_tokens_per_s = max(float(refill_tokens_per_s), 0.0)
        self.burst_tokens = max(int(burst_tokens), 1)
        self.batch_shed_fraction = min(max(float(batch_shed_fraction), 0.0), 1.0)
        self.min_quota = max(int(min_quota), 1)
        # reserved slack no single tenant's quota may consume: the landing
        # room for a tenant the system has not seen yet
        self.headroom = (
            int(headroom) if headroom is not None
            else max(1, self.capacity // 8)
        )
        self.headroom = min(self.headroom, self.capacity - 1)
        self._weights = dict(weights or {})
        self._mutex = make_lock("TenantFairQueue._mutex")
        self._tenants: dict[str, _TenantState] = {}  # guarded-by: _mutex

    # ------------------------------------------------------------- internal

    def _state_locked(self, tenant: str) -> tuple[str, _TenantState]:  # lock-held: _mutex
        assert_held(self._mutex)
        if tenant not in self._tenants and len(self._tenants) >= self.MAX_TRACKED:
            tenant = self.OVERFLOW_TENANT
        state = self._tenants.get(tenant)
        if state is None:
            state = _TenantState(
                weight=max(self._weights.get(tenant, self.default_weight), 1e-3),
            )
            if self.refill_tokens_per_s > 0:
                state.deficit = self.burst_tokens * state.weight
                state.last_refill = time.perf_counter()
            self._tenants[tenant] = state
        return tenant, state

    def _refill_locked(self, state: _TenantState, now: float) -> None:  # lock-held: _mutex
        assert_held(self._mutex)
        if self.refill_tokens_per_s <= 0:
            return
        dt = max(now - state.last_refill, 0.0)
        state.last_refill = now
        state.deficit = min(
            state.deficit + self.refill_tokens_per_s * state.weight * dt,
            self.burst_tokens * state.weight,
        )

    def _quota_locked(self, tenant: str, state: _TenantState) -> int:  # lock-held: _mutex
        assert_held(self._mutex)
        active_weight = state.weight if state.pending == 0 else 0.0
        for other in self._tenants.values():
            if other.pending > 0:
                active_weight += other.weight
        share = (self.capacity - self.headroom) * state.weight \
            / max(active_weight, state.weight)
        return max(self.min_quota, int(share))

    def _shed_locked(self, tenant: str, state: _TenantState, reason: str,
                     message: str, status: int,
                     retry_after_s: float) -> None:  # lock-held: _mutex
        assert_held(self._mutex)
        state.shed += 1
        metrics = get_metrics()
        metrics.record_shed(reason)
        metrics.record_tenant_shed(tenant, reason)
        raise ServiceOverloaded(
            message, status=status, retry_after_s=retry_after_s,
            details={"tenant": tenant, "shed_reason": reason},
        )

    # --------------------------------------------------------------- public

    def admit(self, tenant: str, cost_tokens: int,
              priority: str = PRIORITY_INTERACTIVE,
              reserve: bool = True) -> str:
        """Admit (or, with ``reserve=False``, merely test) one request for
        ``tenant`` with an estimated token cost. Raises a typed
        :class:`ServiceOverloaded` carrying the tenant and shed reason;
        returns the (possibly overflow-bucketed) tenant key actually
        charged, which MUST be passed back to :meth:`release`."""
        now = time.perf_counter()
        with self._mutex:
            tenant, state = self._state_locked(tenant)
            self._refill_locked(state, now)
            total_pending = sum(s.pending for s in self._tenants.values())
            quota = self._quota_locked(tenant, state)
            if state.pending >= quota:
                self._shed_locked(
                    tenant, state, "tenant_quota",
                    f"tenant {tenant!r} is at its fair-share quota "
                    f"({state.pending}/{quota} of {self.capacity} total)",
                    status=429, retry_after_s=1.0,
                )
            if priority == PRIORITY_BATCH and total_pending + 1 > \
                    self.batch_shed_fraction * self.capacity:
                self._shed_locked(
                    tenant, state, "priority_batch",
                    f"batch-tier request shed at {total_pending}/"
                    f"{self.capacity} pending (batch yields to interactive)",
                    status=503, retry_after_s=2.0,
                )
            contended = total_pending - state.pending > 0
            if self.refill_tokens_per_s > 0 and contended and state.deficit < 0:
                wait = -state.deficit / (
                    self.refill_tokens_per_s * state.weight
                )
                self._shed_locked(
                    tenant, state, "tenant_deficit",
                    f"tenant {tenant!r} exhausted its token deficit "
                    f"({state.deficit:.0f}); refilling at "
                    f"{self.refill_tokens_per_s * state.weight:.0f} tok/s",
                    status=429, retry_after_s=max(wait, 0.5),
                )
            if reserve:
                state.pending += 1
                state.admitted += 1
                if self.refill_tokens_per_s > 0:
                    state.deficit -= max(int(cost_tokens), 0)
                get_metrics().record_tenant_admitted(tenant)
            return tenant

    def release(self, tenant: str, cost_tokens: int,
                actual_tokens: Optional[int] = None) -> None:
        """Return one admission. ``actual_tokens`` (when known) corrects the
        estimated debit, so deficits track real consumption — a request that
        stopped early gets its unspent credit back."""
        with self._mutex:
            state = self._tenants.get(tenant)
            if state is None:
                return
            state.pending = max(state.pending - 1, 0)
            if actual_tokens is not None:
                state.tokens += int(actual_tokens)
                if self.refill_tokens_per_s > 0:
                    state.deficit += max(int(cost_tokens), 0) - max(
                        int(actual_tokens), 0
                    )

    def stats(self) -> dict:
        with self._mutex:
            return {
                "capacity": self.capacity,
                "headroom": self.headroom,
                "refill_tokens_per_s": self.refill_tokens_per_s,
                "per_tenant": {
                    name: {
                        "weight": state.weight,
                        "pending": state.pending,
                        "admitted": state.admitted,
                        "shed": state.shed,
                        "tokens": state.tokens,
                        **({"deficit": round(state.deficit, 1)}
                           if self.refill_tokens_per_s > 0 else {}),
                    }
                    for name, state in self._tenants.items()
                },
            }


class ReplicaSet:
    """Front-end over N independent paged-decode replicas: WFQ admission →
    radix-affinity / least-loaded routing → delegate to the chosen
    replica's :class:`PagedGenerationService`. Same call surface as one
    service; N=1 degenerates to a thin pass-through."""

    # duck-typing flag callers use to decide whether tenant/priority kwargs
    # are understood (a bare PagedGenerationService or a test fake is not)
    supports_tenants = True

    def __init__(
        self,
        services: Sequence[PagedGenerationService],
        tenant_weights: Optional[dict[str, float]] = None,
        tenant_default_weight: float = 1.0,
        tenant_refill_tokens_per_s: float = 0.0,
        tenant_burst_tokens: int = 8192,
        tenant_headroom: Optional[int] = None,
        batch_shed_fraction: float = 0.8,
        affinity_stickiness: float = 4.0,
        route_prefix_tokens: int = 512,
    ) -> None:
        services = list(services)
        if not services:
            raise ValueError("ReplicaSet needs at least one replica")
        self._check_isolation(services)
        self._services = services
        for i, svc in enumerate(services):
            svc.replica_id = i
            guard = getattr(svc.engine, "_san", None)
            if guard is not None:
                # per-replica pump ownership: sanitizer errors must name
                # WHICH replica's engine a stray thread touched
                guard.name = f"ContinuousBatchingEngine[r{i}]"
        self.tokenizer = services[0].engine.tokenizer
        # route on at most this many prompt-head tokens: prefixes longer
        # than this are indistinguishable to the router but not to the
        # replica's radix cache, which still reuses the full match
        self.route_prefix_tokens = max(int(route_prefix_tokens),
                                       services[0].engine.page_size)
        # a prefix-hit replica keeps the request only while its backlog is
        # within stickiness x its slot count; past that, cache reuse costs
        # more queueing delay than the suffix prefill it saves
        self.affinity_stickiness = max(float(affinity_stickiness), 0.0)
        self.tenants = TenantFairQueue(
            capacity=sum(svc.max_queue for svc in services),
            weights=tenant_weights,
            default_weight=tenant_default_weight,
            refill_tokens_per_s=tenant_refill_tokens_per_s,
            burst_tokens=tenant_burst_tokens,
            batch_shed_fraction=batch_shed_fraction,
            headroom=tenant_headroom,
        )
        self._mutex = make_lock("ReplicaSet._mutex")
        # routing outcome counters (telemetry only)
        self._routed_affinity = 0  # guarded-by: _mutex
        self._routed_load = 0  # guarded-by: _mutex
        self._affinity_overflow = 0  # guarded-by: _mutex

    @staticmethod
    def _check_isolation(services: Sequence[PagedGenerationService]) -> None:
        """Replicas must not share mutable decode state: a shared engine,
        allocator, pool, or radix tree would be mutated by two pump threads
        at once (immutable weights/tokenizer sharing is the point)."""
        seen: dict[int, tuple[int, str]] = {}
        for i, svc in enumerate(services):
            eng = svc.engine
            parts = {
                "service": svc,
                "engine": eng,
                "allocator": getattr(eng, "allocator", None),
                "pool": getattr(eng, "pool", None),
                "radix": getattr(eng, "_radix", None),
            }
            for what, obj in parts.items():
                if obj is None:
                    continue
                prior = seen.get(id(obj))
                if prior is not None:
                    raise ValueError(
                        f"replica {i} shares its {what} with replica "
                        f"{prior[0]}'s {prior[1]} — replicas must own "
                        f"private decode state"
                    )
                seen[id(obj)] = (i, what)

    # -------------------------------------------------------------- routing

    @property
    def replicas(self) -> int:
        return len(self._services)

    def _route_tokens(self, prompt: str) -> list[int]:
        # chars bound the token count for every tokenizer in the tree (byte
        # tokenizer is 1:1; BPE merges only shrink), so slicing chars first
        # keeps the encode cost flat for very long prompts
        head = prompt[: self.route_prefix_tokens * 4]
        try:
            toks = self.tokenizer.encode(head, add_bos=True)
        except Exception:  # noqa: BLE001 — routing must never fail a request
            return []
        return list(toks[: self.route_prefix_tokens])

    def _route(self, toks: Sequence[int], count: bool = True) -> tuple[int, int]:
        """→ (replica index, predicted prefix-hit tokens). Stage 1: best
        ``peek_prefix`` hit, sticky while that replica's backlog stays under
        ``stickiness x max_slots``. Stage 2: least projected wait.
        ``count=False`` for probes (check_admission): the SSE pre-check
        routes the same request a second time and must not double-count the
        routing-outcome telemetry."""
        best_i, best_hit = -1, 0
        if len(self._services) > 1 and toks:
            for i, svc in enumerate(self._services):
                hit = svc.engine.peek_prefix(toks)
                if hit > best_hit:
                    best_i, best_hit = i, hit
        if best_hit > 0:
            svc = self._services[best_i]
            bound = self.affinity_stickiness * max(svc.engine.max_slots, 1)
            if svc.backlog() <= bound:
                if count:
                    with self._mutex:
                        self._routed_affinity += 1
                return best_i, best_hit
            if count:
                with self._mutex:
                    self._affinity_overflow += 1

        def load_key(pair):
            i, svc = pair
            return (svc.projected_wait() or 0.0, svc.backlog(), i)

        idx = min(enumerate(self._services), key=load_key)[0]
        if count:
            with self._mutex:
                self._routed_load += 1
        return idx, 0

    # ------------------------------------------------------------------ api

    def generate(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: str = PRIORITY_INTERACTIVE,
    ):
        toks = self._route_tokens(prompt)
        cost = len(toks) + max_new_tokens
        charged = self.tenants.admit(tenant or DEFAULT_TENANT, cost,
                                     priority=priority)
        try:
            idx, _hit = self._route(toks)
            result = self._services[idx].generate(
                prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, timeout_s=timeout_s,
                request_id=request_id, deadline_s=deadline_s,
                deadline_ts=deadline_ts, top_k=top_k,
            )
        except BaseException:
            # failed before (shed) or during decode: refund the estimated
            # debit — charging full cost for work that never ran would let
            # replica-level sheds drain an innocent tenant's deficit
            self.tenants.release(charged, cost, actual_tokens=0)
            raise
        self.tenants.release(
            charged, cost,
            actual_tokens=result.prompt_tokens + len(result.tokens),
        )
        return result

    def generate_stream(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: str = PRIORITY_INTERACTIVE,
    ) -> Iterator[str]:
        toks = self._route_tokens(prompt)
        idx, _hit = self._route(toks)
        # the replica's own generate_stream runs its CALL-time validation
        # (top_k vs paged speculation) here, before any SSE 200 commits;
        # its admission — and our tenant reservation — stay deferred to the
        # first next(), the long-standing stream contract
        inner = self._services[idx].generate_stream(
            prompt, max_new_tokens=max_new_tokens, temperature=temperature,
            timeout_s=timeout_s, request_id=request_id,
            deadline_s=deadline_s, deadline_ts=deadline_ts, top_k=top_k,
        )
        return self._stream_impl(inner, tenant or DEFAULT_TENANT,
                                 len(toks) + max_new_tokens, priority)

    def _stream_impl(self, inner: Iterator[str], tenant: str, cost: int,
                     priority: str) -> Iterator[str]:
        charged = self.tenants.admit(tenant, cost, priority=priority)
        try:
            yield from inner
        finally:
            # streams release at close/exhaust/error with the estimate —
            # the exact split is not worth holding the reservation open for
            self.tenants.release(charged, cost)

    def check_admission(
        self,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: str = PRIORITY_INTERACTIVE,
        prompt: Optional[str] = None,
    ) -> None:
        """Raise what a submit right now would raise, WITHOUT reserving:
        WFQ tenant check first (peek mode), then the target replica's own
        admission check. With a ``prompt`` the probe routes exactly as the
        submit will; without one it checks the least-loaded replica (if
        that one sheds, every routing choice would)."""
        self.tenants.admit(tenant or DEFAULT_TENANT, 0, priority=priority,
                           reserve=False)
        toks = self._route_tokens(prompt) if prompt else []
        idx, _hit = self._route(toks, count=False)
        self._services[idx].check_admission(deadline_ts)

    # ------------------------------------------------------------ lifecycle

    def warmup(self, max_new_tokens: int = 4) -> dict:
        """Warm EVERY replica CONCURRENTLY (each compiles its own jit
        variants over its own pool/mesh slice, so serial warmup would
        multiply startup by N) before the compile fence arms — serve
        startup arms the fence only after this returns, i.e. after all
        replicas report. A failed replica warmup re-raises: arming the
        fence over an unwarmed replica would fail its first real request."""
        results: list = [None] * len(self._services)
        errors: list = []

        def _warm(i: int, svc: PagedGenerationService) -> None:
            try:
                results[i] = svc.warmup(max_new_tokens=max_new_tokens)
            except Exception as exc:  # noqa: BLE001 — re-raised below
                errors.append(exc)

        threads = [
            threading.Thread(target=_warm, args=(i, svc),
                             name=f"replica-warmup-{i}", daemon=True)
            for i, svc in enumerate(self._services)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return {
            "prompts": sum(r.get("prompts", 0) for r in results),
            "xla_compiles": sum(r.get("xla_compiles", 0) for r in results),
            "replicas": len(self._services),
        }

    def drain(self, deadline_s: float = 30.0) -> dict:
        """Drain all replicas CONCURRENTLY: each gets the same wall-clock
        window (draining serially would give replica k only the deadline
        minus its predecessors' spend). Aggregates drained/abandoned."""
        results: list[Optional[dict]] = [None] * len(self._services)

        def _drain(i: int, svc: PagedGenerationService) -> None:
            try:
                results[i] = svc.drain(deadline_s)
            except Exception:  # noqa: BLE001 — drain is best-effort
                logger.warning("replica %d drain failed", i, exc_info=True)

        threads = [
            threading.Thread(target=_drain, args=(i, svc),
                             name=f"replica-drain-{i}", daemon=True)
            for i, svc in enumerate(self._services)
        ]
        for t in threads:
            t.start()
        for t in threads:
            # each replica's drain bounds itself by deadline_s; the grace
            # covers close()'s pump join, not extra drain time
            t.join(timeout=deadline_s + 15.0)
        per = []
        for i, (svc, res) in enumerate(zip(self._services, results)):
            if res is None:
                res = {"drained": False, "abandoned": svc.backlog()}
            per.append({"replica": i, **res})
        return {
            "drained": all(r["drained"] for r in per),
            "abandoned": sum(r.get("abandoned", 0) for r in per),
            "replicas": per,
        }

    def close(self) -> None:
        for svc in self._services:
            try:
                svc.close()
            except Exception:  # noqa: BLE001 — close every replica regardless
                logger.warning("replica %d close failed", svc.replica_id,
                               exc_info=True)

    # ---------------------------------------------------------------- stats

    _SUM_KEYS = (
        "active_slots", "max_slots", "queued", "free_pages", "total_pages",
        "pool_hbm_bytes", "head_skips", "ttft_count", "prefill_tokens",
        "decode_tokens", "prefix_hits", "prefix_misses", "prefix_hit_tokens",
        "prefix_miss_tokens", "prefix_cache_pages", "prefix_cache_nodes",
        "queued_inbox", "ticks", "completed", "max_queue", "shed", "expired",
        "cancelled", "requeued", "tick_failures", "pump_leaked",
        "spec_verifies", "spec_emitted",
    )
    _MAX_KEYS = ("max_active_slots", "draining")

    def stats(self) -> dict:
        """Aggregate + per-replica stats. Counters SUM over replicas exactly
        once each (every per-replica total appears in exactly one replica's
        stats, so the sum cannot double-count — the leaked-pump audit relies
        on this); high-water marks take the max; percentile-ish telemetry
        (ttft_p50/p95, avg occupancy) is weighted by each replica's sample
        count and labeled by construction as an approximation."""
        per = []
        agg: dict = {}
        for svc in self._services:
            s = svc.stats()
            per.append(s)
            for key in self._SUM_KEYS:
                if key in s:
                    agg[key] = agg.get(key, 0) + s[key]
            for key in self._MAX_KEYS:
                if key in s:
                    agg[key] = max(agg.get(key, 0), s[key])
        ticks = agg.get("ticks", 0)
        if ticks:
            agg["avg_active_slots"] = round(
                sum(s.get("avg_active_slots", 0.0) * s.get("ticks", 0)
                    for s in per) / ticks, 3,
            )
        else:
            agg["avg_active_slots"] = 0.0
        hit = agg.get("prefix_hit_tokens", 0)
        miss = agg.get("prefix_miss_tokens", 0)
        if hit + miss:
            agg["prefix_hit_token_ratio"] = round(hit / (hit + miss), 4)
        ttft_n = sum(s.get("ttft_count", 0) for s in per
                     if "ttft_p50_ms" in s)
        if ttft_n:
            for key in ("ttft_p50_ms", "ttft_p95_ms"):
                agg[key] = round(
                    sum(s[key] * s.get("ttft_count", 0) for s in per
                        if key in s) / ttft_n, 2,
                )
        spec_v = agg.get("spec_verifies", 0)
        if spec_v:
            agg["spec_tokens_per_verify"] = round(
                agg.get("spec_emitted", 0) / spec_v, 2)
        first = per[0]
        agg["page_size"] = first.get("page_size")
        agg["kv_quant"] = first.get("kv_quant")
        agg["n_replicas"] = len(per)
        agg["replicas"] = per
        with self._mutex:
            agg["routing"] = {
                "affinity": self._routed_affinity,
                "least_loaded": self._routed_load,
                "affinity_overflow": self._affinity_overflow,
            }
        agg["tenants"] = self.tenants.stats()
        return agg
