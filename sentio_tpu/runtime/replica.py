"""Multi-replica serving tier: radix-affinity routing + weighted fair queueing.

One ``PagedGenerationService`` is the hard throughput ceiling no matter how
fast a tick is — one pump thread, one engine, one page pool. This module
scales the serving path out data-parallel, following the continuous-batching
replica model of Orca (Yu et al., OSDI '22) and the prefix-affinity
scheduling idea of SGLang's RadixAttention (Zheng et al., 2024):

* a :class:`ReplicaSet` owns N fully independent engine+service replicas
  (private page pool, radix tree, and pump thread each — replicas share
  only the immutable weights and tokenizer). On real hardware each replica
  maps onto a slice of the mesh's ``dp`` axis
  (:func:`sentio_tpu.parallel.mesh.split_mesh_dp`); in-process CPU replicas
  are the N=1-compatible first rung.
* **two-stage routing** — (1) *radix-prefix affinity*: the router tokenizes
  the prompt head and asks every replica's radix cache, via the read-only
  ``peek_prefix`` probe, for its longest cached prefix; the best hit wins
  unless that replica's backlog exceeds a stickiness bound, because a
  session's follow-up landing on the replica that already holds its KV
  turns a cross-replica cache miss into a suffix-only prefill. (2)
  *least-loaded* by projected wait (each replica's TTFT-EMA scaled by its
  backlog — the same estimate admission control uses against deadlines).
* **weighted fair queueing** — in front of the replicas, the single global
  FIFO admission bound generalizes to per-tenant fairness
  (:class:`TenantFairQueue`): requests carry a tenant key (auth principal
  or ``X-Tenant`` header; default one shared tenant), each tenant gets a
  weight-proportional quota of the set's total queue capacity (with a
  reserved headroom so a flooding tenant can never consume the last slots
  a new tenant's first request needs), optional token-weighted deficit
  counters rate-limit contended tenants DRR-style, and a ``batch``
  priority tier sheds earlier than ``interactive`` under load. Overload
  answers stay typed ``ServiceOverloaded`` → 429/503 + Retry-After, now
  per tenant.

The set exposes the same ``generate / generate_stream / check_admission /
warmup / drain / stats / close`` surface as one service, so the serving
container, graph nodes, and eval swap only the constructor. N=1 with the
default single tenant degenerates to (almost) today's behavior — the one
deliberate difference is the WFQ headroom, which sheds a lone flooding
tenant slightly before the absolute queue bound so fairness is available
the instant a second tenant shows up.

**Replica failure domains** — each replica is an independent failure
domain with a supervised health state machine::

    HEALTHY → DEGRADED → QUARANTINED → REBUILDING → HEALTHY

* the router never selects a QUARANTINED/REBUILDING replica, and DEGRADED
  replicas take traffic only when no HEALTHY replica has queue headroom;
* a per-replica breaker trips to QUARANTINED on the service's latched
  ``broken`` flag (failed tick whose ``engine.reset()`` also failed), on a
  burst of tick failures inside a sliding window, or on a caller-observed
  error rate over the same window;
* a supervisor thread rebuilds quarantined replicas **in place**: fresh
  engine + pool + radix + pump from the shared weights
  (``engine.spawn_fresh()``, the same constructor path the serving
  container uses), re-warmed — under an armed compile fence the NEW
  engine's cold compiles are instance-scoped exempt while steady-state
  recompiles elsewhere still trip — and only then swapped back into
  rotation;
* callers **fail over**: a generate (or a stream that has not yet
  delivered tokens) that dies with a replica-infrastructure failure is
  re-admitted (WFQ released, then re-charged — failover never
  double-counts quota) and re-routed to a surviving replica, bounded by a
  per-request failover budget.

**Resumable streams** — a stream that dies WITH delivered tokens cannot
restart (replay would duplicate output), so it is **resumed by
replay-prefill**: the router tracks the exact delivered token ids per
piece (:class:`~sentio_tpu.runtime.service.StreamProgress`) plus the
call-time sampling knobs, and on a mid-stream replica failure re-admits
on a survivor with ``prior_tokens`` = the delivered prefix — the radix
cache turns the replay into a prefix hit when the prompt pages survive
there, and a bounded replay prefill otherwise. Decode continues from the
splice point and the router yields only post-splice text (re-decoded
over the full token sequence, so UTF-8 withholding at the splice cannot
duplicate or drop characters). Greedy resumes are token-exact vs a
no-fault run; sampled resumes carry the seed and knobs so the
continuation is distribution-correct. ``stream_resume_budget``
(default = failover budget; 0 disables) caps attempts per stream;
opted-out or budget-exhausted streams keep the typed mid-stream error.
Each resume emits a ``stream_resumed`` flight event and counts into
``sentio_tpu_stream_resumes_total{outcome}`` and ``stats()``.

**Stall tolerance** — the breaker only sees faults that *raise*; a tick
that hangs inside a wedged device dispatch raises nothing. The supervisor
pass doubles as a **watchdog**: each service stamps a pump heartbeat per
loop iteration, and a heartbeat stale past the service's
``tick_stall_budget_s`` *with pending work* quarantines the replica with
no exception observed. Since a thread blocked in XLA cannot be killed,
recovery **abandons** the wedged engine+service (admitted tickets fail
typed and fail over; the leaked pump is accounted and the count carried
across the incarnation swap) and rebuilds the slot via the normal
``spawn_fresh`` path. At *any* quarantine — stall or breaker — the dead
replica's queued-but-never-dispatched **inbox tickets are handed off**
directly to survivors (WFQ release/re-charge via
:meth:`TenantFairQueue.recharge`); the blocked caller wakes with the
survivor's result without spending failover budget. Rebuilds run on a
bounded **worker pool** so detection cadence never waits behind a long
(or wedged) rebuild.

Health transitions emit flight-recorder events and the
``sentio_tpu_replica_health{replica,state}`` gauge (plus
``sentio_tpu_pump_heartbeat_age_seconds`` per watchdog pass);
``health_summary()`` feeds ``/health`` so an N-replica pod reports
``degraded`` (keep routing) rather than ``unhealthy`` (restart me) while
at least one replica serves.

Threading: routing probes (``peek_prefix``, ``backlog``, ``projected_wait``)
are advisory reads against live replicas; all ReplicaSet/TenantFairQueue
mutable state sits behind one mutex held only for quick bookkeeping — never
across a generate call, a device tick, or a rebuild.

**Process-mode replicas** — everything above is duck-typed against the
service surface, so a :class:`~sentio_tpu.runtime.worker.ProcessReplica`
(one spawned worker process per replica, ``REPLICA_MODE=process``) slots
into the set unchanged: load/liveness probes (``backlog``,
``projected_wait``, ``broken``) read its pushed status frames, the
prefix-affinity probe is a short-timeout RPC that skips wedged workers
(a stale status frame reads as a cold cache), the watchdog reads the
worker's own pump heartbeat, quarantine abandons
via RPC, and the rebuild path respawns the process (``respawn()``)
instead of swapping an in-process service. Under a supervising set the
process replicas arm **router-side ticket shadowing**
(``enable_shadow_handoff``): a dead worker's never-answered tickets are
extracted from the router-side shadow queue and re-admitted on survivors
through the same ``_handoff_inbox`` path as thread mode — handoff parity.
See runtime/worker.py for the remaining deliberate semantic deltas
(mid-decode generates may re-execute on handoff; worker compiles outside
the router's fence).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterator, Optional, Sequence

from sentio_tpu.analysis.sanitizer import assert_held, guard_locksets, make_lock
from sentio_tpu.infra import faults
from sentio_tpu.infra.exceptions import (
    ReplicaUnavailable,
    SentioError,
    ServiceOverloaded,
)
from sentio_tpu.infra.metrics import get_metrics
from sentio_tpu.infra.phases import duty_fractions, sum_phase_totals
from sentio_tpu.runtime.service import (
    PagedGenerationService,
    StreamProgress,
    finish_ticket_error,
)

logger = logging.getLogger(__name__)

__all__ = [
    "ReplicaSet",
    "TenantFairQueue",
    "WorkerRegistry",
    "DEFAULT_TENANT",
    "PRIORITY_INTERACTIVE",
    "PRIORITY_BATCH",
    "HEALTH_HEALTHY",
    "HEALTH_DEGRADED",
    "HEALTH_QUARANTINED",
    "HEALTH_REBUILDING",
    "HEALTH_RETIRING",
    "HEALTH_RETIRED",
    "HEALTH_STATES",
]

DEFAULT_TENANT = "shared"
PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BATCH = "batch"

# replica health state machine (see module docstring); values are the
# /metrics label and the flight-recorder event vocabulary
HEALTH_HEALTHY = "HEALTHY"
HEALTH_DEGRADED = "DEGRADED"
HEALTH_QUARANTINED = "QUARANTINED"
HEALTH_REBUILDING = "REBUILDING"
# elastic-fleet states: RETIRING drains a replica that is leaving the set
# voluntarily (scale-in / deregister) — the router never selects it but its
# in-flight work finishes or resumes on survivors; RETIRED is the terminal
# parked state of a slot whose worker is gone (the slot id stays stable so
# gauges, tried-sets, and sanitizer guard names never alias across a reuse)
HEALTH_RETIRING = "RETIRING"
HEALTH_RETIRED = "RETIRED"
HEALTH_STATES = (HEALTH_HEALTHY, HEALTH_DEGRADED, HEALTH_QUARANTINED,
                 HEALTH_REBUILDING, HEALTH_RETIRING, HEALTH_RETIRED)


@dataclass
class _ReplicaHealth:
    """Supervision book-keeping for one replica. All fields guarded by the
    owning ReplicaSet's ``_mutex`` (the dataclass never escapes the lock;
    the supervisor and caller paths both mutate it under that mutex)."""

    state: str = HEALTH_HEALTHY
    since: float = 0.0            # perf_counter of the last transition
    last_reason: str = ""
    # caller-observed outcomes: (perf_counter ts, ok) within the breaker
    # window — replica-infrastructure failures only, never policy sheds
    outcomes: deque = field(default_factory=lambda: deque(maxlen=512))
    # perf_counter stamps of observed tick-failure increments
    tick_fails: deque = field(default_factory=lambda: deque(maxlen=64))
    ticks_seen: int = 0           # service tick_failure counter baseline
    quarantined_at: float = 0.0
    next_rebuild_at: float = 0.0  # earliest perf_counter for a rebuild try
    rebuild_attempts: int = 0     # failed attempts THIS quarantine episode
    rebuilds: int = 0             # lifetime successful in-place rebuilds
    # a rebuild for this replica is queued on (or running on) the worker
    # pool: the next supervisor pass must not enqueue it again
    rebuild_inflight: bool = False


@dataclass
class _TenantState:
    """Book-keeping for one tenant. All fields guarded by the queue's
    mutex (the dataclass itself never escapes the lock)."""

    weight: float = 1.0
    pending: int = 0          # requests admitted and not yet released
    deficit: float = 0.0      # DRR token credit (refill-rate mode only)
    last_refill: float = 0.0  # perf_counter of the last deficit refill
    admitted: int = 0
    shed: int = 0
    tokens: int = 0           # actual tokens consumed (prompt + generated)


@guard_locksets
class TenantFairQueue:
    """Weighted fair admission across tenants over a shared queue capacity.

    Three independent rules, every rejection typed and counted per tenant:

    * **quota** — tenant ``t`` may hold at most
      ``max(min_quota, (capacity - headroom) * w_t / Σ w_active)`` pending
      requests, where the active set is every tenant with pending work plus
      the requester. With one active tenant the quota is the whole capacity
      minus the reserved headroom — the slack that guarantees a second
      tenant's FIRST request always finds room (without it, a flood fills
      every replica inbox and fairness can never begin).
    * **deficit** (off by default, ``refill_tokens_per_s > 0`` arms it) —
      token-weighted deficit-round-robin: each tenant's credit refills at
      ``rate x weight`` tokens/s (capped at ``burst x weight``), admission
      under contention (other tenants have pending work) requires a
      non-negative credit, and each admission debits its token cost
      (corrected to actual consumption at release). A lone tenant is never
      deficit-limited — idle capacity is not rationed.
    * **priority tiers** — ``batch`` requests shed once total pending
      crosses ``batch_shed_fraction x capacity``; ``interactive`` requests
      may use the full capacity. Two tiers, shed-earlier semantics: batch
      traffic yields headroom to interactive traffic under load.
    """

    # label-cardinality bound for /metrics: beyond this many distinct
    # tenant keys, new ones share one overflow bucket (a client minting
    # random tenant headers must not grow the metric space unboundedly)
    MAX_TRACKED = 256
    OVERFLOW_TENANT = "overflow"

    def __init__(
        self,
        capacity: int,
        weights: Optional[dict[str, float]] = None,
        default_weight: float = 1.0,
        refill_tokens_per_s: float = 0.0,
        burst_tokens: int = 8192,
        batch_shed_fraction: float = 0.8,
        headroom: Optional[int] = None,
        min_quota: int = 1,
    ) -> None:
        self.capacity = max(int(capacity), 1)
        self.default_weight = max(float(default_weight), 1e-3)
        self.refill_tokens_per_s = max(float(refill_tokens_per_s), 0.0)
        self.burst_tokens = max(int(burst_tokens), 1)
        self.batch_shed_fraction = min(max(float(batch_shed_fraction), 0.0), 1.0)
        self.min_quota = max(int(min_quota), 1)
        # reserved slack no single tenant's quota may consume: the landing
        # room for a tenant the system has not seen yet. An explicit
        # headroom survives capacity re-derivation (set_capacity); the
        # default formula re-derives with the fleet.
        self._explicit_headroom = headroom is not None
        self.headroom = (
            int(headroom) if headroom is not None
            else max(1, self.capacity // 8)
        )
        self.headroom = min(self.headroom, self.capacity - 1)
        self._weights = dict(weights or {})
        self._mutex = make_lock("TenantFairQueue._mutex")
        self._tenants: dict[str, _TenantState] = {}  # guarded-by: _mutex

    # ------------------------------------------------------------- internal

    def _state_locked(self, tenant: str) -> tuple[str, _TenantState]:  # lock-held: _mutex
        assert_held(self._mutex)
        if tenant not in self._tenants and len(self._tenants) >= self.MAX_TRACKED:
            tenant = self.OVERFLOW_TENANT
        state = self._tenants.get(tenant)
        if state is None:
            state = _TenantState(
                weight=max(self._weights.get(tenant, self.default_weight), 1e-3),
            )
            if self.refill_tokens_per_s > 0:
                state.deficit = self.burst_tokens * state.weight
                state.last_refill = time.perf_counter()
            self._tenants[tenant] = state
        return tenant, state

    def _refill_locked(self, state: _TenantState, now: float) -> None:  # lock-held: _mutex
        assert_held(self._mutex)
        if self.refill_tokens_per_s <= 0:
            return
        dt = max(now - state.last_refill, 0.0)
        state.last_refill = now
        state.deficit = min(
            state.deficit + self.refill_tokens_per_s * state.weight * dt,
            self.burst_tokens * state.weight,
        )

    def _quota_locked(self, tenant: str, state: _TenantState) -> int:  # lock-held: _mutex
        assert_held(self._mutex)
        active_weight = state.weight if state.pending == 0 else 0.0
        for other in self._tenants.values():
            if other.pending > 0:
                active_weight += other.weight
        share = (self.capacity - self.headroom) * state.weight \
            / max(active_weight, state.weight)
        return max(self.min_quota, int(share))

    def _shed_locked(self, tenant: str, state: _TenantState, reason: str,
                     message: str, status: int,
                     retry_after_s: float) -> None:  # lock-held: _mutex
        assert_held(self._mutex)
        state.shed += 1
        metrics = get_metrics()
        metrics.record_shed(reason)
        metrics.record_tenant_shed(tenant, reason)
        raise ServiceOverloaded(
            message, status=status, retry_after_s=retry_after_s,
            details={"tenant": tenant, "shed_reason": reason},
        )

    # --------------------------------------------------------------- public

    def set_capacity(self, capacity: int) -> None:
        """Re-derive the shared queue capacity from live fleet membership
        (elastic join / graceful retire). Quotas are computed per-admit from
        ``capacity``/``headroom``, so held reservations need no migration: a
        shrink only tightens FUTURE admissions, it never revokes a pending
        one. An explicitly configured headroom is kept (re-clamped); the
        default formula re-derives with the new capacity."""
        with self._mutex:
            self.capacity = max(int(capacity), 1)  # guarded-by: _mutex
            if not self._explicit_headroom:
                self.headroom = max(1, self.capacity // 8)  # guarded-by: _mutex
            self.headroom = min(self.headroom, self.capacity - 1)  # guarded-by: _mutex

    def admit(self, tenant: str, cost_tokens: int,
              priority: str = PRIORITY_INTERACTIVE,
              reserve: bool = True) -> str:
        """Admit (or, with ``reserve=False``, merely test) one request for
        ``tenant`` with an estimated token cost. Raises a typed
        :class:`ServiceOverloaded` carrying the tenant and shed reason;
        returns the (possibly overflow-bucketed) tenant key actually
        charged, which MUST be passed back to :meth:`release`."""
        now = time.perf_counter()
        with self._mutex:
            tenant, state = self._state_locked(tenant)
            self._refill_locked(state, now)
            total_pending = sum(s.pending for s in self._tenants.values())
            quota = self._quota_locked(tenant, state)
            if state.pending >= quota:
                self._shed_locked(
                    tenant, state, "tenant_quota",
                    f"tenant {tenant!r} is at its fair-share quota "
                    f"({state.pending}/{quota} of {self.capacity} total)",
                    status=429, retry_after_s=1.0,
                )
            if priority == PRIORITY_BATCH and total_pending + 1 > \
                    self.batch_shed_fraction * self.capacity:
                self._shed_locked(
                    tenant, state, "priority_batch",
                    f"batch-tier request shed at {total_pending}/"
                    f"{self.capacity} pending (batch yields to interactive)",
                    status=503, retry_after_s=2.0,
                )
            contended = total_pending - state.pending > 0
            if self.refill_tokens_per_s > 0 and contended and state.deficit < 0:
                wait = -state.deficit / (
                    self.refill_tokens_per_s * state.weight
                )
                self._shed_locked(
                    tenant, state, "tenant_deficit",
                    f"tenant {tenant!r} exhausted its token deficit "
                    f"({state.deficit:.0f}); refilling at "
                    f"{self.refill_tokens_per_s * state.weight:.0f} tok/s",
                    status=429, retry_after_s=max(wait, 0.5),
                )
            if reserve:
                state.pending += 1
                state.admitted += 1
                if self.refill_tokens_per_s > 0:
                    state.deficit -= max(int(cost_tokens), 0)
                get_metrics().record_tenant_admitted(tenant)
            return tenant

    def recharge(self, tenant: str, cost_tokens: int,
                 priority: str = PRIORITY_INTERACTIVE) -> None:
        """Atomically release + re-admit one HELD reservation — the
        quarantine inbox handoff's WFQ move. The ticket is already pending
        (its caller still blocks on it), so this re-evaluates the quota and
        priority rules as if the reservation were being granted now: on
        success the pending count is unchanged and one admission is
        recorded (a handoff is an attempt, like a failover retry); on shed
        the original reservation is RESTORED before the typed error raises,
        so the caller's eventual ``release`` still balances. The deficit is
        untouched — the tokens were debited at original admission and the
        handoff does not re-spend them."""
        now = time.perf_counter()
        with self._mutex:
            state = self._tenants.get(tenant)
            if state is None or state.pending == 0:
                return  # already released (racing completion): nothing held
            self._refill_locked(state, now)
            state.pending -= 1
            try:
                total_pending = sum(s.pending for s in self._tenants.values())
                quota = self._quota_locked(tenant, state)
                if state.pending >= quota:
                    self._shed_locked(
                        tenant, state, "tenant_quota",
                        f"tenant {tenant!r} is over its fair-share quota at "
                        f"handoff ({state.pending + 1}/{quota} of "
                        f"{self.capacity} total)",
                        status=429, retry_after_s=1.0,
                    )
                if priority == PRIORITY_BATCH and total_pending + 1 > \
                        self.batch_shed_fraction * self.capacity:
                    self._shed_locked(
                        tenant, state, "priority_batch",
                        f"batch-tier handoff shed at {total_pending + 1}/"
                        f"{self.capacity} pending (batch yields to "
                        "interactive)",
                        status=503, retry_after_s=2.0,
                    )
            finally:
                state.pending += 1
            state.admitted += 1
            get_metrics().record_tenant_admitted(tenant)

    def release(self, tenant: str, cost_tokens: int,
                actual_tokens: Optional[int] = None) -> None:
        """Return one admission. ``actual_tokens`` (when known) corrects the
        estimated debit, so deficits track real consumption — a request that
        stopped early gets its unspent credit back."""
        with self._mutex:
            state = self._tenants.get(tenant)
            if state is None:
                return
            state.pending = max(state.pending - 1, 0)
            if actual_tokens is not None:
                state.tokens += int(actual_tokens)
                if self.refill_tokens_per_s > 0:
                    state.deficit += max(int(cost_tokens), 0) - max(
                        int(actual_tokens), 0
                    )

    def stats(self) -> dict:
        with self._mutex:
            return {
                "capacity": self.capacity,
                "headroom": self.headroom,
                "refill_tokens_per_s": self.refill_tokens_per_s,
                "per_tenant": {
                    name: {
                        "weight": state.weight,
                        "pending": state.pending,
                        "admitted": state.admitted,
                        "shed": state.shed,
                        "tokens": state.tokens,
                        **({"deficit": round(state.deficit, 1)}
                           if self.refill_tokens_per_s > 0 else {}),
                    }
                    for name, state in self._tenants.items()
                },
            }


@guard_locksets
class WorkerRegistry:
    """Router-side registry of SOCKET replica workers: who is connected,
    at which **incarnation epoch**, and which frames are too old to trust.

    The multi-host worker tier (``REPLICA_MODE=socket``,
    runtime/worker.py + runtime/transport.py) replaces the spawn pipe's
    built-in identity — one pipe, one process, one lifetime — with TCP
    connections that can outlive, predate, or overlap a worker's useful
    life. The registry restores identity with one monotonic counter per
    replica slot:

    * every (re)registration — a spawned worker's first connect, a
      partitioned worker's reconnect, a router dial to an advertised
      remote worker — bumps the slot's epoch and stamps it into the
      connection's frame headers (``SocketTransport.epoch``);
    * the router-side dispatcher drops any frame whose epoch is older
      than the slot's CURRENT epoch (:meth:`note_stale_frame`): a worker
      that vanished behind a partition and later heals can never
      resurrect dead tickets or double-deliver stream chunks, because its
      pre-partition frames are fenced the instant the new incarnation
      registers;
    * the supervisor's respawn path *awaits re-registration* here
      (:meth:`await_registration`) before deciding between **heal** (a
      live worker reconnected — adopt the new connection, keep the
      process) and **respawn** (no re-registration in time — reap and
      spawn fresh).

    One listener serves every slot; worker hellos are authenticated with
    the shared token (constant-time compare) and version-checked before
    any epoch is granted. Rejections are counted into
    ``sentio_tpu_worker_reconnects_total{outcome=rejected_*}``.

    **Elastic membership** — the startup slot count is a floor, not a
    ceiling. A hello carrying ``slot == -1`` is an ELASTIC JOIN: the
    registry allocates a slot (reusing a released one when available, else
    growing the set), acks the assigned slot back (``hello_ack`` carries
    ``"slot"`` — the worker adopts it for reconnects), and publishes a
    join event (:meth:`drain_joins`) the ReplicaSet's supervisor consumes
    to wire a new :class:`~sentio_tpu.runtime.worker.ProcessReplica` into
    rotation. :meth:`release_slot` returns a slot after graceful retire;
    the slot's epoch entry SURVIVES release, so a reused slot's first
    epoch continues the monotonic fence and pre-retire frames can never
    read as fresh. Explicit out-of-range slots stay rejected — elastic
    join is opt-in via the sentinel, not a blanket trust of any slot id."""

    def __init__(
        self,
        auth_token: str,
        slots: int,
        bind_host: str = "127.0.0.1",
        bind_port: int = 0,
        max_frame_bytes: int = 32 * 1024 * 1024,
        frame_timeout_s: float = 30.0,
        hello_timeout_s: float = 10.0,
    ) -> None:
        import socket as _socket

        if not auth_token:
            raise ValueError("WorkerRegistry needs a non-empty auth token")
        self.auth_token = auth_token
        self.slots = max(int(slots), 1)
        self.max_frame_bytes = int(max_frame_bytes)
        self.frame_timeout_s = float(frame_timeout_s)
        self.hello_timeout_s = float(hello_timeout_s)
        self._mutex = make_lock("WorkerRegistry._mutex")
        self._epochs = [0] * self.slots  # guarded-by: _mutex
        self._stale = [0] * self.slots  # guarded-by: _mutex
        self._registrations = 0  # guarded-by: _mutex
        self._rejections = 0  # guarded-by: _mutex
        # elastic membership book-keeping: released slot ids available for
        # reuse, elastic-join counters, and the join-event queue the
        # ReplicaSet supervisor drains to attach new workers. _pending only
        # GROWS (never shrinks) so lock-free indexed reads stay valid; the
        # per-slot queues are themselves thread-safe.
        self._free: list[int] = []  # guarded-by: _mutex
        self._elastic_joins = 0  # guarded-by: _mutex
        self._released = 0  # guarded-by: _mutex
        self._joins: _queue.Queue = _queue.Queue()
        # deliberately NOT lock-guarded: the list only grows (appends
        # happen under _mutex in _alloc_slot, indices never shift), so a
        # lock-free indexed read always lands on a valid thread-safe Queue
        self._pending: list[_queue.Queue] = [
            _queue.Queue() for _ in range(self.slots)
        ]
        self._stop = threading.Event()
        listener = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        listener.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        # bounded accept wait: close() must be able to stop the loop
        listener.settimeout(0.2)
        listener.bind((bind_host, int(bind_port)))
        listener.listen(max(2 * self.slots, 8))
        self._listener = listener
        self._addr = listener.getsockname()
        self._accepter = threading.Thread(
            target=self._accept_loop, name="worker-registry-accept",
            daemon=True,
        )
        self._accepter.start()

    @property
    def address(self) -> tuple:
        """(host, port) workers dial to (self-)register."""
        return self._addr

    # ------------------------------------------------------------ epoch book

    def current_epoch(self, slot: int) -> int:
        with self._mutex:
            return self._epochs[slot]

    def assign_epoch(self, slot: int) -> int:
        """Bump + return the slot's incarnation epoch. The bump is the
        fence: from this instant every frame of the PREVIOUS incarnation
        is stale. Also used directly by the dial-out path
        (``REPLICA_WORKERS``), where the router initiates the connection
        and no listener registration happens."""
        with self._mutex:
            self._epochs[slot] += 1
            epoch = self._epochs[slot]
        try:
            get_metrics().record_worker_incarnation(slot, epoch)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass
        return epoch

    def note_stale_frame(self, slot: int) -> None:
        with self._mutex:
            self._stale[slot] += 1
        try:
            get_metrics().record_stale_frames(slot)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass

    def stale_frames(self, slot: int) -> int:
        with self._mutex:
            return self._stale[slot]

    # ------------------------------------------------------------ elasticity

    def _alloc_slot(self) -> int:
        """Allocate a slot for an elastic join: reuse the lowest released
        slot when one exists (its epoch entry was kept, so the fence
        continues), else grow the slot set by one."""
        with self._mutex:
            if self._free:
                self._free.sort()
                slot = self._free.pop(0)
            else:
                slot = self.slots
                self.slots += 1  # guarded-by: _mutex
                self._epochs.append(0)
                self._stale.append(0)
                self._pending.append(_queue.Queue())
            self._elastic_joins += 1
        return slot

    def release_slot(self, slot: int) -> None:
        """Return a slot after a graceful retire. The epoch entry is KEPT
        (not reset): the next worker on this slot registers at a HIGHER
        epoch than every frame the retired incarnation ever sent, so slot
        reuse can never un-fence stale frames. Double-release is a no-op."""
        with self._mutex:
            if not (0 <= slot < self.slots) or slot in self._free:
                return
            self._free.append(slot)
            self._released += 1
        # drop any registration that raced the release onto the queue: a
        # redial of the retired incarnation must not be adopted later
        q = self._pending[slot]
        while True:
            try:
                transport, _h, _e = q.get_nowait()
            except _queue.Empty:
                break
            transport.close()

    def drain_joins(self) -> list[int]:
        """Slots elastically joined since the last call (non-blocking).
        The ReplicaSet supervisor polls this to wire new workers into
        rotation; each slot appears once per registration event."""
        slots: list[int] = []
        while True:
            try:
                slots.append(self._joins.get_nowait())
            except _queue.Empty:
                break
        return slots

    # ---------------------------------------------------------- registration

    def _accept_loop(self) -> None:
        import socket as _socket

        while not self._stop.is_set():
            try:
                conn, _peer = self._listener.accept()
            except _socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: shutting down
            # handshake on its own short-lived thread: a connector that
            # never sends its hello must not stall the accept loop (the
            # hello read is bounded by hello_timeout_s)
            threading.Thread(
                target=self._handshake, args=(conn,),
                name="worker-registry-handshake", daemon=True,
            ).start()

    # frame-emit: handshake-to-dialer via=socket
    def _handshake(self, conn) -> None:
        from sentio_tpu.runtime.transport import (
            SocketTransport,
            TransportClosed,
            TransportError,
            expect_hello,
        )

        transport = SocketTransport(
            conn, max_frame_bytes=self.max_frame_bytes,
            frame_timeout_s=self.frame_timeout_s,
        )
        try:
            hello = expect_hello(transport, self.auth_token,
                                 timeout_s=self.hello_timeout_s)
        except TransportClosed as exc:
            # a connection that never spoke (port scan, TCP liveness
            # probe, flaky dialer): not a protocol rejection — booking it
            # as rejected_* would pollute the series operators are told
            # should be zero in steady state
            logger.debug("silent connection to the worker registry "
                         "dropped: %s", exc)
            with self._mutex:
                self._rejections += 1
            transport.close()
            return
        except TransportError as exc:
            self._reject(transport, None, str(exc))
            return
        except Exception:  # noqa: BLE001 — a hostile hello must not kill the thread
            logger.exception("worker registration handshake crashed")
            transport.close()
            return
        slot = hello.get("slot", -1)
        elastic = isinstance(slot, int) and slot == -1
        if elastic:
            # elastic join: the worker asks for a slot instead of claiming
            # one — allocate (reuse-or-grow) and tell it the answer in the
            # ack so its reconnect loop redials the SAME identity
            try:
                faults.hit("registry.elastic_join")
            except Exception as exc:  # noqa: BLE001 — chaos: an injected join failure must reject typed, not kill the handshake thread
                self._reject(transport, transport,
                             f"elastic join failed: {exc}")
                return
            slot = self._alloc_slot()
        elif not isinstance(slot, int) or not (0 <= slot < self.slots):
            self._reject(transport, transport, f"unknown slot {slot!r}")
            return
        else:
            with self._mutex:
                retired = slot in self._free
            if retired:
                # a retired incarnation redialing its released slot: a
                # typed rejection stops its reconnect loop — adopting it
                # would resurrect a worker the fleet already drained out
                self._reject(transport, transport,
                             f"slot {slot} was retired")
                return
        epoch = self.assign_epoch(slot)
        transport.fault_scope = f"r{slot}"
        transport.epoch = epoch
        try:
            transport.send((0, "hello_ack", {"epoch": epoch, "slot": slot}))
        except TransportError:
            if elastic:
                self.release_slot(slot)
            transport.close()
            return
        with self._mutex:
            self._registrations += 1
        logger.info("worker registered for slot %d at epoch %d (pid %s%s)",
                    slot, epoch, hello.get("pid"),
                    ", elastic join" if elastic else "")
        q = self._pending[slot]
        # supersede by EPOCH, not by arrival order: two racing
        # registrations for a slot (a partitioned worker's redial vs the
        # supervisor's fresh respawn) may drain each other concurrently,
        # and keeping whichever thread ran last would let the STALE
        # connection bury the live one. Collect everything queued plus
        # this one, keep the highest epoch, close the rest.
        entries = [(transport, hello, epoch)]
        while True:
            try:
                entries.append(q.get_nowait())
            except _queue.Empty:
                break
        entries.sort(key=lambda e: e[2])
        for old_transport, _h, _e in entries[:-1]:
            old_transport.close()
        q.put(entries[-1])
        if elastic:
            # publish the join AFTER the registration is queued: the
            # consumer's await_registration must find the transport
            self._joins.put(slot)

    # frame-emit: handshake-to-dialer via=socket
    def _reject(self, transport, ackable, reason: str) -> None:
        with self._mutex:
            self._rejections += 1
        outcome = ("rejected_auth" if "token" in reason
                   else "rejected_proto")
        logger.warning("worker registration rejected: %s", reason)
        try:
            get_metrics().record_worker_reconnect(outcome)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass
        if ackable is not None:
            from sentio_tpu.runtime.transport import TransportError

            try:
                ackable.send((0, "hello_reject", {"reason": reason}))
            except TransportError:
                pass
        transport.close()

    def await_registration(self, slot: int, timeout_s: float):
        """Block until a worker registers for ``slot`` (or raise a typed
        :class:`ReplicaUnavailable` after ``timeout_s``). Returns
        ``(transport, hello, epoch)`` for the NEWEST registration —
        superseded ones were already fenced and closed."""
        deadline = time.perf_counter() + max(timeout_s, 0.0)
        q = self._pending[slot]
        while True:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                raise ReplicaUnavailable(
                    f"no worker registered for slot {slot} within "
                    f"{timeout_s:.0f}s",
                    retry_after_s=2.0,
                    details={"replica": slot, "reason": "no_registration"},
                )
            try:
                transport, hello, epoch = q.get(timeout=min(remaining, 0.5))
            except _queue.Empty:
                continue
            if epoch < self.current_epoch(slot):
                transport.close()  # superseded while queued
                continue
            return transport, hello, epoch

    # ------------------------------------------------------------- lifecycle

    def stats(self) -> dict:
        with self._mutex:
            return {
                "epochs": list(self._epochs),
                "stale_frames": list(self._stale),
                "registrations": self._registrations,
                "rejections": self._rejections,
                "slots": self.slots,
                "free_slots": sorted(self._free),
                "elastic_joins": self._elastic_joins,
                "released_slots": self._released,
            }

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._accepter.is_alive():
            self._accepter.join(timeout=5.0)
        for q in self._pending:
            while True:
                try:
                    transport, _h, _e = q.get_nowait()
                except _queue.Empty:
                    break
                transport.close()


@guard_locksets
class ReplicaSet:
    """Front-end over N independent paged-decode replicas: WFQ admission →
    radix-affinity / least-loaded routing → delegate to the chosen
    replica's :class:`PagedGenerationService`. Same call surface as one
    service; N=1 degenerates to a thin pass-through."""

    # duck-typing flag callers use to decide whether tenant/priority kwargs
    # are understood (a bare PagedGenerationService or a test fake is not)
    supports_tenants = True

    def __init__(
        self,
        services: Sequence[PagedGenerationService],
        tenant_weights: Optional[dict[str, float]] = None,
        tenant_default_weight: float = 1.0,
        tenant_refill_tokens_per_s: float = 0.0,
        tenant_burst_tokens: int = 8192,
        tenant_headroom: Optional[int] = None,
        batch_shed_fraction: float = 0.8,
        affinity_stickiness: float = 4.0,
        route_prefix_tokens: int = 512,
        supervise: bool = True,
        probe_interval_s: float = 0.25,
        breaker_window_s: float = 30.0,
        breaker_error_rate: float = 0.5,
        breaker_min_samples: int = 4,
        breaker_tick_failures: int = 3,
        quarantine_backoff_s: float = 0.5,
        rebuild_budget: int = 3,
        rebuild_drain_s: float = 5.0,
        failover_budget: int = 1,
        stream_resume_budget: Optional[int] = None,
        rebuild_workers: int = 1,
    ) -> None:
        services = list(services)
        if not services:
            raise ValueError("ReplicaSet needs at least one replica")
        self._check_isolation(services)
        # element SWAPS (supervised rebuild) happen under _mutex; reads are
        # deliberately lock-free GIL-atomic list indexing — a caller that
        # grabbed the old replica mid-swap gets a typed failure and fails
        # over, which is cheaper than locking every routing probe
        self._services = services
        for i, svc in enumerate(services):
            svc.replica_id = i
            guard = getattr(svc.engine, "_san", None)
            if guard is not None:
                # per-replica pump ownership: sanitizer errors must name
                # WHICH replica's engine a stray thread touched
                guard.name = f"ContinuousBatchingEngine[r{i}]"
        self.tokenizer = services[0].engine.tokenizer
        # route on at most this many prompt-head tokens: prefixes longer
        # than this are indistinguishable to the router but not to the
        # replica's radix cache, which still reuses the full match
        self.route_prefix_tokens = max(int(route_prefix_tokens),
                                       services[0].engine.page_size)
        # a prefix-hit replica keeps the request only while its backlog is
        # within stickiness x its slot count; past that, cache reuse costs
        # more queueing delay than the suffix prefill it saves
        self.affinity_stickiness = max(float(affinity_stickiness), 0.0)
        self.tenants = TenantFairQueue(
            capacity=sum(svc.max_queue for svc in services),
            weights=tenant_weights,
            default_weight=tenant_default_weight,
            refill_tokens_per_s=tenant_refill_tokens_per_s,
            burst_tokens=tenant_burst_tokens,
            batch_shed_fraction=batch_shed_fraction,
            headroom=tenant_headroom,
        )
        self._mutex = make_lock("ReplicaSet._mutex")
        # routing outcome counters (telemetry only)
        self._routed_affinity = 0  # guarded-by: _mutex
        self._routed_load = 0  # guarded-by: _mutex
        self._affinity_overflow = 0  # guarded-by: _mutex
        # ---- replica supervision (failure domains) ----
        self.probe_interval_s = max(float(probe_interval_s), 0.01)
        self.breaker_window_s = max(float(breaker_window_s), 0.1)
        self.breaker_error_rate = min(max(float(breaker_error_rate), 0.0), 1.0)
        self.breaker_min_samples = max(int(breaker_min_samples), 1)
        self.breaker_tick_failures = max(int(breaker_tick_failures), 1)
        self.quarantine_backoff_s = max(float(quarantine_backoff_s), 0.0)
        # failed rebuild attempts beyond this budget fall back to the max
        # backoff (the supervisor never gives up — a replica stuck broken
        # just retries slowly instead of hot-looping expensive rebuilds)
        self.rebuild_budget = max(int(rebuild_budget), 0)
        self.rebuild_drain_s = max(float(rebuild_drain_s), 0.0)
        # ReplicaSet-layer retry budget for failed-over requests (PR 5's
        # per-ticket crash retry budget, lifted across replicas)
        self.failover_budget = max(int(failover_budget), 0)
        # resume-by-replay budget for DELIVERED-token streams (the case
        # plain failover cannot restart without duplicating output): None
        # follows the failover budget; 0 disables resumption and keeps the
        # pre-resume typed mid-stream error (STREAM_RESUME_BUDGET env via
        # serve/dependencies.py)
        self.stream_resume_budget = (
            max(int(stream_resume_budget), 0)
            if stream_resume_budget is not None else self.failover_budget
        )
        self._health = [
            _ReplicaHealth(since=time.perf_counter(),
                           # baseline, not zero: pre-existing tick failures
                           # on a reused engine must not instantly trip the
                           # burst breaker
                           ticks_seen=svc.tick_failure_count)
            for svc in services
        ]  # guarded-by: _mutex
        self._failovers = 0  # guarded-by: _mutex
        self._closed = False  # guarded-by: _mutex
        # elastic-fleet counters: runtime joins, graceful retires, and the
        # ONLY trace a retired replica leaves behind besides its slot id
        self._joined = 0  # guarded-by: _mutex
        self._retired = 0  # guarded-by: _mutex
        self._retire_drain_s: deque = deque(maxlen=256)  # guarded-by: _mutex
        # membership source: a callable returning freshly registered
        # services to wire into rotation (socket mode wires the registry's
        # drain_joins here). Single-writer (set once at startup before the
        # supervisor observes it), read by the supervisor pass.
        self._membership_source = None
        self._release_slot = None
        # stall-tolerance telemetry: inbox tickets moved to survivors at
        # quarantine, stall-triggered quarantines, and pump_leaked counts
        # carried over from service incarnations a rebuild replaced (the
        # per-replica sum only sees CURRENT incarnations — without the
        # carryover an abandoned wedged pump would vanish from stats)
        self._handed_off = 0  # guarded-by: _mutex
        self._stall_quarantines = 0  # guarded-by: _mutex
        self._pump_leaked_carryover = 0  # guarded-by: _mutex
        # resumable-stream telemetry: successful mid-flight splices, the
        # delivered tokens replayed for them, and streams whose resume
        # budget (or opt-out) still surfaced the typed mid-stream error
        self._stream_resumes = 0  # guarded-by: _mutex
        self._resume_replayed_tokens = 0  # guarded-by: _mutex
        self._resume_exhausted = 0  # guarded-by: _mutex
        metrics = get_metrics()
        for i in range(len(services)):
            metrics.record_replica_health(i, HEALTH_HEALTHY)
        self._stop = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        # rebuild worker pool: rebuilds are seconds-to-minutes of drain +
        # compile — running them on the supervisor thread would delay the
        # NEXT breaker/watchdog pass behind them. With the pool, the
        # supervisor only detects and enqueues; workers rebuild. Without a
        # supervisor (test mode) _supervise_once rebuilds inline so
        # deterministic stepping keeps working.
        self.rebuild_workers = max(int(rebuild_workers), 0)
        self._rebuild_q: Optional[_queue.Queue] = None
        self._rebuild_pool: list[threading.Thread] = []
        if supervise:
            # process-mode replicas (runtime/worker.py) mirror their
            # never-dispatched tickets router-side; with a supervisor
            # running, a dead worker's shadowed tickets are handed off to
            # survivors instead of failing typed — parity with thread
            # mode's quarantine inbox handoff. Without a supervisor nobody
            # would ever extract the shadow queue, so the flag stays off
            # and death keeps its fail-fast typed surface.
            for svc in services:
                enable = getattr(svc, "enable_shadow_handoff", None)
                if enable is not None:
                    enable()
            if self.rebuild_workers > 0:
                self._rebuild_q = _queue.Queue()
                self._rebuild_pool = [
                    threading.Thread(
                        target=self._rebuild_worker,
                        name=f"replica-rebuild-{k}", daemon=True,
                    )
                    for k in range(self.rebuild_workers)
                ]
                for t in self._rebuild_pool:
                    t.start()
            self._supervisor = threading.Thread(
                target=self._supervise_loop, name="replica-supervisor",
                daemon=True,
            )
            self._supervisor.start()

    @staticmethod
    def _check_isolation(services: Sequence[PagedGenerationService]) -> None:
        """Replicas must not share mutable decode state: a shared engine,
        allocator, pool, or radix tree would be mutated by two pump threads
        at once (immutable weights/tokenizer sharing is the point)."""
        seen: dict[int, tuple[int, str]] = {}
        for i, svc in enumerate(services):
            eng = svc.engine
            parts = {
                "service": svc,
                "engine": eng,
                "allocator": getattr(eng, "allocator", None),
                "pool": getattr(eng, "pool", None),
                "radix": getattr(eng, "_radix", None),
            }
            for what, obj in parts.items():
                if obj is None:
                    continue
                prior = seen.get(id(obj))
                if prior is not None:
                    raise ValueError(
                        f"replica {i} shares its {what} with replica "
                        f"{prior[0]}'s {prior[1]} — replicas must own "
                        f"private decode state"
                    )
                seen[id(obj)] = (i, what)

    # -------------------------------------------------------------- routing

    @property
    def replicas(self) -> int:
        return len(self._services)

    def _route_tokens(self, prompt: str) -> list[int]:
        # chars bound the token count for every tokenizer in the tree (byte
        # tokenizer is 1:1; BPE merges only shrink), so slicing chars first
        # keeps the encode cost flat for very long prompts
        head = prompt[: self.route_prefix_tokens * 4]
        try:
            toks = self.tokenizer.encode(head, add_bos=True)
        except Exception:  # noqa: BLE001 — routing must never fail a request
            return []
        return list(toks[: self.route_prefix_tokens])

    def _eligible(self, exclude: frozenset = frozenset()) -> list[int]:
        """Replica indices the router may pick, by health: HEALTHY first;
        DEGRADED replicas join only when every healthy replica's backlog is
        at its admission bound (no headroom) — and carry the set alone when
        no replica is HEALTHY. QUARANTINED/REBUILDING replicas are NEVER
        eligible. Raises a typed :class:`ReplicaUnavailable` (503 +
        Retry-After) when nothing can serve — the supervisor is rebuilding,
        so retrying IS the right caller move."""
        with self._mutex:
            if self._closed:
                # a closed set never heals: retryable=False so callers (and
                # the wire layer) do not wait on a rebuild nobody will run
                raise ReplicaUnavailable(
                    "replica set is closed", retry_after_s=1.0,
                    retryable=False,
                )
            states = [h.state for h in self._health]
            retry_in = self._rebuild_eta_locked()
        healthy = [i for i, s in enumerate(states)
                   if s == HEALTH_HEALTHY and i not in exclude]
        degraded = [i for i, s in enumerate(states)
                    if s == HEALTH_DEGRADED and i not in exclude]
        if healthy:
            if degraded and all(
                self._services[i].backlog() >= self._services[i].max_queue
                for i in healthy
            ):
                return healthy + degraded
            return healthy
        if degraded:
            return degraded
        raise ReplicaUnavailable(
            "no serving replica available (every replica is quarantined, "
            "rebuilding, or already failed this request over)",
            retry_after_s=max(retry_in, 1.0),
            details={"replica_states": states},
        )

    def _least_loaded(self, eligible: Sequence[int]) -> int:
        """The least-loaded replica among ``eligible`` (projected wait,
        then backlog, then index) — the routing stage-2 key, shared with
        the quarantine inbox handoff's survivor choice."""
        def load_key(i: int):
            svc = self._services[i]
            return (svc.projected_wait() or 0.0, svc.backlog(), i)

        return min(eligible, key=load_key)

    def _rebuild_eta_locked(self) -> float:  # lock-held: _mutex
        """Seconds until the next quarantined replica is due a rebuild try
        — the honest Retry-After for an all-replicas-down shed."""
        assert_held(self._mutex)
        now = time.perf_counter()
        etas = [h.next_rebuild_at - now for h in self._health
                if h.state in (HEALTH_QUARANTINED, HEALTH_REBUILDING)]
        return max(min(etas), 0.0) if etas else 1.0

    def _route(self, toks: Sequence[int], count: bool = True,
               exclude: frozenset = frozenset()) -> tuple[int, int]:
        """→ (replica index, predicted prefix-hit tokens). Stage 0: filter
        to health-eligible replicas (minus ``exclude``, the replicas a
        failing-over request already tried). Stage 1: best ``peek_prefix``
        hit, sticky while that replica's backlog stays under ``stickiness x
        max_slots``. Stage 2: least projected wait. ``count=False`` for
        probes (check_admission): the SSE pre-check routes the same request
        a second time and must not double-count the routing-outcome
        telemetry."""
        eligible = self._eligible(exclude)
        best_i, best_hit = -1, 0
        if len(eligible) > 1 and toks:
            for i in eligible:
                hit = self._services[i].engine.peek_prefix(toks)
                if hit > best_hit:
                    best_i, best_hit = i, hit
        if best_hit > 0:
            svc = self._services[best_i]
            bound = self.affinity_stickiness * max(svc.engine.max_slots, 1)
            if svc.backlog() <= bound:
                if count:
                    with self._mutex:
                        self._routed_affinity += 1
                return best_i, best_hit
            if count:
                with self._mutex:
                    self._affinity_overflow += 1

        idx = self._least_loaded(eligible)
        if count:
            with self._mutex:
                self._routed_load += 1
        return idx, 0

    # ------------------------------------------------------------------ api

    @staticmethod
    def _is_replica_failure(exc: BaseException) -> bool:
        """Failures that indict the REPLICA (its engine broke, its service
        closed under it) rather than the request (sheds, deadlines,
        validation) — only these are worth failing over."""
        return isinstance(exc, ReplicaUnavailable)

    def generate(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: str = PRIORITY_INTERACTIVE,
    ):
        """Route + delegate, with cross-replica failover: a replica that
        dies under this request (typed ReplicaUnavailable, or the
        finish_reason='error' result a crashed pump hands its waiters) is
        reported to the breaker and — within ``failover_budget`` — the
        request is re-admitted and re-routed to a surviving replica. The
        WFQ reservation is fully released before each retry re-charges, so
        failover can never double-count a tenant's quota."""
        toks = self._route_tokens(prompt)
        cost = len(toks) + max_new_tokens
        tenant_key = tenant or DEFAULT_TENANT
        attempts = 0
        tried: set[int] = set()
        while True:
            charged = self.tenants.admit(tenant_key, cost, priority=priority)
            idx = svc = None
            try:
                idx, _hit = self._route(toks, exclude=frozenset(tried))
                svc = self._services[idx]
                result = svc.generate(
                    prompt, max_new_tokens=max_new_tokens,
                    temperature=temperature, timeout_s=timeout_s,
                    request_id=request_id, deadline_s=deadline_s,
                    deadline_ts=deadline_ts, top_k=top_k,
                    # opaque WFQ metadata riding the ticket: the quarantine
                    # inbox handoff uses it to release/re-charge this
                    # reservation when the ticket moves to a survivor
                    tenant=charged, priority=priority, cost_tokens=cost,
                )
            except BaseException as exc:
                # failed before (shed) or during decode: refund the
                # estimated debit — charging full cost for work that never
                # ran would let replica-level sheds drain an innocent
                # tenant's deficit
                self.tenants.release(charged, cost, actual_tokens=0)
                if idx is not None and self._is_replica_failure(exc):
                    self._note_failure(idx, exc, svc)
                    tried.add(idx)
                    if attempts < self.failover_budget:
                        attempts += 1
                        with self._mutex:
                            self._failovers += 1
                        continue  # re-admits (re-charges) at the loop top
                raise
            if result.finish_reason == "error":
                # the crashed pump's budget-exhausted waiter surface: the
                # request itself never misbehaved, so it is resumable here
                self._note_failure(
                    idx, ReplicaUnavailable("error result from replica"),
                    svc)
                tried.add(idx)
                if attempts < self.failover_budget:
                    self.tenants.release(charged, cost, actual_tokens=0)
                    attempts += 1
                    with self._mutex:
                        self._failovers += 1
                    continue
            else:
                self._note_success(idx, svc)
            self.tenants.release(
                charged, cost,
                actual_tokens=result.prompt_tokens + len(result.tokens),
            )
            return result

    def generate_stream(
        self,
        prompt: str,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout_s: Optional[float] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        deadline_ts: Optional[float] = None,
        top_k: int = 0,
        tenant: Optional[str] = None,
        priority: str = PRIORITY_INTERACTIVE,
        stats_out: Optional[dict] = None,
        seed: Optional[int] = None,
        resumable: bool = True,
    ) -> Iterator[str]:
        """Streaming with MID-FLIGHT failover. A stream that dies before
        delivering anything fails over like a generate (fresh restart on a
        survivor, within ``failover_budget``). A stream that dies WITH
        delivered tokens — today's only non-resumable case before this —
        is RESUMED by replay-prefill: the router re-admits on a survivor
        with the exact delivered token prefix as a prior context suffix
        (``prior_tokens``), decode continues from the splice point, and
        only post-splice text is yielded — the client sees one
        uninterrupted stream with zero duplicated and zero missing tokens.
        Greedy resumes are token-exact vs a no-fault run; sampled resumes
        carry the call-time knobs (temperature/top_k/``seed``) so the
        continuation is distribution-correct. ``resumable=False`` (or
        ``stream_resume_budget=0``) opts out and keeps the typed
        mid-stream error."""
        toks = self._route_tokens(prompt)
        idx, _hit = self._route(toks)
        progress = StreamProgress()
        kwargs = dict(
            max_new_tokens=max_new_tokens, temperature=temperature,
            timeout_s=timeout_s, request_id=request_id,
            deadline_s=deadline_s, deadline_ts=deadline_ts, top_k=top_k,
            # WFQ handoff metadata (see generate): streams charge at first
            # next(), so the ticket is stamped provisionally with the raw
            # key here and RE-STAMPED with the charged (possibly overflow-
            # bucketed) key inside _stream_impl once admit() resolves it —
            # a quarantine-handoff recharge looks the ticket's key up in
            # the fair queue, and the raw key of a bucketed tenant is
            # unknown there (the PR 10 recharge gap)
            tenant=tenant or DEFAULT_TENANT, priority=priority,
            cost_tokens=len(toks) + max_new_tokens,
            stats_out=stats_out,
            # delivered-state tracking: per-piece token ids mirrored by the
            # replica's stream impl — the splice a resume re-admits; the
            # sampling knobs above (temperature/top_k) plus this seed are
            # stamped at CALL time and ride kwargs into every attempt
            seed=seed, progress=progress,
        )
        # the replica's own generate_stream runs its CALL-time validation
        # (top_k vs paged speculation) here, before any SSE 200 commits;
        # its admission — and our tenant reservation — stay deferred to the
        # first next(), the long-standing stream contract
        svc = self._services[idx]
        inner = svc.generate_stream(prompt, **kwargs)
        return self._stream_impl(inner, idx, svc, toks, prompt, kwargs,
                                 tenant or DEFAULT_TENANT,
                                 len(toks) + max_new_tokens, priority,
                                 progress, max_new_tokens, resumable)

    def _stream_impl(self, inner: Iterator[str], idx: int, svc,
                     toks: Sequence[int], prompt: str, kwargs: dict,
                     tenant: str, cost: int, priority: str,
                     progress: StreamProgress, max_new_tokens: int,
                     resumable: bool) -> Iterator[str]:
        attempts = 0   # fresh-restart failovers (nothing delivered yet)
        resumes = 0    # replay-prefill resumes (delivered tokens spliced)
        tried = {idx}
        base: list[int] = []  # token ids delivered by PRIOR attempts
        flushed = ""          # text already yielded to the caller
        # a resume is BOOKED (counters, flight event, metric) only after
        # its attempt clears the loop-top WFQ admission below — booking in
        # the except branch would count a resume the quota then shed
        pending_resume_note: Optional[tuple] = None
        while True:
            try:
                charged = self.tenants.admit(tenant, cost, priority=priority)
            except BaseException:
                if pending_resume_note is not None:
                    self._record_resume_outcome("failed")
                raise
            if pending_resume_note is not None:
                self._note_resume(*pending_resume_note)
                pending_resume_note = None
            if kwargs.get("tenant") != charged:
                # the reservation landed under a DIFFERENT key than the one
                # stamped at call time (overflow bucketing): re-create the
                # not-yet-started inner iterator with the charged key, so a
                # quarantine inbox handoff can recharge the reservation it
                # actually holds instead of silently skipping it. The
                # discarded iterator never ran (generator bodies defer to
                # first next()), so no ticket or admission leaks.
                kwargs["tenant"] = charged
                inner = svc.generate_stream(prompt, **kwargs)
            try:
                if not base:
                    # first attempt (or fresh restart): forward verbatim —
                    # the zero-overhead happy path; the service's own UTF-8
                    # withholding already shaped the pieces
                    for piece in inner:
                        flushed += piece
                        yield piece
                else:
                    # resumed attempt: the inner stream's pieces decode the
                    # CONTINUATION tokens in isolation, which may not
                    # splice cleanly onto text the dead attempt already
                    # flushed (withheld trailing chars, multi-token UTF-8).
                    # Re-decode the FULL delivered sequence at each piece
                    # and yield only what extends the flushed prefix: zero
                    # duplicated, zero missing tokens by construction.
                    for _piece in inner:
                        text = self.tokenizer.decode(
                            base + list(progress.tokens))
                        safe = text[:-1] if text.endswith("�") else text
                        if len(safe) > len(flushed):
                            delta = safe[len(flushed):]
                            flushed = safe
                            yield delta
                    # final flush is unconditional, like the service's own
                    # done-path: a finished answer may end in a replacement
                    # char for real
                    text = self.tokenizer.decode(base + list(progress.tokens))
                    if len(text) > len(flushed):
                        delta = text[len(flushed):]
                        flushed = text
                        yield delta
                stats_out = kwargs.get("stats_out")
                if stats_out is not None and resumes:
                    # the service's done-path stats cover the CONTINUATION
                    # request only; restore the whole-stream token count and
                    # stamp the resume provenance for bench/confidence sinks
                    stats_out["tokens"] = len(base) + len(progress.tokens)
                    stats_out["resumed"] = resumes
                    stats_out["replayed_tokens"] = len(base)
                self.tenants.release(charged, cost)
                self._note_success(idx, svc)
                return
            except BaseException as exc:
                # streams release at close/exhaust/error with the estimate —
                # the exact split is not worth holding the reservation open
                self.tenants.release(charged, cost)
                if not self._is_replica_failure(exc):
                    raise
                self._note_failure(idx, exc, svc)
                delivered = bool(flushed) or bool(base)
                if not delivered and attempts < self.failover_budget:
                    tried.add(idx)
                    attempts += 1
                    with self._mutex:
                        self._failovers += 1
                    progress.reset()
                    # may itself raise typed ReplicaUnavailable when no
                    # survivor exists — still a typed terminal outcome
                    idx, _hit = self._route(toks, exclude=frozenset(tried))
                    svc = self._services[idx]
                    inner = svc.generate_stream(prompt, **kwargs)
                    continue
                if delivered and resumable \
                        and resumes < self.stream_resume_budget:
                    from_idx = idx
                    tried.add(idx)
                    resumes += 1
                    base = base + list(progress.tokens)
                    progress.reset()
                    remaining = max_new_tokens - len(base)
                    if remaining <= 0:
                        # every requested token was already delivered; only
                        # a final flush can be owed — emit it and finish
                        # without re-admitting anything. replica_to=-1:
                        # the death was absorbed with NO survivor
                        # re-admission, so the event must not claim a
                        # splice landed on some replica
                        text = self.tokenizer.decode(base)
                        self._note_resume(from_idx, -1, 0, len(base))
                        stats_out = kwargs.get("stats_out")
                        if stats_out is not None:
                            # the dead attempt never reached its done-path
                            # stats fill; stamp what the router knows so
                            # bench/confidence sinks see a completed,
                            # resumed stream instead of an empty dict
                            stats_out["tokens"] = len(base)
                            stats_out["resumed"] = resumes
                            stats_out["replayed_tokens"] = 0
                        if len(text) > len(flushed):
                            yield text[len(flushed):]
                        return
                    try:
                        # survivor choice favors the deepest cached prefix
                        # of prompt+delivered (peek_prefix walks the full
                        # resume context head): surviving pages turn the
                        # replay into a prefix hit. Valid only while the
                        # routing head covers the WHOLE prompt — toks is
                        # clamped to route_prefix_tokens, and appending
                        # base after a truncated head would probe a token
                        # sequence no radix holds
                        resume_toks = (
                            list(toks) + base
                            if len(toks) < self.route_prefix_tokens
                            else list(toks)
                        )
                        # exclude only the replica that just died — not the
                        # whole `tried` history: a replica a FRESH failover
                        # left behind may have been rebuilt and healthy by
                        # now, and `_route` already skips quarantined/
                        # rebuilding replicas on its own
                        idx, _hit = self._route(
                            resume_toks, exclude=frozenset({from_idx}))
                    except BaseException:
                        self._record_resume_outcome("failed")
                        raise
                    svc = self._services[idx]
                    kwargs["prior_tokens"] = list(base)
                    kwargs["max_new_tokens"] = remaining
                    inner = svc.generate_stream(prompt, **kwargs)
                    # booked at the top of the loop AFTER the WFQ admission
                    # for this attempt clears
                    pending_resume_note = (from_idx, idx, len(base),
                                           len(base))
                    continue
                if delivered:
                    self._record_resume_outcome(
                        "exhausted" if resumable
                        and self.stream_resume_budget > 0 else "opt_out")
                raise

    def _note_resume(self, replica_from: int, replica_to: int,
                     replayed: int, splice_index: int) -> None:
        """Book one successful mid-flight resume: counters, the
        ``stream_resumed`` flight event, and the outcome metric.
        ``replica_to=-1`` marks a death absorbed with NO survivor
        re-admission (every requested token was already delivered)."""
        with self._mutex:
            self._stream_resumes += 1
            self._resume_replayed_tokens += replayed
        self._record_resume_outcome("resumed")
        try:
            from sentio_tpu.infra.flight import get_flight_recorder

            get_flight_recorder().record_tick(
                event="stream_resumed", replica_from=replica_from,
                replica_to=replica_to, replayed_tokens=replayed,
                splice_index=splice_index,
            )
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            logger.debug("stream resume telemetry failed", exc_info=True)

    def _record_resume_outcome(self, outcome: str) -> None:
        if outcome == "exhausted":
            with self._mutex:
                self._resume_exhausted += 1
        try:
            get_metrics().record_stream_resume(outcome)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            logger.debug("stream resume metric failed", exc_info=True)

    def check_admission(
        self,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: str = PRIORITY_INTERACTIVE,
        prompt: Optional[str] = None,
    ) -> None:
        """Raise what a submit right now would raise, WITHOUT reserving:
        WFQ tenant check first (peek mode), then the target replica's own
        admission check. With a ``prompt`` the probe routes exactly as the
        submit will; without one it checks the least-loaded replica (if
        that one sheds, every routing choice would). With every replica
        quarantined the routing stage itself raises the typed 503."""
        self.tenants.admit(tenant or DEFAULT_TENANT, 0, priority=priority,
                           reserve=False)
        toks = self._route_tokens(prompt) if prompt else []
        idx, _hit = self._route(toks, count=False)
        self._services[idx].check_admission(deadline_ts)

    # ------------------------------------------------------- elastic fleet

    def set_membership_source(self, source, release_slot=None) -> None:
        """Install the callable the supervisor polls each pass for freshly
        joined replicas (socket mode wires a closure that drains the
        WorkerRegistry's join events and builds one ``ProcessReplica`` per
        new slot). The source returns ``[(slot, service), ...]`` —
        ``slot=None`` lets the set pick its own index (thread mode).
        ``release_slot`` (optional) is called with the slot index after a
        graceful retire closes the worker, returning the registry slot to
        the elastic free list. Install at startup, before traffic — both
        attributes are single-writer and read only by supervisor-side
        passes."""
        self._membership_source = source
        self._release_slot = release_slot

    def _rederive_capacity(self) -> None:
        """Re-derive the WFQ summed capacity (and default headroom) from
        live membership after a join or retire. The snapshot is taken under
        ``_mutex``; the fair queue is updated OUTSIDE it so no ReplicaSet →
        TenantFairQueue lock-order edge is ever created."""
        with self._mutex:
            caps = [
                getattr(self._services[i], "max_queue", 0)
                for i, h in enumerate(self._health)
                if h.state != HEALTH_RETIRED
            ]
        self.tenants.set_capacity(sum(caps))
        try:
            live = len(caps)
            get_metrics().record_fleet_size(live)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass

    def fleet_load(self) -> dict:
        """Lightweight saturation sample for the autoscaler: serving
        replica count, mean busy fraction (``1 - idle`` duty), and summed
        backlog as a fraction of summed queue capacity — all from cached
        probes (process/socket replicas answer from their pushed status
        frames, so sampling at supervisor cadence costs zero RPCs)."""
        with self._mutex:
            serving = [
                (i, self._services[i])
                for i, h in enumerate(self._health)
                if h.state in (HEALTH_HEALTHY, HEALTH_DEGRADED)
            ]
        per: list[dict] = []
        backlog_total = 0
        capacity_total = 0
        for i, svc in serving:
            try:
                duty = svc.duty_cycle() or {}
                idle = float(duty.get("idle", 1.0))
                backlog = int(svc.backlog())
            except Exception:  # noqa: BLE001 — replica mid-swap: skip one sample
                continue
            busy = max(0.0, min(1.0, 1.0 - idle))
            backlog_total += backlog
            capacity_total += int(getattr(svc, "max_queue", 0) or 0)
            per.append({"replica": i, "busy": busy, "backlog": backlog})
        busy_mean = (sum(p["busy"] for p in per) / len(per)) if per else 0.0
        return {
            "serving": len(serving),
            "busy": busy_mean,
            "backlog_fraction": (backlog_total / capacity_total
                                 if capacity_total else 0.0),
            "replicas": per,
        }

    def add_replica(self, svc, idx: Optional[int] = None) -> int:
        """Wire a NEW replica into rotation at runtime (elastic join).
        ``idx=None`` reuses the lowest RETIRED slot, else appends; socket
        mode passes the registry slot so router index and wire identity
        stay aligned. The new replica enters HEALTHY, the WFQ capacity and
        headroom re-derive from live membership, and — under a supervising
        set — shadow handoff arms exactly like a startup replica. Returns
        the slot index the replica serves under."""
        faults.hit("replica.join")
        supervised = self._supervisor is not None
        with self._mutex:
            if self._closed:
                raise ReplicaUnavailable(
                    "replica set is closed", retry_after_s=1.0,
                    retryable=False,
                )
            if idx is None:
                idx = next((i for i, h in enumerate(self._health)
                            if h.state == HEALTH_RETIRED), None)
            elif idx < len(self._health) \
                    and self._health[idx].state != HEALTH_RETIRED:
                raise ValueError(
                    f"slot {idx} is occupied by a "
                    f"{self._health[idx].state} replica")
            elif idx > len(self._health):
                raise ValueError(
                    f"slot {idx} would leave a gap (set holds "
                    f"{len(self._health)} slots)")
            elif idx == len(self._health):
                idx = None  # plain append
            live = [self._services[i] for i, h in enumerate(self._health)
                    if h.state != HEALTH_RETIRED]
            self._check_isolation(live + [svc])
            fresh_health = _ReplicaHealth(
                since=time.perf_counter(),
                ticks_seen=getattr(svc, "tick_failure_count", 0) or 0,
            )
            if idx is None:
                idx = len(self._services)
                svc.replica_id = idx
                self._services.append(svc)
                self._health.append(fresh_health)
            else:
                # RETIRED slot reuse: stable index, fresh incarnation — the
                # retired service already folded its leaked pumps into the
                # carryover at retire time
                svc.replica_id = idx
                self._services[idx] = svc
                self._health[idx] = fresh_health
            guard = getattr(getattr(svc, "engine", None), "_san", None)
            if guard is not None:
                guard.name = f"ContinuousBatchingEngine[r{idx}]"
            self._joined += 1
        if supervised:
            enable = getattr(svc, "enable_shadow_handoff", None)
            if enable is not None:
                enable()
        self._rederive_capacity()
        logger.info("replica %d joined the set at runtime", idx)
        try:
            get_metrics().record_replica_health(idx, HEALTH_HEALTHY)
            from sentio_tpu.infra.flight import get_flight_recorder

            get_flight_recorder().record_tick(
                event="replica_joined", replica=idx,
            )
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            logger.debug("replica join telemetry failed", exc_info=True)
        return idx

    def retire(self, idx: int, deadline_s: Optional[float] = None) -> dict:
        """Gracefully remove replica ``idx`` (scale-in / voluntary
        deregister): mark RETIRING (the router never selects it again),
        hand its never-dispatched inbox tickets to survivors through the
        quarantine handoff path (WFQ recharge — callers just wake with a
        survivor's result), drain in-flight work within ``deadline_s``
        (default ``rebuild_drain_s``; a delivered-token stream that the
        deadline cuts off resumes token-exact on a survivor via the normal
        resume path, costing the caller nothing), then close the service,
        park the slot RETIRED, release the registry slot, and re-derive
        WFQ capacity. Refuses to retire the last serving replica. Blocking
        (up to the drain deadline) — callers that must not stall ride the
        rebuild worker pool via the supervisor's deregister path."""
        deadline = (float(deadline_s) if deadline_s is not None
                    else self.rebuild_drain_s)
        with self._mutex:
            if self._closed:
                raise ReplicaUnavailable(
                    "replica set is closed", retry_after_s=1.0,
                    retryable=False,
                )
            if not (0 <= idx < len(self._health)):
                raise ValueError(f"no replica {idx} to retire")
            state = self._health[idx].state
            if state in (HEALTH_RETIRING, HEALTH_RETIRED):
                return {"replica": idx, "state": state, "retired": False}
            serving_others = sum(
                1 for i, h in enumerate(self._health)
                if i != idx and h.state in (HEALTH_HEALTHY, HEALTH_DEGRADED)
            )
            if serving_others == 0:
                raise ReplicaUnavailable(
                    f"cannot retire replica {idx}: no other serving "
                    "replica would remain", retry_after_s=5.0,
                    retryable=False,
                    details={"replica": idx, "reason": "last_serving"},
                )
        faults.hit("replica.retire")
        t0 = time.perf_counter()
        self._transition(idx, HEALTH_RETIRING, "scale-in")
        svc = self._services[idx]
        # queued-never-dispatched tickets move to survivors NOW — waiting
        # out the drain would add the whole deadline to their latency
        inbox: list = []
        try:
            inbox = svc.extract_inbox()
        except Exception:  # noqa: BLE001 — retire must complete regardless
            logger.exception("replica %d retire inbox extraction failed",
                             idx)
        self._handoff_inbox(idx, inbox)
        drained: dict = {}
        try:
            drained = svc.drain(deadline) or {}
        except Exception:  # noqa: BLE001 — drain is best-effort on retire
            logger.warning("replica %d retire drain failed", idx,
                           exc_info=True)
        if not getattr(svc, "closed", False):
            try:
                svc.close()
            except Exception:  # noqa: BLE001 — close every retiree regardless
                logger.warning("replica %d retire close failed", idx,
                               exc_info=True)
        leaked = getattr(svc, "pump_leaked_count", 0) or 0
        drain_s = time.perf_counter() - t0
        with self._mutex:
            self._retired += 1
            self._pump_leaked_carryover += leaked
            self._retire_drain_s.append(drain_s)
        self._transition(idx, HEALTH_RETIRED,
                         f"retired after {drain_s:.2f}s drain")
        release = self._release_slot
        if release is not None:
            try:
                release(idx)
            except Exception:  # noqa: BLE001 — slot release is best-effort
                logger.warning("registry slot %d release failed", idx,
                               exc_info=True)
        self._rederive_capacity()
        return {
            "replica": idx,
            "retired": True,
            "drain_s": round(drain_s, 3),
            "handed_off": len(inbox),
            "drained": drained.get("drained", True),
        }

    def _attach_new_members(self) -> None:
        """One supervisor-cadence poll of the membership source: wire every
        freshly registered worker into rotation. A single bad joiner must
        not block the pass (or its sibling joiners)."""
        source = self._membership_source
        if source is None:
            return
        try:
            fresh = source() or []
        except Exception:  # noqa: BLE001 — the supervisor must survive
            logger.exception("membership source poll failed")
            return
        for slot, svc in fresh:
            try:
                self.add_replica(svc, idx=slot)
            except Exception:  # noqa: BLE001 — one bad joiner, not the pass
                logger.exception("elastic join of slot %s failed", slot)
                try:
                    svc.close()
                except Exception:  # noqa: BLE001 — already on the error path
                    logger.debug("failed joiner cleanup failed",
                                 exc_info=True)

    def _enqueue_retire(self, idx: int) -> bool:
        """Hand one voluntary-deregister retire to the rebuild worker pool
        (False = no pool, caller retires inline). Reuses the rebuild
        in-flight latch so one worker slot is never queued twice."""
        if self._rebuild_q is None:
            return False
        with self._mutex:
            health = self._health[idx]
            if health.rebuild_inflight:
                return True  # already queued or running
            health.rebuild_inflight = True
        self._rebuild_q.put(("retire", idx))
        return True

    # ---------------------------------------------------------- supervision

    def _transition(self, idx: int, state: str, reason: str = "") -> bool:
        """Move replica ``idx`` to ``state`` (no-op if already there),
        emitting the flight-recorder event + health gauge + log line every
        operator surface shares. Returns whether a transition happened."""
        with self._mutex:
            health = self._health[idx]
            prev = health.state
            if prev == state:
                return False
            health.state = state
            health.since = time.perf_counter()
            health.last_reason = reason
        logger.warning("replica %d health %s -> %s (%s)",
                       idx, prev, state, reason or "n/a")
        try:  # telemetry is best-effort; supervision must not die on it
            get_metrics().record_replica_health(idx, state)
            from sentio_tpu.infra.flight import get_flight_recorder

            get_flight_recorder().record_tick(
                event="replica_health", replica=idx,
                state_from=prev, state_to=state, reason=reason[:200],
            )
        except Exception:  # noqa: BLE001
            logger.debug("health transition telemetry failed", exc_info=True)
        return True

    def _note_success(self, idx: int, svc=None) -> None:
        with self._mutex:
            if idx >= len(self._health):
                return
            if svc is not None and self._services[idx] is not svc:
                return  # slot was rebuilt under this request; stale sample
            self._health[idx].outcomes.append((time.perf_counter(), True))

    def _note_failure(self, idx: int, exc: BaseException, svc=None) -> None:
        """Caller-observed replica-infrastructure failure: feed the breaker
        window and, when the service has LATCHED broken (reset failed — it
        can never recover by itself), quarantine immediately instead of
        waiting for the next supervisor pass; by backlog a corpse looks
        least-loaded, so every poll-interval of delay re-routes live
        traffic into it. ``svc`` is the service object the caller actually
        talked to: if the slot has since been rebuilt (swap under _mutex),
        the outcome belongs to the DEAD incarnation and is dropped — a
        straggler's failure must not demote the fresh replica."""
        now = time.perf_counter()
        with self._mutex:
            if self._closed or idx >= len(self._health):
                return  # shutdown churn is not a health signal
            current = self._services[idx]
            if svc is not None and current is not svc:
                return  # failure observed on a replaced incarnation
            health = self._health[idx]
            health.outcomes.append((now, False))
            state = health.state
        if state in (HEALTH_QUARANTINED, HEALTH_REBUILDING,
                     HEALTH_RETIRING, HEALTH_RETIRED):
            return
        if getattr(current, "broken", False) or getattr(current, "closed",
                                                        False):
            self._quarantine(idx, f"replica latched unavailable: {exc}")

    def _quarantine(self, idx: int, reason: str, stalled: bool = False) -> None:
        now = time.perf_counter()
        with self._mutex:
            health = self._health[idx]
            if health.state in (HEALTH_QUARANTINED, HEALTH_REBUILDING,
                                HEALTH_RETIRING, HEALTH_RETIRED):
                # a retiring replica is already leaving gracefully — its
                # drain/close supersedes any quarantine the breaker or a
                # caller might race in
                return
            health.quarantined_at = now
            health.rebuild_attempts = 0
            # first rebuild try is immediate (next supervisor pass); the
            # exponential backoff applies to FAILED rebuild attempts
            health.next_rebuild_at = now
            if stalled:
                self._stall_quarantines += 1
        self._transition(idx, HEALTH_QUARANTINED, reason)
        svc = self._services[idx]
        inbox: list = []
        if stalled:
            # a wedged pump cannot be killed: abandon the engine+service
            # outright — admitted tickets fail typed (their KV dies with
            # the wedged engine; callers fail over), inbox tickets hand off
            try:
                inbox = svc.abandon(reason)
            except Exception:  # noqa: BLE001 — quarantine must complete
                logger.exception("replica %d abandon failed", idx)
        else:
            # breaker quarantine of a WORKING replica: in-flight work gets
            # the rebuild's drain grace, but queued-never-dispatched
            # tickets would otherwise sit out the whole rebuild — move them
            try:
                inbox = svc.extract_inbox()
            except Exception:  # noqa: BLE001
                logger.exception("replica %d inbox extraction failed", idx)
        self._handoff_inbox(idx, inbox)

    def _handoff_inbox(self, idx: int, tickets: list) -> None:
        """Quarantine inbox handoff: re-admit the dead replica's
        never-dispatched tickets directly to surviving replicas instead of
        leaving them to ride each caller's failover loop (which only fires
        after the caller OBSERVES a failure — for a queued ticket that
        means waiting out its full deadline). Each ticket's WFQ reservation
        is released and re-charged (``TenantFairQueue.recharge``); a ticket
        no survivor can take fails with the typed error the caller's
        failover budget is NOT billed for — the ticket object itself moves,
        so the blocked caller just wakes with a result (or a typed
        error)."""
        if not tickets:
            return
        moved = 0
        for ticket in tickets:
            exc: Optional[Exception] = None
            if ticket.tenant is not None:
                try:
                    self.tenants.recharge(
                        ticket.tenant, ticket.cost_tokens,
                        priority=ticket.priority or PRIORITY_INTERACTIVE,
                    )
                except ServiceOverloaded as shed:
                    exc = shed
            if exc is None:
                try:
                    eligible = self._eligible(exclude=frozenset({idx}))
                    target = self._least_loaded(eligible)
                    self._services[target].adopt(ticket)
                    moved += 1
                    continue
                except Exception as adopt_exc:  # noqa: BLE001 — typed below
                    exc = adopt_exc
            if not isinstance(exc, SentioError):
                # the caller blocked on this ticket must never see an
                # untyped infrastructure error
                exc = ReplicaUnavailable(
                    f"inbox handoff failed: {exc}", retry_after_s=2.0,
                    details={"replica": idx},
                )
            self._finish_handoff_ticket(ticket, exc)
        with self._mutex:
            self._handed_off += moved
        logger.warning("replica %d quarantine: %d/%d inbox tickets handed "
                       "off to survivors", idx, moved, len(tickets))
        try:
            from sentio_tpu.infra.flight import get_flight_recorder

            get_flight_recorder().record_tick(
                event="inbox_handoff", replica=idx,
                handed_off=moved, failed=len(tickets) - moved,
            )
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            logger.debug("handoff telemetry failed", exc_info=True)

    @staticmethod
    def _finish_handoff_ticket(ticket, exc: Exception) -> None:
        """Terminal typed outcome for a ticket no survivor could adopt.
        The ticket was extracted from its dead service's inbox, so this
        thread owns it exclusively — no service lock applies; the shared
        sequence in runtime/service.py keeps this path byte-identical to
        the normal in-service error path."""
        finish_ticket_error(ticket, exc, "failed_over")

    def _prune_locked(self, series: deque, now: float) -> None:  # lock-held: _mutex
        assert_held(self._mutex)
        horizon = now - self.breaker_window_s
        while series and series[0][0] < horizon:
            series.popleft()

    def _supervise_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self._supervise_once()
            except Exception:  # noqa: BLE001 — the supervisor must survive
                logger.exception("replica supervision pass failed")

    def _supervise_once(self) -> None:
        """One breaker + rebuild pass over every replica (also directly
        callable by tests for deterministic stepping). Breakers for ALL
        replicas are evaluated BEFORE any rebuild runs: a rebuild is
        seconds-to-minutes of drain + compile, and a sibling replica's trip
        must not wait behind it within the pass (it still waits between
        passes — the supervisor is one thread; see ROADMAP)."""
        now = time.perf_counter()
        # elastic joins first: a freshly registered worker should be in
        # rotation before this pass evaluates breakers (it may be the
        # survivor a handoff needs)
        self._attach_new_members()
        rebuild_ready: list[int] = []
        retire_ready: list[int] = []
        for idx in range(len(self._services)):
            svc = self._services[idx]
            with self._mutex:
                health = self._health[idx]
                state = health.state
                if state in (HEALTH_RETIRING, HEALTH_RETIRED):
                    continue
                if state in (HEALTH_HEALTHY, HEALTH_DEGRADED):
                    # tick-failure burst: fold counter growth into the
                    # window (each increment is one failed decode tick)
                    count = None
                    try:
                        count = svc.tick_failure_count
                    except Exception:  # noqa: BLE001 — service mid-swap
                        pass
                    if count is not None:
                        for _ in range(max(count - health.ticks_seen, 0)):
                            health.tick_fails.append((now, False))
                        health.ticks_seen = max(count, health.ticks_seen)
                    self._prune_locked(health.tick_fails, now)
                    self._prune_locked(health.outcomes, now)
                    burst = len(health.tick_fails)
                    fails = sum(1 for _, ok in health.outcomes if not ok)
                    samples = len(health.outcomes)
                rebuild_due = (state == HEALTH_QUARANTINED
                               and now >= health.next_rebuild_at
                               and not health.rebuild_inflight)
            if state in (HEALTH_HEALTHY, HEALTH_DEGRADED) and \
                    getattr(svc, "deregister_requested", None):
                # voluntary deregister frame observed: queue a graceful
                # retire (pool-side — the drain deadline must never stall
                # this detection pass)
                retire_ready.append(idx)
            if state in (HEALTH_QUARANTINED, HEALTH_REBUILDING):
                # zero the heartbeat gauge for out-of-rotation replicas:
                # left at its last (over-budget) value it would keep the
                # stall alert firing for the whole rebuild, making
                # "watchdog acted" indistinguishable from "watchdog dead"
                try:
                    get_metrics().record_heartbeat_age(idx, 0.0)
                except Exception:  # noqa: BLE001 — telemetry best-effort
                    pass
                if rebuild_due:
                    rebuild_ready.append(idx)
                continue
            # ---- stall watchdog (detection only — recovery is the normal
            # quarantine → abandon → rebuild path). A pump wedged inside a
            # device dispatch raises nothing and latches nothing; the only
            # observable is a stale heartbeat WITH pending work, so this
            # check needs no exception to fire.
            budget = getattr(svc, "tick_stall_budget_s", 0.0) or 0.0
            age = None
            if budget > 0:
                try:
                    age = svc.heartbeat_age()
                except Exception:  # noqa: BLE001 — service mid-swap
                    pass
            try:
                get_metrics().record_heartbeat_age(
                    idx, age if age is not None else 0.0)
                # duty cycle rides the same supervisor cadence, so the
                # host/device/idle gauge stays fresh between scrapes
                get_metrics().record_duty_cycle(idx, svc.duty_cycle())
                # telemetry freshness gauge (process/socket replicas only —
                # duck-typed so thread services stay untouched): seconds
                # since the last ACCEPTED worker telemetry frame. The alert
                # joins this against replica health: stale telemetry on a
                # HEALTHY worker means the observability plane itself broke
                tel_age = getattr(svc, "telemetry_age", None)
                if callable(tel_age):
                    age_t = tel_age()
                    if age_t is not None:
                        get_metrics().record_telemetry_age(idx, age_t)
            except Exception:  # noqa: BLE001 — telemetry best-effort
                pass
            if age is not None and age > budget:
                try:
                    from sentio_tpu.infra.flight import get_flight_recorder

                    get_flight_recorder().record_tick(
                        event="pump_stall", replica=idx,
                        heartbeat_age_s=round(age, 3), budget_s=budget,
                    )
                except Exception:  # noqa: BLE001
                    logger.debug("stall telemetry failed", exc_info=True)
                self._quarantine(
                    idx,
                    f"pump stalled: heartbeat {age:.1f}s old with pending "
                    f"work (budget {budget:.0f}s)",
                    stalled=True,
                )
                continue
            if getattr(svc, "broken", False):
                self._quarantine(idx, "engine latched broken (reset failed)")
            elif burst >= self.breaker_tick_failures:
                self._quarantine(
                    idx, f"{burst} tick failures inside "
                         f"{self.breaker_window_s:.0f}s window")
            elif (samples >= self.breaker_min_samples
                  and fails / samples >= self.breaker_error_rate):
                self._quarantine(
                    idx, f"error rate {fails}/{samples} over "
                         f"{self.breaker_window_s:.0f}s window")
            elif fails > 0 or burst > 0:
                self._transition(
                    idx, HEALTH_DEGRADED,
                    f"{fails} caller failures / {burst} tick failures "
                    "in window")
            elif state == HEALTH_DEGRADED:
                self._transition(idx, HEALTH_HEALTHY, "window clean")
        for idx in rebuild_ready:
            if self._stop.is_set():
                break
            if not self._enqueue_rebuild(idx):
                # no worker pool (supervise=False test mode): rebuild
                # inline so deterministic _supervise_once stepping keeps
                # its synchronous contract
                self._rebuild(idx)
        for idx in retire_ready:
            if self._stop.is_set():
                break
            with self._mutex:
                serving_others = sum(
                    1 for i, h in enumerate(self._health)
                    if i != idx
                    and h.state in (HEALTH_HEALTHY, HEALTH_DEGRADED))
            if serving_others == 0:
                # the last serving replica asked to leave: hold the retire
                # until a sibling joins (debug — this re-evaluates every
                # pass and must not spam operator logs)
                logger.debug("replica %d deregister held: last serving "
                             "replica", idx)
                continue
            if not self._enqueue_retire(idx):
                try:
                    self.retire(idx)
                except Exception:  # noqa: BLE001 — the pass must survive
                    logger.exception("replica %d deregister retire failed",
                                     idx)

    def _enqueue_rebuild(self, idx: int) -> bool:
        """Hand one due rebuild to the worker pool (False = no pool, run
        inline). Marks the replica's rebuild in-flight so the next
        detection pass — which keeps running at the probe cadence while
        workers rebuild — does not enqueue it twice."""
        if self._rebuild_q is None:
            return False
        with self._mutex:
            health = self._health[idx]
            if health.rebuild_inflight:
                return True  # already queued or running
            health.rebuild_inflight = True
        self._rebuild_q.put(idx)
        return True

    def _rebuild_worker(self) -> None:
        """One bounded-pool rebuild worker: detection (supervisor) cadence
        is decoupled from rebuild duration — a minutes-long (or wedged)
        rebuild occupies a worker, not the supervisor's breaker pass."""
        while not self._stop.is_set():
            try:
                item = self._rebuild_q.get(timeout=0.25)
            except _queue.Empty:
                continue
            if item is None:
                return  # shutdown sentinel
            if isinstance(item, tuple) and item[0] == "retire":
                # voluntary-deregister retire rides the same bounded pool:
                # the drain deadline occupies a worker, not the supervisor
                idx = item[1]
                try:
                    self.retire(idx)
                except Exception:  # noqa: BLE001 — the pool must survive
                    logger.exception("replica %d retire crashed on worker",
                                     idx)
                finally:
                    with self._mutex:
                        if idx < len(self._health):
                            self._health[idx].rebuild_inflight = False
                continue
            idx = item
            try:
                self._rebuild(idx)
            except Exception:  # noqa: BLE001 — the pool must survive
                logger.exception("replica %d rebuild crashed on worker", idx)

    def _rebuild(self, idx: int) -> bool:
        """In-place rebuild of a quarantined replica: fresh engine + pool +
        radix + pump from the shared weights, re-warmed, then swapped back
        into rotation. Runs on the supervisor thread (or a test driver) —
        never under ``_mutex``, since it compiles and decodes.

        Process-mode replicas (runtime/worker.py) duck-type the rebuild: a
        replica exposing ``respawn()`` is rebuilt by SPAWNING A FRESH WORKER
        PROCESS from the same spec instead of constructing an in-process
        engine+service — the backoff, warm-before-swap, and health
        bookkeeping are identical either way."""
        with self._mutex:
            attempt = self._health[idx].rebuild_attempts + 1
            self._health[idx].rebuild_inflight = True
        self._transition(idx, HEALTH_REBUILDING, f"rebuild attempt {attempt}")
        fresh = None
        try:
            faults.hit("replica.rebuild")
            old = self._services[idx]
            if not getattr(old, "closed", False):
                try:
                    # error-rate quarantines leave a WORKING service: give
                    # its in-flight callers a bounded window to finish
                    # before the swap orphans them. An ABANDONED (stalled)
                    # service has no pending tickets left, so this returns
                    # immediately and close()'s join — bounded by the drain
                    # deadline's remainder — counts the wedged pump leaked
                    old.drain(self.rebuild_drain_s)
                except Exception:  # noqa: BLE001 — drain is best-effort
                    logger.warning("replica %d pre-rebuild drain failed",
                                   idx, exc_info=True)
            respawn = getattr(old, "respawn", None)
            if respawn is not None:
                # process mode: the dead worker is reaped (drain → close
                # above SIGKILLs stragglers) and a fresh process takes the
                # slot; its cold compiles happen in the WORKER, outside the
                # router's compile fence
                fresh = respawn()
            else:
                engine = old.engine.spawn_fresh()
                guard = getattr(engine, "_san", None)
                if guard is not None:
                    guard.name = f"ContinuousBatchingEngine[r{idx}]"
                fresh = PagedGenerationService(
                    engine,
                    default_timeout_s=old.default_timeout_s,
                    max_queue=old.max_queue,
                    default_deadline_s=old.default_deadline_s,
                    retry_budget=old.retry_budget,
                    replica_id=idx,
                    tick_stall_budget_s=old.tick_stall_budget_s,
                    warmup_budget_s=getattr(old, "warmup_budget_s", 600.0),
                )
            self._warm_rebuilt(fresh)
            if self._stop.is_set():
                # the set is shutting down: never swap a live pump into a
                # closing rotation
                fresh.close()
                return False
            # the old incarnation leaves rotation: carry its leaked-pump
            # count (the wedged pump a stall abandonment left behind) so
            # the set's summed pump_leaked never silently shrinks
            leaked = old.pump_leaked_count
            with self._mutex:
                # baselined cross-thread-race: the ONLY _services mutation,
                # and it holds _mutex; the list is deliberately un-annotated
                # because readers take lock-free GIL-atomic snapshots
                # (router hot path — see the header comment on _route)
                self._services[idx] = fresh
                self._pump_leaked_carryover += leaked
                health = self._health[idx]
                health.outcomes.clear()
                health.tick_fails.clear()
                health.ticks_seen = 0
                health.rebuild_attempts = 0
                health.rebuilds += 1
            self._transition(idx, HEALTH_HEALTHY, "rebuilt in place")
            return True
        except Exception as exc:  # noqa: BLE001 — rebuild retries on backoff
            logger.exception("replica %d rebuild failed", idx)
            if fresh is not None:
                # the half-built service never entered rotation: close it
                # (pump + engine pool), or every failed attempt would stack
                # another live KV pool until the device OOMs
                try:
                    fresh.close()
                except Exception:  # noqa: BLE001 — already on the error path
                    logger.warning("replica %d failed-rebuild cleanup "
                                   "failed", idx, exc_info=True)
            now = time.perf_counter()
            with self._mutex:
                health = self._health[idx]
                health.rebuild_attempts += 1
                # exponential backoff per failed attempt; attempts past the
                # rebuild budget idle at the max backoff (keep trying, slowly)
                if health.rebuild_attempts > self.rebuild_budget:
                    backoff = 60.0
                else:
                    backoff = min(
                        self.quarantine_backoff_s
                        * (2.0 ** (health.rebuild_attempts - 1)),
                        60.0,
                    )
                health.next_rebuild_at = now + backoff
            self._transition(idx, HEALTH_QUARANTINED,
                             f"rebuild failed: {exc}")
            return False
        finally:
            with self._mutex:
                if idx < len(self._health):
                    self._health[idx].rebuild_inflight = False

    def _warm_rebuilt(self, fresh: PagedGenerationService) -> None:
        """Warm a rebuilt replica before it re-enters rotation. Under an
        ARMED compile fence the full warmup sweep runs with the NEW
        engine's FamilyFn instances marked fence-exempt — its cold compiles
        are expected and scoped to this rebuild, while a steady-state
        recompile on any sibling replica still trips the fence throughout.
        Without an armed fence a smoke generation suffices (later compiles
        are legal, just slow)."""
        from sentio_tpu.analysis.audit import fence

        if fence.enabled() and fence.is_armed():
            fresh.engine.set_fence_exempt(True)
            try:
                fresh.warmup()
            finally:
                fresh.engine.set_fence_exempt(False)
        else:
            result = fresh.generate("replica rebuild smoke probe",
                                    max_new_tokens=2, temperature=0.0,
                                    deadline_s=0, timeout_s=120.0)
            if result.finish_reason == "error":
                raise RuntimeError("rebuilt replica failed its smoke probe")

    def health_summary(self) -> dict:
        """Set-level health for ``/health``: ``healthy`` while every replica
        is HEALTHY, ``degraded`` while at least one replica can serve
        (HEALTHY or DEGRADED — k8s must keep routing to a half-alive pod,
        not restart it), ``unhealthy`` only at zero serving replicas."""
        with self._mutex:
            # RETIRED slots left the fleet on purpose: they are invisible
            # here (a retired worker must read as "never existed") except
            # through the retired counter; RETIRING replicas stay visible
            # — they are draining, which an operator should see
            replicas = [
                {
                    "replica": i,
                    "state": h.state,
                    "since_s": round(time.perf_counter() - h.since, 1),
                    "rebuilds": h.rebuilds,
                    **({"reason": h.last_reason} if h.last_reason else {}),
                }
                for i, h in enumerate(self._health)
                if h.state != HEALTH_RETIRED
            ]
            joined, retired = self._joined, self._retired
        serving = sum(1 for r in replicas
                      if r["state"] in (HEALTH_HEALTHY, HEALTH_DEGRADED))
        healthy = sum(1 for r in replicas if r["state"] == HEALTH_HEALTHY)
        if healthy == len(replicas):
            status = "healthy"
        elif serving >= 1:
            status = "degraded"
        else:
            status = "unhealthy"
        return {
            "status": status,
            "healthy_replicas": healthy,
            "serving_replicas": serving,
            "total_replicas": len(replicas),
            "joined_replicas": joined,
            "retired_replicas": retired,
            "replicas": replicas,
        }

    # ------------------------------------------------------------ lifecycle

    def _stop_supervisor(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        supervisor = self._supervisor
        if supervisor is not None and supervisor.is_alive():
            supervisor.join(timeout=timeout_s)
            if supervisor.is_alive():
                # a rebuild mid-flight can outlive the join window; it
                # checks _stop before swapping and closes its fresh
                # service, so the straggler is bounded — surface it
                logger.warning(
                    "replica supervisor did not exit within %.0fs "
                    "(rebuild in flight?)", timeout_s,
                )
        if self._rebuild_q is not None:
            for _ in self._rebuild_pool:
                self._rebuild_q.put(None)  # wake idle workers immediately
            for t in self._rebuild_pool:
                if t.is_alive():
                    t.join(timeout=timeout_s)
                    if t.is_alive():
                        # a worker wedged inside a stalled rebuild cannot
                        # be killed — it checks _stop before swapping, so
                        # abandoning it is bounded; surface the leak
                        logger.warning(
                            "rebuild worker %s did not exit within %.0fs "
                            "(stalled rebuild?)", t.name, timeout_s,
                        )

    def warmup(self, max_new_tokens: int = 4) -> dict:
        """Warm EVERY replica CONCURRENTLY (each compiles its own jit
        variants over its own pool/mesh slice, so serial warmup would
        multiply startup by N) before the compile fence arms — serve
        startup arms the fence only after this returns, i.e. after all
        replicas report. A failed replica warmup re-raises: arming the
        fence over an unwarmed replica would fail its first real request."""
        results: list = [None] * len(self._services)
        errors: list = []

        def _warm(i: int, svc: PagedGenerationService) -> None:
            try:
                results[i] = svc.warmup(max_new_tokens=max_new_tokens)
            except Exception as exc:  # noqa: BLE001 — re-raised below
                errors.append(exc)

        threads = [
            threading.Thread(target=_warm, args=(i, svc),
                             name=f"replica-warmup-{i}", daemon=True)
            for i, svc in enumerate(self._services)
        ]
        for t in threads:
            t.start()
        for t in threads:
            # each replica warmup bounds its own generations; the join only
            # outwaits that, never blocks startup forever on a wedged pump
            t.join(timeout=max(svc.default_timeout_s
                               for svc in self._services) + 120.0)
        if errors:
            raise errors[0]
        return {
            "prompts": sum(r.get("prompts", 0) for r in results),
            "xla_compiles": sum(r.get("xla_compiles", 0) for r in results),
            "replicas": len(self._services),
        }

    def drain(self, deadline_s: float = 30.0) -> dict:
        """Drain all replicas CONCURRENTLY: each gets the same wall-clock
        window (draining serially would give replica k only the deadline
        minus its predecessors' spend). Aggregates drained/abandoned. The
        supervisor stops FIRST so a mid-drain rebuild cannot swap a fresh
        pump into a rotation that is shutting down."""
        self._stop_supervisor()
        with self._mutex:
            # RETIRED replicas already drained + closed at retire time:
            # draining them again would only log spurious failures
            live = [(i, self._services[i])
                    for i, h in enumerate(self._health)
                    if h.state != HEALTH_RETIRED]
        results: dict[int, Optional[dict]] = {i: None for i, _svc in live}

        def _drain(i: int, svc: PagedGenerationService) -> None:
            try:
                results[i] = svc.drain(deadline_s)
            except Exception:  # noqa: BLE001 — drain is best-effort
                logger.warning("replica %d drain failed", i, exc_info=True)

        threads = [
            threading.Thread(target=_drain, args=(i, svc),
                             name=f"replica-drain-{i}", daemon=True)
            for i, svc in live
        ]
        for t in threads:
            t.start()
        for t in threads:
            # each replica's drain bounds itself by deadline_s; the grace
            # covers close()'s pump join, not extra drain time
            t.join(timeout=deadline_s + 15.0)
        per = []
        for i, svc in live:
            res = results[i]
            if res is None:
                try:
                    backlog = svc.backlog()
                except Exception:  # noqa: BLE001 — replica mid-close
                    backlog = 0
                res = {"drained": False, "abandoned": backlog}
            per.append({"replica": i, **res})
        with self._mutex:
            # every replica's drain ends in close(): the set is done — later
            # submits get the non-retryable closed-set error instead of
            # failover churn against corpses
            self._closed = True
        return {
            "drained": all(r["drained"] for r in per),
            "abandoned": sum(r.get("abandoned", 0) for r in per),
            "replicas": per,
        }

    def close(self) -> None:
        self._stop_supervisor()
        with self._mutex:
            self._closed = True
        for svc in self._services:
            if getattr(svc, "closed", False):
                continue  # retired replicas closed at retire time
            try:
                svc.close()
            except Exception:  # noqa: BLE001 — close every replica regardless
                logger.warning("replica %d close failed", svc.replica_id,
                               exc_info=True)

    # ---------------------------------------------------------------- stats

    _SUM_KEYS = (
        "active_slots", "max_slots", "queued", "free_pages", "total_pages",
        "pool_hbm_bytes", "head_skips", "ttft_count", "prefill_tokens",
        "decode_tokens", "prefix_hits", "prefix_misses", "prefix_hit_tokens",
        "prefix_miss_tokens", "prefix_cache_pages", "prefix_cache_nodes",
        "queued_inbox", "ticks", "completed", "max_queue", "shed", "expired",
        "cancelled", "requeued", "tick_failures", "pump_leaked",
        "spec_verifies", "spec_emitted", "stale_frames",
        "worker_reconnects",
    )
    _MAX_KEYS = ("max_active_slots", "draining")

    def stats(self) -> dict:
        """Aggregate + per-replica stats. Counters SUM over replicas exactly
        once each (every per-replica total appears in exactly one replica's
        stats, so the sum cannot double-count — the leaked-pump audit relies
        on this); high-water marks take the max; percentile-ish telemetry
        (ttft_p50/p95, avg occupancy) is weighted by each replica's sample
        count and labeled by construction as an approximation."""
        with self._mutex:
            # RETIRED slots are closed (a stats RPC against a reaped worker
            # would fail anyway) and must read as "never existed": only
            # live membership aggregates
            live = [self._services[i] for i, h in enumerate(self._health)
                    if h.state != HEALTH_RETIRED]
        per = []
        agg: dict = {}
        for svc in live:
            try:
                s = svc.stats()
            except Exception:  # noqa: BLE001 — a replica mid-retire/rebuild
                logger.debug("replica %d stats unavailable",
                             getattr(svc, "replica_id", -1), exc_info=True)
                continue
            per.append(s)
            for key in self._SUM_KEYS:
                if key in s:
                    agg[key] = agg.get(key, 0) + s[key]
            for key in self._MAX_KEYS:
                if key in s:
                    agg[key] = max(agg.get(key, 0), s[key])
        if not per:
            per = [{}]
        ticks = agg.get("ticks", 0)
        if ticks:
            agg["avg_active_slots"] = round(
                sum(s.get("avg_active_slots", 0.0) * s.get("ticks", 0)
                    for s in per) / ticks, 3,
            )
        else:
            agg["avg_active_slots"] = 0.0
        hit = agg.get("prefix_hit_tokens", 0)
        miss = agg.get("prefix_miss_tokens", 0)
        if hit + miss:
            agg["prefix_hit_token_ratio"] = round(hit / (hit + miss), 4)
        ttft_n = sum(s.get("ttft_count", 0) for s in per
                     if "ttft_p50_ms" in s)
        if ttft_n:
            for key in ("ttft_p50_ms", "ttft_p95_ms"):
                agg[key] = round(
                    sum(s[key] * s.get("ttft_count", 0) for s in per
                        if key in s) / ttft_n, 2,
                )
        spec_v = agg.get("spec_verifies", 0)
        if spec_v:
            agg["spec_tokens_per_verify"] = round(
                agg.get("spec_emitted", 0) / spec_v, 2)
        # tick-phase attribution (infra/phases.py): phase seconds sum
        # across replicas; the set-level duty cycle is summed busy time
        # over summed wall time — i.e. the per-replica AVERAGE split (the
        # per-replica rows below keep the individual gauges honest)
        phase_totals, duty_elapsed = sum_phase_totals(per)
        if duty_elapsed > 0:
            agg["phase_seconds"] = {k: round(v, 6)
                                    for k, v in phase_totals.items()}
            agg["duty_elapsed_s"] = round(duty_elapsed, 6)
            agg["duty_cycle"] = duty_fractions(phase_totals, duty_elapsed)
        first = per[0]
        agg["page_size"] = first.get("page_size")
        agg["kv_quant"] = first.get("kv_quant")
        agg["n_replicas"] = len(per)
        agg["replicas"] = per
        with self._mutex:
            agg["routing"] = {
                "affinity": self._routed_affinity,
                "least_loaded": self._routed_load,
                "affinity_overflow": self._affinity_overflow,
            }
            agg["failovers"] = self._failovers
            # stall tolerance: tickets moved at quarantine, stall-triggered
            # quarantines, and leaked pumps whose service incarnation a
            # rebuild already replaced (summed pump_leaked above only sees
            # the CURRENT incarnations)
            agg["handed_off"] = self._handed_off
            agg["stall_quarantines"] = self._stall_quarantines
            agg["pump_leaked"] = (
                agg.get("pump_leaked", 0) + self._pump_leaked_carryover
            )
            # resumable streams: successful mid-flight splices, delivered
            # tokens replayed for them, and resumes that ran out of budget
            agg["stream_resumes"] = self._stream_resumes
            agg["resume_replayed_tokens"] = self._resume_replayed_tokens
            agg["resume_exhausted"] = self._resume_exhausted
            # elastic fleet: runtime joins/retires and the graceful-drain
            # latency distribution scale-in decisions pay
            drains = sorted(self._retire_drain_s)
            agg["fleet"] = {
                "live_replicas": len(live),
                "joined": self._joined,
                "retired": self._retired,
                **({
                    "retire_drain_p95_s": round(
                        drains[min(int(len(drains) * 0.95),
                                   len(drains) - 1)], 3),
                } if drains else {}),
            }
        agg["tenants"] = self.tenants.stats()
        agg["health"] = self.health_summary()
        return agg
