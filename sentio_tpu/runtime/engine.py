"""GeneratorEngine: the in-process LLM serving runtime.

This is what replaces the reference's four HTTP process boundaries
(SURVEY.md §3.1): the model lives on the mesh, loaded ONCE at startup
(inverting the reference's lazy first-request graph init, chat.py:38-87
there), and requests become device dispatches:

* **prefill** — bucketed prompt lengths ([B, bucket] padded), one compiled
  program per (batch, bucket) pair, aligned cache write at slot 0;
* **decode** — single fused step: forward(T=1) → sample → append, with
  per-row positions/cache offsets (ragged batches from the coalescer);
* **stream** — the host loop yields tokens as they land, feeding SSE.

Two loops are provided: a host-stepped loop (streaming, early EOS exit) and
a fully-jitted ``lax.while_loop`` bulk loop (no host round-trips — the bench
path). Weights are TP-sharded via parallel/sharding rules when a mesh is
given; the KV cache shards batch-on-dp / heads-on-tp from the same mesh.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterator, Optional, Sequence

import numpy as np

from sentio_tpu.analysis.audit.registry import jit_family
from sentio_tpu.config import GeneratorConfig, get_settings
from sentio_tpu.models.llama import LlamaConfig
from sentio_tpu.parallel.batcher import bucket_size


@dataclass
class GenerationResult:
    text: str
    tokens: list[int]
    prompt_tokens: int
    finish_reason: str  # "stop" | "length"
    latency_ms: float = 0.0


class GeneratorEngine:
    PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048, 4096)
    BATCH_BUCKETS = (1, 2, 4, 8, 16)

    def __init__(
        self,
        config: Optional[GeneratorConfig] = None,
        model_config: Optional[LlamaConfig] = None,
        params=None,
        tokenizer=None,
        mesh=None,
        rng_seed: int = 0,
        forward_fn=None,
        sharding_rules=None,
    ) -> None:
        """``forward_fn`` swaps the model family behind the serving seams:
        any fn with ``llama_forward``'s (params, cfg, ids, positions, cache,
        cache_index, pad_mask, attn_fn) → (logits, cache) contract — e.g.
        ``models.moe.moe_serving_forward`` for expert-routed checkpoints
        (pair it with ``sharding_rules=MOE_EP_RULES`` under a mesh)."""
        import jax

        from sentio_tpu.models.llama import init_llama
        from sentio_tpu.models.tokenizer import ByteTokenizer

        self.config = config or get_settings().generator
        explicit_params = params
        from_checkpoint = False
        if params is None and self.config.checkpoint_path:
            # real weights: a `cli convert` checkpoint + HF tokenizer; the
            # family rides the checkpoint meta (llama or moe) and the
            # matching forward_fn is auto-selected from the restored config
            from sentio_tpu.runtime.weights import WeightsError, load_model

            params, model_config, ck_tok = load_model(
                self.config.checkpoint_path,
                tokenizer_path=self.config.tokenizer_path,
            )
            if not isinstance(model_config, LlamaConfig):
                raise WeightsError(
                    f"checkpoint {self.config.checkpoint_path!r} holds a "
                    f"{type(model_config).__name__} model — the generator "
                    "engine serves decoder families (llama, moe)"
                )
            tokenizer = tokenizer or ck_tok
            from_checkpoint = True
        self.model_config = model_config or (
            LlamaConfig.tiny() if self.config.model_preset == "tiny" else LlamaConfig.llama3_8b()
        )
        self.tokenizer = tokenizer or ByteTokenizer(self.model_config.vocab_size)
        self.mesh = mesh
        from sentio_tpu.models.llama import llama_forward
        from sentio_tpu.models.moe import MoeConfig, moe_serving_forward

        is_moe = isinstance(self.model_config, MoeConfig)
        if params is None:
            # random-init at the config's family (the fake-model test mode)
            if is_moe:
                from sentio_tpu.models.moe import init_moe

                params = init_moe(jax.random.PRNGKey(rng_seed), self.model_config)
            else:
                params = init_llama(jax.random.PRNGKey(rng_seed), self.model_config)
        if mesh is not None:
            from sentio_tpu.parallel.sharding import (
                LLAMA_TP_RULES,
                MOE_EP_RULES,
                shard_params,
            )

            default_rules = MOE_EP_RULES if is_moe else LLAMA_TP_RULES
            rules = sharding_rules if sharding_rules is not None else default_rules
            params = shard_params(params, mesh, rules)
        self.params = params
        if forward_fn is None:
            forward_fn = moe_serving_forward if is_moe else llama_forward
        elif forward_fn in (moe_serving_forward, llama_forward):
            # the two in-tree families are cheap to cross-check
            if (forward_fn is moe_serving_forward) != is_moe:
                raise ValueError(
                    f"forward_fn {forward_fn.__name__} does not match the "
                    f"{type(self.model_config).__name__} model family"
                )
        elif explicit_params is None and not from_checkpoint:
            # a custom family's fn against default-initialized params would
            # KeyError deep inside jit
            raise ValueError(
                "forward_fn overrides the model family; pass matching params "
                "explicitly (the default init builds the config family's tree)"
            )
        self.forward_fn = forward_fn
        self._rng = jax.random.PRNGKey(rng_seed + 17)
        self._build_fns()

    # ------------------------------------------------------------- compiled fns

    def _build_fns(self) -> None:
        import jax
        import jax.numpy as jnp

        from sentio_tpu.runtime.sampling import sample_tokens

        llama_forward = self.forward_fn  # model-family seam (see __init__)

        cfg = self.model_config
        # Pallas flash attention for the prefill pass (the multi-token causal
        # block); decode (T=1) keeps the fused XLA path. Under a mesh the
        # kernel runs INSIDE shard_map: heads on tp (matching the wq/wk/wv
        # column sharding), ring attention over sp for sequence-parallel
        # long-context prefill.
        from sentio_tpu.kernels import default_attn_fn, make_mesh_attn_fn

        if self.mesh is None:
            attn_fn = default_attn_fn()
        elif jax.default_backend() != "tpu":
            attn_fn = None  # CPU test meshes: XLA attention under GSPMD
        else:
            base_fn = make_mesh_attn_fn(self.mesh)

            def attn_fn(q, k, v, kv_lens=None):
                import jax.numpy as jnp

                from sentio_tpu.models import layers as L

                try:
                    return base_fn(q, k, v, kv_lens)
                except ValueError:  # indivisible head/seq shapes → XLA path
                    mask = L.causal_mask(q.shape[1])
                    if kv_lens is not None:
                        key_ok = jnp.arange(k.shape[1])[None, :] < kv_lens[:, None]
                        mask = mask & key_ok[:, None, None, :]
                    return L.attention(q, k, v, mask, q.dtype)

        self._attn_fn = attn_fn  # exposed for the speculative decoder

        @jit_family("engine.prefill")
        def prefill(params, ids, positions, cache, pad_mask):
            # pad_mask marks real (row, token) cells: llama ignores it on the
            # cache path, routed families (MoE) need it so padding claims no
            # expert capacity
            logits, cache = llama_forward(
                params, cfg, ids, positions=positions, cache=cache, cache_index=0,
                pad_mask=pad_mask, attn_fn=attn_fn,
            )
            return logits, cache

        @jit_family("engine.decode_step")
        def decode_step(params, tok, lens, cache, rng, temperature, top_k):
            # tok [B,1]; lens [B] = current absolute position per row.
            # top_k rides TRACED (int32 scalar): per-request values share one
            # compiled program — the old static_argnames form recompiled the
            # whole decode step per distinct k (analysis/baseline.json entry,
            # now fixed)
            logits, cache = llama_forward(
                params, cfg, tok, positions=lens[:, None], cache=cache, cache_index=lens
            )
            rng, sub = jax.random.split(rng)
            nxt, _lp = sample_tokens(logits[:, -1], sub, temperature, top_k=top_k)
            return nxt, cache, rng

        @jit_family("engine.generate_fused",
                    static_argnames=("steps", "eos_id"))
        def generate_fused(params, ids, positions, lens, cache, rng, temperature,
                           steps, top_k, eos_id, pad_mask):
            """Prefill + first-token sample + the whole decode scan as ONE
            compiled program. The bulk path dispatches this once and fetches
            one output — on remote-attached devices every extra blocking
            host<->device round trip costs ~RTT (measured ~70 ms through a
            tunnel), which dwarfs the actual compute at serving batch sizes.
            ``steps`` comes from ``_stable_steps`` (STEP_BUCKETS only) and
            ``top_k`` is traced, so the variant space stays the bounded set
            the compile manifest commits to."""
            logits, cache = llama_forward(
                params, cfg, ids, positions=positions, cache=cache, cache_index=0,
                pad_mask=pad_mask, attn_fn=attn_fn,
            )
            row_valid = pad_mask.any(axis=1, keepdims=True)  # junk bucket rows
            last = jnp.take_along_axis(logits, (lens - 1)[:, None, None], axis=1)[:, 0]
            rng, sub = jax.random.split(rng)
            first, _first_lp = sample_tokens(last, sub, temperature, top_k=top_k)

            def body(carry, _):
                tok, lens, cache, rng, done = carry
                # done rows leave routing too — a finished row must not keep
                # claiming expert capacity from live rows
                logits, cache = llama_forward(
                    params, cfg, tok[:, None], positions=lens[:, None],
                    cache=cache, cache_index=lens,
                    pad_mask=row_valid & ~done[:, None],
                )
                rng, sub = jax.random.split(rng)
                nxt, _lp = sample_tokens(logits[:, -1], sub, temperature, top_k=top_k)
                nxt = jnp.where(done, eos_id, nxt)
                done = done | (nxt == eos_id)
                return (nxt, lens + 1, cache, rng, done), nxt

            if steps > 1:
                init = (first, lens, cache, rng, first == eos_id)
                _, rest = jax.lax.scan(body, init, None, length=steps - 1)
                toks = jnp.concatenate([first[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)
            else:
                toks = first[:, None]
            return toks

        self._prefill = prefill
        self._decode_step = decode_step
        self._generate_fused = generate_fused

    # --------------------------------------------------------------- helpers

    def _encode_batch(self, prompts: Sequence[str], max_new: int):
        import jax.numpy as jnp

        from sentio_tpu.models.llama import init_cache
        from sentio_tpu.models.tokenizer import batch_encode

        # prompts always leave >= 8 decode slots in the window, even at the
        # model's max_len — a prompt that fills the cache exactly would have
        # its first generated token clamped onto the last prompt slot
        max_prompt = min(self.config.max_prompt_tokens, self.model_config.max_len - 8)
        ids, mask = batch_encode(self.tokenizer, prompts, max_len=max_prompt, add_bos=True)
        lens = mask.sum(axis=1).astype(np.int32)
        n = len(prompts)
        rows = bucket_size(n, self.BATCH_BUCKETS)
        width = bucket_size(ids.shape[1], self.PREFILL_BUCKETS)
        ids = np.pad(ids, ((0, rows - n), (0, width - ids.shape[1])),
                     constant_values=self.tokenizer.pad_id)
        lens = np.pad(lens, (0, rows - n), constant_values=1)
        # real (row, token) cells: padding tails AND junk bucket rows are
        # False — llama ignores this on the cache path, routed families use
        # it to keep padding out of expert capacity
        pad_mask = (np.arange(width)[None, :] < lens[:, None]) & (
            np.arange(rows) < n
        )[:, None]

        window = min(
            self.model_config.max_len,
            bucket_size(width + max_new, self.PREFILL_BUCKETS + (self.model_config.max_len,)),
        )
        cache = init_cache(self.model_config, rows, window)
        if self.mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from sentio_tpu.parallel.mesh import AXIS_DP, AXIS_TP

            spec = NamedSharding(self.mesh, P(None, AXIS_DP, None, AXIS_TP, None))
            cache = {k: jax.device_put(v, spec) for k, v in cache.items()}
        positions = np.broadcast_to(np.arange(width, dtype=np.int32)[None, :], ids.shape)
        # ids/positions/lens stay HOST numpy: host math on them (lens.max(),
        # per-row slicing) must not trigger device round trips; they ride to
        # the device as jit-call args (async, no blocking device_put)
        return ids, positions.copy(), lens, cache, n, window, pad_mask

    STEP_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

    def _stable_steps(self, requested: int, headroom: int) -> int:
        """Static scan lengths must come from the committed STEP_BUCKETS set
        or every distinct value recompiles the whole fused decode loop (the
        compile manifest pins this family's variant space). Requested counts
        round UP to a bucket — the scan over-runs by at most a bucket gap
        and ``generate`` truncates host-side — while a cache-headroom clamp
        rounds DOWN (finish_reason becomes 'length')."""
        from sentio_tpu.parallel.batcher import floor_bucket

        # _encode_batch truncates prompts to leave >= 8 slots, so headroom >= 8
        # always holds in practice; the assert guards the invariant
        assert headroom >= 1, f"no KV headroom ({headroom}); prompt truncation failed"
        # min() with the top bucket: bucket_size returns n ITSELF past the
        # last bucket, which would reopen the one-program-per-value hole
        # for requests above max(STEP_BUCKETS) — those clamp (length-finish
        # at the top bucket) instead of compiling off-manifest
        steps = min(bucket_size(max(requested, 1), self.STEP_BUCKETS),
                    max(self.STEP_BUCKETS))
        if steps > headroom:
            steps = floor_bucket(headroom, self.STEP_BUCKETS)
        return max(min(steps, headroom), 1)

    def compile_variant_space(self) -> dict[str, list[dict]]:
        """The DECLARED compile-variant space per jit family — every
        (shape-static) combination the serving paths above can request,
        derived from the same constants/helpers they use. ``sentio audit``
        abstractly lowers each descriptor and diffs the result against the
        committed compile manifest; widening any bucket set here (or in the
        helpers) is a deliberate, manifest-visible act."""
        cfg = self.model_config
        max_prompt = min(self.config.max_prompt_tokens, cfg.max_len - 8)
        # achievable prefill widths: bucket_size over 1..max_prompt
        top_w = bucket_size(max_prompt, self.PREFILL_BUCKETS)
        widths = sorted(
            {b for b in self.PREFILL_BUCKETS if b <= top_w} | {top_w}
        )
        # achievable cache windows per width (_encode_batch): the bucket set
        # extended by max_len, values above width, capped at max_len
        ext = sorted(set(self.PREFILL_BUCKETS) | {cfg.max_len})

        def windows(width: int) -> list[int]:
            return sorted({min(cfg.max_len, b) for b in ext if b > width})

        rows = list(self.BATCH_BUCKETS)
        # achievable fused-scan lengths (_stable_steps: STEP_BUCKETS only,
        # down-clamped by headroom < max_len)
        steps = [b for b in self.STEP_BUCKETS if b <= cfg.max_len - 1]
        space: dict[str, list[dict]] = {
            "engine.prefill": [
                {"rows": r, "width": w, "window": win}
                for w in widths for win in windows(w) for r in rows
            ],
            "engine.decode_step": [
                {"rows": r, "window": win}
                for win in sorted({win for w in widths for win in windows(w)})
                for r in rows
            ],
            "engine.generate_fused": [
                {"rows": r, "width": w, "window": win, "steps": s}
                for w in widths for win in windows(w) for r in rows
                for s in steps if s < win
            ],
        }
        return space

    # ----------------------------------------------------------------- public

    def generate(
        self,
        prompts: Sequence[str],
        max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: int = 0,
    ) -> list[GenerationResult]:
        """Batched bulk generation through the on-device scan loop. Batches
        larger than the biggest batch bucket are chunked transparently."""
        import jax
        import jax.numpy as jnp

        from sentio_tpu.infra import faults

        faults.hit("engine.generate")

        max_batch = max(self.BATCH_BUCKETS)
        if len(prompts) > max_batch:
            out: list[GenerationResult] = []
            for start in range(0, len(prompts), max_batch):
                out.extend(
                    self.generate(
                        prompts[start : start + max_batch],
                        max_new_tokens=max_new_tokens,
                        temperature=temperature,
                        top_k=top_k,
                    )
                )
            return out

        t0 = time.perf_counter()
        requested = max_new_tokens or self.config.max_new_tokens
        temp = self.config.temperature() if temperature is None else temperature
        ids, positions, lens, cache, n, window, pad_mask = self._encode_batch(prompts, requested)
        max_new = self._stable_steps(requested, window - int(lens.max()))

        # one dispatch, one fetch: prefill + sampling + decode scan fused
        self._rng, sub = jax.random.split(self._rng)
        toks = np.asarray(self._generate_fused(
            self.params, ids, positions, lens, cache, sub,
            jnp.asarray(temp, jnp.float32), max_new, np.int32(top_k),
            self.tokenizer.eos_id, pad_mask,
        ))
        dt_ms = (time.perf_counter() - t0) * 1000.0

        out = []
        for i in range(n):
            # steps round UP to a bucket; the over-run tail past the caller's
            # budget is dropped here (EOS inside it must not flip the reason)
            row = toks[i, :requested].tolist()
            if self.tokenizer.eos_id in row:
                cut = row.index(self.tokenizer.eos_id)
                row, reason = row[:cut], "stop"
            else:
                reason = "length"
            out.append(
                GenerationResult(
                    text=self.tokenizer.decode(row),
                    tokens=row,
                    prompt_tokens=int(lens[i]),
                    finish_reason=reason,
                    latency_ms=dt_ms,
                )
            )
        return out

    def stream(
        self,
        prompt: str,
        max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: int = 0,
    ) -> Iterator[str]:
        """Host-stepped decode yielding decoded text increments (SSE feed).
        UTF-8 safe: bytes are buffered until they decode cleanly."""
        import jax
        import jax.numpy as jnp

        max_new = max_new_tokens or self.config.max_new_tokens
        temp = self.config.temperature() if temperature is None else temperature
        ids, positions, lens, cache, _, window, pad_mask = self._encode_batch([prompt], max_new)
        # the stream loop is host-driven (no static scan length), so the
        # caller's budget applies exactly — only the cache window clamps it
        max_new = max(min(max_new, window - int(lens.max())), 1)

        logits, cache = self._prefill(self.params, ids, positions, cache, pad_mask)
        last = jnp.take_along_axis(logits, (lens - 1)[:, None, None], axis=1)[:, 0]
        from sentio_tpu.runtime.sampling import sample_tokens

        self._rng, sub = jax.random.split(self._rng)
        tok, _lp = sample_tokens(last, sub, temp, top_k=top_k)
        lens = jnp.asarray(lens)
        emitted: list[int] = []
        flushed = ""
        for _ in range(max_new):
            t = int(tok[0])
            if t == self.tokenizer.eos_id:
                break
            emitted.append(t)
            text = self.tokenizer.decode(emitted)
            # withhold at most the final char: a trailing '�' may be an
            # incomplete UTF-8 sequence the next token resolves
            safe = text[:-1] if text.endswith("�") else text
            if len(safe) > len(flushed):
                yield safe[len(flushed):]
                flushed = safe
            tok, cache, self._rng = self._decode_step(
                self.params, tok[:, None], lens, cache, self._rng,
                jnp.asarray(temp, jnp.float32), np.int32(top_k),
            )
            lens = lens + 1
        final = self.tokenizer.decode(emitted)
        if len(final) > len(flushed):
            yield final[len(flushed):]

    def device_stats(self) -> dict:
        """Health-endpoint payload: device kind, count, mesh shape."""
        import jax

        devices = jax.devices()
        stats = {
            "platform": devices[0].platform if devices else "none",
            "n_devices": len(devices),
            "mesh": dict(self.mesh.shape) if self.mesh is not None else None,
            "model": {
                "layers": self.model_config.n_layers,
                "dim": self.model_config.dim,
                "vocab": self.model_config.vocab_size,
            },
        }
        try:  # HBM headroom where the backend exposes it
            m = devices[0].memory_stats()
            if m:
                stats["memory"] = {
                    "bytes_in_use": m.get("bytes_in_use"),
                    "bytes_limit": m.get("bytes_limit"),
                }
        except Exception:  # noqa: BLE001 — device stats are best-effort diagnostics
            pass
        return stats
