"""Radix prefix cache: automatic multi-prefix KV reuse over the paged pool.

RadixAttention-style (SGLang, Zheng et al. 2024) prefix sharing layered on
the PagedAttention page pool (Kwon et al. 2023): a token-id radix tree whose
edges own runs of **full KV pages**. Admission does a longest-prefix match,
reuses the matched pages read-only, and prefills only the unmatched suffix;
every admitted prompt's full-page span is inserted back, so the tree learns
the workload's shared heads (system prompt, retrieved context, the
generate-prompt head the verify prompt embeds) with no registration step.

Design constraints that shape the structure:

* **page granularity everywhere** — pages are the pool's unit of sharing,
  so edges hold whole pages and nodes split only at page boundaries; a
  divergence inside a page means that page simply isn't shared. Children
  are keyed by their edge's FIRST PAGE of tokens (a tuple), since two
  siblings may agree on a first token but diverge later in the page.
* **refcount pinning** — a live slot locks the node chain covering the
  pages its table references; eviction only ever touches refcount-0
  leaves, so a shared page can never be freed (and reallocated, and
  scribbled over) while any in-flight sequence still attends to it.
* **LRU under pressure** — when the engine needs pages it evicts unpinned
  leaves oldest-touch-first (a touch is a match walking through the node),
  cascading upward as parents become leaves.

Single-threaded by contract, like the engine that owns it: only the pump
thread calls in. The tree never talks to the device — it tracks integer
page ids; the engine orders actual KV writes via its dispatch sequence.

**Prior-prefix admissions** (resume-by-replay, runtime/replica.py): a
resumed stream re-admits with its delivered tokens appended after the
prompt, so the token sequences this tree matches and inserts are NOT
always pure prompts — they may embed generated continuations. Nothing in
the tree distinguishes the two (tokens are tokens), which is exactly what
makes the replay cheap: when the dead stream's prompt pages were already
cached here, the resume admission matches them and prefills only the
delivered suffix; the insert afterwards caches prompt+delivered, so a
SECOND resume of the same stream (a flapping replica) is a full-prefix
hit. Eviction, pinning, and page accounting are oblivious to the origin
of the tokens — the conservation invariants hold unchanged.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Optional, Sequence

__all__ = ["RadixNode", "RadixPrefixCache"]


class RadixNode:
    """One edge of the tree: ``tokens`` (length a multiple of page_size)
    backed by ``pages`` (one id per page_size tokens)."""

    __slots__ = ("tokens", "pages", "children", "parent", "refcount",
                 "last_used")

    def __init__(self, tokens: list[int], pages: list[int],
                 parent: Optional["RadixNode"]) -> None:
        self.tokens = tokens
        self.pages = pages
        self.children: dict[tuple, "RadixNode"] = {}
        self.parent = parent
        self.refcount = 0
        self.last_used = 0

    def __repr__(self) -> str:  # debugging aid only
        return (f"RadixNode(tokens={len(self.tokens)}, pages={self.pages}, "
                f"rc={self.refcount}, children={len(self.children)})")


class RadixPrefixCache:
    """Token-id radix tree over page-aligned KV page runs.

    The cache OWNS the pages held by its nodes: the engine transfers
    ownership on :meth:`insert` (donated pages are no longer freed at slot
    retirement) and gets them back via :meth:`evict`, which returns freed
    ids to the allocator.
    """

    def __init__(self, page_size: int, allocator) -> None:
        self.page_size = page_size
        self.allocator = allocator
        self.root = RadixNode([], [], None)  # guarded-by: engine-thread
        self.pages_held = 0  # guarded-by: engine-thread
        self.node_count = 0  # guarded-by: engine-thread
        self.evicted_pages = 0  # guarded-by: engine-thread
        self._clock = itertools.count(1)

    # ------------------------------------------------------------------ reads

    @property
    def empty(self) -> bool:
        return not self.root.children

    def match(self, tokens: Sequence[int]) -> tuple[int, list[int], Optional[RadixNode]]:
        """Longest page-aligned prefix of ``tokens`` present in the tree →
        ``(n_matched, pages, deepest_node)``. Only whole pages match; a
        partial match inside an edge returns that edge's node (pinning it
        protects the matched page prefix). Touches the walked path for LRU.
        """
        page = self.page_size
        now = next(self._clock)
        node = self.root
        pages: list[int] = []
        pos = 0
        while pos + page <= len(tokens):
            key = tuple(tokens[pos : pos + page])
            child = node.children.get(key)
            if child is None:
                break
            # count full pages of the edge matching from ``pos``
            j = 1  # first page matched via the key
            edge_pages = len(child.pages)
            while j < edge_pages:
                lo = pos + j * page
                if lo + page > len(tokens) or \
                        child.tokens[j * page : (j + 1) * page] != list(tokens[lo : lo + page]):
                    break
                j += 1
            pages.extend(child.pages[:j])
            pos += j * page
            child.last_used = now
            if j < edge_pages:
                return pos, pages, child
            node = child
        # touch ancestors so a deep hit refreshes its whole path
        walk = node
        while walk is not None:
            walk.last_used = now
            walk = walk.parent
        return pos, pages, (node if node is not self.root else None)

    def peek_prefix(self, tokens: Sequence[int]) -> int:
        """Length (in tokens) of the longest page-aligned prefix of
        ``tokens`` this tree holds, WITHOUT taking refcounts or touching
        LRU clocks — the read-only routing probe the multi-replica router
        calls to pick the replica already holding a session's KV.

        Unlike every other method, this one MAY be called from a thread
        that is not the engine driver: it only reads (dict ``.get``, list
        slices — each GIL-atomic), never mutates, and its result is an
        advisory hint, not a correctness input. A concurrent insert/split/
        evict on the driver thread can at worst make the count stale by a
        few pages, which costs a slightly suboptimal routing choice."""
        page = self.page_size
        node = self.root
        pos = 0
        while pos + page <= len(tokens):
            child = node.children.get(tuple(tokens[pos : pos + page]))
            if child is None:
                break
            j = 1
            edge_pages = len(child.pages)
            while j < edge_pages:
                lo = pos + j * page
                if lo + page > len(tokens) or \
                        child.tokens[j * page : (j + 1) * page] != list(tokens[lo : lo + page]):
                    break
                j += 1
            pos += j * page
            if j < edge_pages:
                break
            node = child
        return pos

    # ----------------------------------------------------------------- writes

    def insert(self, tokens: Sequence[int], start: int, pages: Sequence[int],
               ) -> tuple[Optional[RadixNode], list[int]]:
        """Insert ``tokens`` (page-aligned length) whose span ``[start:)``
        is backed by ``pages`` (the inserting slot's own, freshly prefilled
        pages; ``start`` is page-aligned — the span the slot matched at
        admission). Returns ``(deepest_node, donated)`` where ``donated``
        are the pages whose ownership moved to the tree; pages covering
        spans some earlier insert already cached stay with the caller.
        """
        page = self.page_size
        assert len(tokens) % page == 0 and start % page == 0
        now = next(self._clock)
        node = self.root
        pos = 0
        donated: list[int] = []
        while pos < len(tokens):
            key = tuple(tokens[pos : pos + page])
            child = node.children.get(key)
            if child is None:
                if pos < start:
                    # the matched span must still be present: admission
                    # pinned it, and pins block eviction
                    raise RuntimeError(
                        f"radix insert: matched span [{pos}:{start}) vanished"
                    )
                new_pages = list(pages[(pos - start) // page :])
                tail = RadixNode(list(tokens[pos:]), new_pages, node)
                tail.last_used = now
                node.children[key] = tail
                donated.extend(new_pages)
                self.pages_held += len(new_pages)
                self.node_count += 1
                node = tail
                pos = len(tokens)
                break
            # walk the edge page by page
            j = 1
            edge_pages = len(child.pages)
            while j < edge_pages:
                lo = pos + j * page
                if lo + page > len(tokens) or \
                        child.tokens[j * page : (j + 1) * page] != list(tokens[lo : lo + page]):
                    break
                j += 1
            child.last_used = now
            if j < edge_pages:
                split = self._split(child, j)
                pos += j * page
                if pos >= len(tokens):
                    node = split
                    break
                node = split
                continue  # diverged mid-edge: next loop attaches the tail
            node = child
            pos += edge_pages * page
        return (node if node is not self.root else None), donated

    def _split(self, node: RadixNode, j: int) -> RadixNode:
        """Split ``node``'s edge after ``j`` pages; returns the new upper
        node (which keeps the parent link, refcount, and children key)."""
        page = self.page_size
        upper = RadixNode(node.tokens[: j * page], node.pages[:j], node.parent)
        upper.last_used = node.last_used
        # a pin on the lower node pins its whole chain; the upper node
        # inherits the count so chain pins stay consistent after the split
        upper.refcount = node.refcount
        key = tuple(node.tokens[:page])
        node.parent.children[key] = upper
        node.tokens = node.tokens[j * page :]
        node.pages = node.pages[j:]
        node.parent = upper
        upper.children[tuple(node.tokens[:page])] = node
        self.node_count += 1
        return upper

    # ------------------------------------------------------------- pin/unpin

    def lock(self, node: Optional[RadixNode]) -> None:
        """Pin ``node`` and every ancestor (a slot's page table references
        the whole chain down to its match point)."""
        while node is not None and node is not self.root:
            node.refcount += 1
            node = node.parent

    def unlock(self, node: Optional[RadixNode]) -> None:
        while node is not None and node is not self.root:
            node.refcount -= 1
            assert node.refcount >= 0, "radix refcount underflow"
            node = node.parent

    # -------------------------------------------------------------- eviction

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages from unpinned leaves, LRU first,
        cascading to parents as they become leaves. Returns pages freed
        (returned to the allocator). One tree traversal total: candidates
        collect into a ``last_used`` min-heap and parents push as their
        last child evicts — not a fresh full-tree scan per victim, which
        would cost O(nodes x victims) on the admission path exactly when
        the pool is most contended."""
        heap: list[tuple[int, int, RadixNode]] = []
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif node.refcount == 0:
                heap.append((node.last_used, id(node), node))
        heapq.heapify(heap)
        freed = 0
        while freed < n_pages and heap:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            self.allocator.free(victim.pages)
            freed += len(victim.pages)
            self.pages_held -= len(victim.pages)
            self.evicted_pages += len(victim.pages)
            self.node_count -= 1
            del parent.children[tuple(victim.tokens[: self.page_size])]
            if parent is not self.root and not parent.children \
                    and parent.refcount == 0:
                heapq.heappush(heap, (parent.last_used, id(parent), parent))
        return freed

    def clear(self) -> None:
        """Drop every node, returning all held pages to the allocator.
        Callers must ensure no live page table references the tree."""
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.allocator.free(node.pages)
        self.root = RadixNode([], [], None)
        self.pages_held = 0
        self.node_count = 0

    # ------------------------------------------------------------------ stats

    def stats(self) -> dict:
        return {
            "pages": self.pages_held,
            "nodes": self.node_count,
            "evicted_pages": self.evicted_pages,
        }
