"""Host-side BM25 over a CSR postings index — no external IR library.

Replaces both of the reference's sparse legs: the in-memory ``rank_bm25``
Okapi index (/root/reference/src/core/retrievers/sparse.py:33-203) and the
Lucene/Pyserini path for large corpora (:206-276). Here the index is our own:
a term→postings CSR layout in numpy (vectorized scoring, `argpartition`
top-k), with an optional C++ backend (``sentio_tpu.native``) swapped in for
million-doc scale. Scoring runs on the TPU VM host CPU concurrently with
dense retrieval on the device.

Supports Okapi BM25 and BM25+ (delta smoothing), pickle-free persistence
(npz + json vocab), and incremental corpus stats identical in contract to the
reference (k1/b knobs, lowercase tokenizer, save/load).
"""

from __future__ import annotations

import json
import re
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, NamedTuple, Optional, Sequence

import numpy as np

from sentio_tpu.models.document import Document

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)


def default_tokenizer(text: str) -> list[str]:
    """Lowercase unicode word tokenizer (the reference used whitespace+lower;
    \\w keeps accented and CJK text indexable, unlike an ASCII class)."""
    return _TOKEN_RE.findall(text.lower())


@dataclass
class BM25Params:
    k1: float = 1.5
    b: float = 0.75
    delta: float = 0.0  # >0 → BM25+ lower-bounding
    variant: str = "okapi"  # okapi | plus


class _Postings(NamedTuple):
    """One consistent, immutable snapshot of the index state. ``build()``
    publishes a new snapshot in a single reference assignment AFTER all
    arrays are final, so concurrent queries read either the old or the new
    corpus — never a torn mix. Arrays referenced by a published snapshot
    are never written again."""

    term_offsets: np.ndarray
    post_docs: np.ndarray
    post_tfs: np.ndarray
    idf: np.ndarray
    norm: np.ndarray
    avgdl: float
    doc_ids: list
    documents: list


class BM25Index:
    """Immutable-after-build BM25 index.

    Layout: ``term_offsets[t]:term_offsets[t+1]`` slices ``post_docs``/
    ``post_tfs`` — the postings of term ``t``. Per-term slices have unique doc
    ids, so score accumulation is a vectorized fancy-index add per query term
    (cost: O(sum of query-term posting lengths), the same work Lucene does,
    without the JVM).

    Queries read only the :class:`_Postings` snapshot (``self._epoch``), so
    they are lock-free and safe against a concurrent ``build()``; the vocab
    is shared across rebuilds and append-only, and snapshot readers bounds-
    check term ids against their own snapshot's term count.
    """

    def __init__(
        self,
        params: BM25Params | None = None,
        tokenizer: Callable[[str], list[str]] = default_tokenizer,
    ) -> None:
        self.params = params or BM25Params()
        if self.params.variant == "plus" and self.params.delta == 0.0:
            self.params.delta = 1.0
        self.tokenizer = tokenizer
        self._norm: Optional[np.ndarray] = None  # k1*(1-b+b*dl/avgdl), built once
        self.vocab: dict[str, int] = {}
        self.doc_ids: list[str] = []
        self.doc_lens = np.zeros(0, dtype=np.float32)
        self.avgdl: float = 0.0
        self.term_offsets = np.zeros(1, dtype=np.int64)
        self.post_docs = np.zeros(0, dtype=np.int32)
        self.post_tfs = np.zeros(0, dtype=np.float32)
        self.idf = np.zeros(0, dtype=np.float32)
        self._documents: list[Document] = []
        self._epoch = self._snapshot()

    # ------------------------------------------------------------------ build

    def build(self, documents: Sequence[Document]) -> "BM25Index":
        self._documents = list(documents)
        self.doc_ids = [d.id for d in documents]
        n_docs = len(documents)
        term_postings: dict[int, dict[int, int]] = {}
        doc_lens = np.zeros(n_docs, dtype=np.float32)
        for di, doc in enumerate(documents):
            tokens = self.tokenizer(doc.content)
            doc_lens[di] = len(tokens)
            for tok in tokens:
                tid = self.vocab.setdefault(tok, len(self.vocab))
                postings = term_postings.setdefault(tid, {})
                postings[di] = postings.get(di, 0) + 1
        self.doc_lens = doc_lens
        self.avgdl = float(doc_lens.mean()) if n_docs else 0.0

        n_terms = len(self.vocab)
        lengths = np.zeros(n_terms, dtype=np.int64)
        for tid, postings in term_postings.items():
            lengths[tid] = len(postings)
        self.term_offsets = np.concatenate([[0], np.cumsum(lengths)])
        total = int(self.term_offsets[-1])
        self.post_docs = np.zeros(total, dtype=np.int32)
        self.post_tfs = np.zeros(total, dtype=np.float32)
        for tid, postings in term_postings.items():
            start = self.term_offsets[tid]
            docs = np.fromiter(postings.keys(), dtype=np.int32, count=len(postings))
            tfs = np.fromiter(postings.values(), dtype=np.float32, count=len(postings))
            order = np.argsort(docs)
            self.post_docs[start : start + len(docs)] = docs[order]
            self.post_tfs[start : start + len(docs)] = tfs[order]
        # Robertson-Sparck-Jones idf with 0.5 smoothing, floored at 0 like Lucene
        df = lengths.astype(np.float64)
        with np.errstate(divide="ignore"):
            idf = np.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
        self.idf = np.maximum(idf, 0.0).astype(np.float32)
        self._finalize_norm()
        # single atomic publish: queries in flight keep the old snapshot
        self._epoch = self._snapshot()
        return self

    def _finalize_norm(self) -> None:
        k1, b = self.params.k1, self.params.b
        if self.avgdl > 0:
            self._norm = (k1 * (1.0 - b + b * self.doc_lens / self.avgdl)).astype(np.float32)
        else:
            self._norm = np.zeros_like(self.doc_lens)

    def _snapshot(self) -> _Postings:
        return _Postings(
            term_offsets=self.term_offsets,
            post_docs=self.post_docs,
            post_tfs=self.post_tfs,
            idf=self.idf,
            norm=self._norm if self._norm is not None else np.zeros(0, np.float32),
            avgdl=self.avgdl,
            doc_ids=self.doc_ids,
            documents=self._documents,
        )

    @property
    def size(self) -> int:
        return len(self.doc_ids)

    # ------------------------------------------------------------------ score

    def scores(self, query: str, _e: Optional[_Postings] = None) -> np.ndarray:
        """Dense score vector over the whole corpus for one query."""
        e = _e if _e is not None else self._epoch
        n = len(e.doc_ids)
        out = np.zeros(n, dtype=np.float32)
        if n == 0 or e.avgdl == 0:
            return out
        k1, delta = self.params.k1, self.params.delta
        n_terms = len(e.term_offsets) - 1
        for tok in self.tokenizer(query):
            tid = self.vocab.get(tok)
            # vocab is shared/append-only; ids minted after this snapshot
            # have no postings here
            if tid is None or tid >= n_terms:
                continue
            start, end = e.term_offsets[tid], e.term_offsets[tid + 1]
            docs = e.post_docs[start:end]
            tfs = e.post_tfs[start:end]
            denom = tfs + e.norm[docs]
            contrib = e.idf[tid] * (tfs * (k1 + 1.0) / denom + delta)
            np.add.at(out, docs, contrib)  # repeated query terms hit same docs
        return out

    def search(
        self, query: str, top_k: int = 10, _e: Optional[_Postings] = None
    ) -> list[tuple[int, float]]:
        """Top-k under the total order (score desc, doc id asc) — the
        deterministic tie-break the native core uses, so backends agree.
        Work stays O(n + k log k) even when a huge fraction of the corpus
        ties at the k-th score (boilerplate tokens): only the ``need``
        smallest doc ids among boundary ties are materialized, never the
        whole tie set sorted."""
        e = _e if _e is not None else self._epoch
        scores = self.scores(query, e)
        k = min(top_k, len(e.doc_ids))
        if k == 0:
            return []
        idx = np.argpartition(-scores, k - 1)[:k]
        kth = scores[idx].min()
        if kth <= 0.0:
            # sparse match set: fewer than k docs score positive
            cand = np.nonzero(scores > 0.0)[0]
            cand = cand[np.lexsort((cand, -scores[cand]))][:k]
            return [(int(i), float(scores[i])) for i in cand]
        above = np.nonzero(scores > kth)[0]  # < k elements
        above = above[np.lexsort((above, -scores[above]))]
        ties = np.nonzero(scores == kth)[0]  # ascending already (nonzero order)
        need = k - len(above)
        cand = np.concatenate([above, ties[:need]])
        return [(int(i), float(scores[i])) for i in cand]

    def retrieve(self, query: str, top_k: int = 10) -> list[Document]:
        e = self._epoch  # one snapshot: indices resolve against the same docs
        out = []
        for di, score in self.search(query, top_k, e):
            doc = e.documents[di]
            meta = dict(doc.metadata)
            meta["score"] = score
            meta["retriever"] = "bm25"
            out.append(Document(text=doc.text, metadata=meta, id=doc.id))
        return out

    # ------------------------------------------------------------ persistence

    def save(self, path: str | Path) -> None:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        np.savez_compressed(
            path.with_suffix(".npz"),
            doc_lens=self.doc_lens,
            term_offsets=self.term_offsets,
            post_docs=self.post_docs,
            post_tfs=self.post_tfs,
            idf=self.idf,
        )
        meta = {
            "custom_tokenizer": self.tokenizer is not default_tokenizer,
            "vocab": self.vocab,
            "doc_ids": self.doc_ids,
            "avgdl": self.avgdl,
            "params": {
                "k1": self.params.k1,
                "b": self.params.b,
                "delta": self.params.delta,
                "variant": self.params.variant,
            },
            "documents": [d.to_dict() for d in self._documents],
        }
        path.with_suffix(".json").write_text(json.dumps(meta))

    @classmethod
    def load(
        cls,
        path: str | Path,
        tokenizer: Optional[Callable[[str], list[str]]] = None,
    ) -> "BM25Index":
        """Load a saved index. An index built with a custom tokenizer MUST be
        loaded with that same tokenizer — the vocab was produced by it, and a
        mismatched query tokenizer silently returns empty results."""
        path = Path(path)
        meta = json.loads(path.with_suffix(".json").read_text())
        if meta.get("custom_tokenizer") and tokenizer is None:
            raise ValueError(
                f"index at {path} was built with a custom tokenizer; "
                "pass the same tokenizer= to BM25Index.load"
            )
        params = BM25Params(**meta["params"])
        index = cls(params=params, tokenizer=tokenizer or default_tokenizer)
        index.vocab = {str(k): int(v) for k, v in meta["vocab"].items()}
        index.doc_ids = list(meta["doc_ids"])
        index.avgdl = float(meta["avgdl"])
        index._documents = [Document.from_dict(d) for d in meta["documents"]]
        arrays = np.load(path.with_suffix(".npz"))
        index.doc_lens = arrays["doc_lens"]
        index.term_offsets = arrays["term_offsets"]
        index.post_docs = arrays["post_docs"]
        index.post_tfs = arrays["post_tfs"]
        index.idf = arrays["idf"]
        index._finalize_norm()
        index._epoch = index._snapshot()
        return index


class _NativeHandle:
    """Refcounted wrapper around one C++ index handle + a SNAPSHOT of the
    Python-side state it must stay consistent with.

    The C++ core is stateless per call (caller-owned scratch), so any number
    of threads may score through one handle concurrently — the hazards are
    lifecycle and consistency: a rebuild must not destroy the handle while a
    search is mid-flight (use-after-free), the borrowed numpy buffers must
    outlive it, AND a query running against an old handle must size its
    output by the OLD corpus (the C++ core writes ``n_docs`` floats — a
    buffer sized from post-rebuild ``self.size`` would overflow) and map
    result indices through the OLD document list. ``n_docs``/``documents``
    are snapshotted here for that; the vocab is safe to share because
    ``build`` only ever APPENDS term ids (setdefault) and the core
    bounds-checks ids ≥ its n_terms. ``acquire``/``release`` bracket each
    call; ``retire`` marks the handle dead and the LAST releaser (or retire
    itself when idle) frees it.
    """

    def __init__(self, lib, handle, pinned: tuple, n_docs: int, documents: list) -> None:
        self.lib = lib
        self.handle = handle
        self.n_docs = n_docs
        self.documents = documents  # the list object this handle indexed
        self._pinned = pinned
        self._refs = 0
        self._dead = False
        self._lock = threading.Lock()

    def acquire(self) -> bool:
        with self._lock:
            if self._dead:
                return False
            self._refs += 1
            return True

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            free_now = self._dead and self._refs == 0
        if free_now:
            self._destroy()

    def retire(self) -> None:
        with self._lock:
            if self._dead:
                return
            self._dead = True
            free_now = self._refs == 0
        if free_now:
            self._destroy()

    def _destroy(self) -> None:
        try:
            self.lib.sbm25_destroy(self.handle)
        finally:
            self._pinned = ()


class NativeBM25Index(BM25Index):
    """BM25Index scored by the C++ core (sentio_tpu/native/bm25.cpp).

    Python keeps tokenization, vocab, and the CSR build (so persistence and
    scores are identical to the numpy path); the per-query hot loop —
    postings traversal, accumulation, top-k selection — runs native. The
    index buffers are shared zero-copy; the handle borrows them, so they
    are pinned for the handle's lifetime (``_NativeHandle``). Queries run
    lock-free and concurrent; ``_native_lock`` only serializes handle
    creation/retirement (build/rebuild). If the native library is
    unavailable (no toolchain), every call transparently degrades to the
    numpy implementation, which reads the lock-free ``_Postings`` snapshot
    — concurrent rebuilds can't tear it either.
    """

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._box: Optional[_NativeHandle] = None
        self._native_lock = threading.Lock()

    # build() swaps the CSR arrays out from under a live handle — retire it
    # (in-flight searches finish against the old buffers, then it frees)
    def build(self, documents: Sequence[Document]) -> "NativeBM25Index":
        with self._native_lock:
            if self._box is not None:
                self._box.retire()
                self._box = None
            super().build(documents)
        return self

    def __del__(self) -> None:  # noqa: D105
        try:
            if self._box is not None:
                self._box.retire()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def _get_box(self) -> Optional[_NativeHandle]:
        """The live handle, creating it on first use. Lock covers creation
        only; callers bracket actual use with acquire/release."""
        box = self._box
        if box is not None:
            return box
        with self._native_lock:
            if self._box is not None:
                return self._box
            if self.size == 0 or self._norm is None:
                return None
            from sentio_tpu import native

            lib = native.load_bm25()
            if lib is None:
                return None
            import ctypes as C

            to = np.ascontiguousarray(self.term_offsets, dtype=np.int64)
            pd = np.ascontiguousarray(self.post_docs, dtype=np.int32)
            pt = np.ascontiguousarray(self.post_tfs, dtype=np.float32)
            idf = np.ascontiguousarray(self.idf, dtype=np.float32)
            norm = np.ascontiguousarray(self._norm, dtype=np.float32)
            handle = lib.sbm25_create(
                self.size, len(self.vocab),
                to.ctypes.data_as(C.POINTER(C.c_int64)),
                pd.ctypes.data_as(C.POINTER(C.c_int32)),
                pt.ctypes.data_as(C.POINTER(C.c_float)),
                idf.ctypes.data_as(C.POINTER(C.c_float)),
                norm.ctypes.data_as(C.POINTER(C.c_float)),
                self.params.k1, self.params.delta,
            )
            if handle is None:
                return None
            self._box = _NativeHandle(
                lib, handle, (to, pd, pt, idf, norm),
                n_docs=self.size, documents=self._documents,
            )
            return self._box

    def _query_ids(self, query: str) -> np.ndarray:
        """Vocab ids of query tokens, repeats preserved (np.add.at parity)."""
        ids = [self.vocab[t] for t in self.tokenizer(query) if t in self.vocab]
        return np.asarray(ids, dtype=np.int32)

    def scores(self, query: str, _e: Optional[_Postings] = None) -> np.ndarray:
        import ctypes as C

        if _e is not None:
            # caller pinned a snapshot (fallback search mid-rebuild): the
            # native box may index a different corpus — stay consistent
            return super().scores(query, _e)
        box = self._get_box()
        if box is None or not box.acquire():
            return super().scores(query)
        try:
            # size the buffer by the handle's snapshot, not live self.size —
            # a concurrent rebuild may have changed the corpus under us
            qids = self._query_ids(query)
            out = np.zeros(box.n_docs, dtype=np.float32)
            box.lib.sbm25_scores(
                box.handle, qids.ctypes.data_as(C.POINTER(C.c_int32)), len(qids),
                out.ctypes.data_as(C.POINTER(C.c_float)),
            )
            return out
        finally:
            box.release()

    def search(
        self, query: str, top_k: int = 10, _e: Optional[_Postings] = None
    ) -> list[tuple[int, float]]:
        if _e is not None:
            return super().search(query, top_k, _e)
        box = self._get_box()
        if box is None or not box.acquire():
            return super().search(query, top_k)
        try:
            return self._native_search(box, query, top_k)
        finally:
            box.release()

    def _native_search(self, box: _NativeHandle, query: str, top_k: int) -> list[tuple[int, float]]:
        import ctypes as C

        qids = self._query_ids(query)
        k = min(top_k, box.n_docs)
        if k == 0:
            return []
        idx = np.zeros(k, dtype=np.int32)
        sc = np.zeros(k, dtype=np.float32)
        n = box.lib.sbm25_search(
            box.handle, qids.ctypes.data_as(C.POINTER(C.c_int32)), len(qids), k,
            idx.ctypes.data_as(C.POINTER(C.c_int32)),
            sc.ctypes.data_as(C.POINTER(C.c_float)),
        )
        return [(int(idx[i]), float(sc[i])) for i in range(n)]

    def retrieve(self, query: str, top_k: int = 10) -> list[Document]:
        box = self._get_box()
        if box is None or not box.acquire():
            return super().retrieve(query, top_k)
        try:
            # one box snapshot for the whole operation: indices from the
            # native search resolve against the SAME document list the
            # handle indexed, even mid-rebuild
            out = []
            for di, score in self._native_search(box, query, top_k):
                doc = box.documents[di]
                meta = dict(doc.metadata)
                meta["score"] = score
                meta["retriever"] = "bm25"
                out.append(Document(text=doc.text, metadata=meta, id=doc.id))
            return out
        finally:
            box.release()


def make_bm25_index(
    params: BM25Params | None = None,
    tokenizer: Callable[[str], list[str]] = default_tokenizer,
    backend: str = "auto",
) -> BM25Index:
    """BM25 factory honoring ``retrieval.bm25_backend``: ``native`` requires
    the C++ core (raises if the toolchain can't produce it), ``numpy`` forces
    pure Python, ``auto`` uses native when it builds and numpy otherwise."""
    if backend not in ("auto", "numpy", "native"):
        raise ValueError(f"unknown bm25 backend {backend!r}")
    if backend == "numpy":
        return BM25Index(params=params, tokenizer=tokenizer)
    from sentio_tpu import native

    available = native.load_bm25() is not None
    if backend == "native" and not available:
        raise RuntimeError("bm25_backend=native but the C++ core failed to build/load")
    if available:
        return NativeBM25Index(params=params, tokenizer=tokenizer)
    return BM25Index(params=params, tokenizer=tokenizer)
