"""Prompt templates: file-loaded, class-cached, with inline fallbacks.

Parity with /root/reference/src/core/llm/prompt_builder.py:22-162 — templates
live in ``prompts/*.md``, substitution uses literal ``str.replace`` on
``{instruction}/{context}/{query}`` (NOT ``.format``, so braces inside
retrieved context can never KeyError), files are read once per process, and
missing files fall back to built-in templates so the framework runs from a
bare checkout.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Optional

_FALLBACK_TEMPLATES = {
    "profile": (
        "You are a retrieval-grounded assistant. Answer strictly from the "
        "provided sources, cite them as [n], and say when the sources are "
        "insufficient."
    ),
    "retrieve": (
        "{instruction}\n\n"
        "Context documents:\n{context}\n\n"
        "Question: {query}\n\n"
        "Answer using only the context above. Cite sources inline as [n]. "
        "If the context does not contain the answer, say so plainly."
    ),
    # the verify prompt EMBEDS the retrieve prompt verbatim as its head —
    # byte-identical through the generate instruction — so the paged
    # engine's radix prefix cache serves the whole generate-prompt span
    # (instruction + context + question) read-only on the verify admission
    # and prefills only the audit tail
    "verify": (
        "{instruction}\n\n"
        "Context documents:\n{context}\n\n"
        "Question: {query}\n\n"
        "Answer using only the context above. Cite sources inline as [n]. "
        "If the context does not contain the answer, say so plainly.\n\n"
        "You are now auditing the answer below for faithfulness to the "
        "context documents above.\n\nAnswer under audit:\n{answer}\n\n"
        'Reply with ONLY a JSON object: {"verdict": "pass"|"warn"|"fail", '
        '"citations_ok": true|false, "notes": ["..."], '
        '"revised_answer": "... (only when verdict is fail)"}'
    ),
    "summarize": "Summarize the following faithfully and concisely:\n\n{context}",
    "fallback_no_retrieval": (
        "I could not search the knowledge base just now. From general "
        "knowledge, with no citations available: {query}"
    ),
    "fallback_no_llm": (
        "The language model is unavailable. The most relevant passages "
        "found were:\n{context}"
    ),
    "fallback_apology": (
        "I'm sorry — I can't answer right now due to an internal error. "
        "Please try again shortly."
    ),
}


class PromptBuilder:
    _cache: dict[str, str] = {}

    def __init__(self, prompts_dir: Optional[str] = None) -> None:
        self.prompts_dir = Path(prompts_dir) if prompts_dir else Path("prompts")

    def static_head(self, name: str, **values) -> str:
        """The template's constant leading text — everything before the
        first request-varying placeholder ({context}/{query}/{answer}) —
        with the provided static values substituted. The serving layer
        warms the paged engine's radix prefix cache with this span: every
        /chat prompt built from this template starts with these exact
        bytes, so even the first request after boot admits suffix-only."""
        text = self.load(name)
        cut = len(text)
        for dynamic in ("{context}", "{query}", "{answer}"):
            idx = text.find(dynamic)
            if idx != -1:
                cut = min(cut, idx)
        head = text[:cut]
        for key, value in values.items():
            head = head.replace("{" + key + "}", value)
        return head

    def load(self, name: str) -> str:
        cache_key = f"{self.prompts_dir}:{name}"
        cached = self._cache.get(cache_key)
        if cached is not None:
            return cached
        path = self.prompts_dir / f"{name}.md"
        try:
            text = path.read_text().strip()
        except OSError:
            text = _FALLBACK_TEMPLATES.get(name, "{instruction}\n{context}\n{query}")
        self._cache[cache_key] = text
        return text

    def build(
        self,
        name: str,
        instruction: str = "",
        context: str = "",
        query: str = "",
        answer: str = "",
    ) -> str:
        template = self.load(name)
        values = {
            "instruction": instruction, "context": context,
            "query": query, "answer": answer,
        }
        # single-pass substitution: placeholder strings occurring INSIDE a
        # substituted value (an answer quoting "{context}", say) must not be
        # re-expanded, and other braces in retrieved text stay literal
        return re.sub(
            r"\{(instruction|context|query|answer)\}",
            lambda m: values[m.group(1)], template,
        )

    @classmethod
    def clear_cache(cls) -> None:
        cls._cache.clear()
