"""Answer-confidence scoring for the verify gate.

The LLM self-audit (ops/verifier.py) costs a full decode round-trip —
BENCH_r06 measured it at 482 ms p50, MORE than generation itself. Most of
that spend buys nothing: when the model decoded its answer with uniformly
high token probability AND retrieval produced a clearly-separated top
document, the audit almost always returns ``pass``. This module turns the
two signals the serving path already computes for free into one calibrated
confidence score in [0, 1]:

* **generation logprobs** — the per-token logprob accumulators the paged
  engine carries through its fused decode scan (runtime/sampling.py /
  runtime/paged.py): the mean token probability ``exp(logprob_mean)`` says
  how sure the model was on average, the worst token ``exp(logprob_min)``
  catches a single hallucinated span hiding inside an otherwise confident
  answer;
* **retrieval support** — the fused scores on the selected documents
  (ops/fusion.py / ops/scorers.py): a top document that clearly separates
  from the runner-up means the answer had one strong source to ground on,
  a flat score profile means the generator was synthesizing from noise.

``confidence_score`` returns ``None`` whenever the logprob signal is
missing (non-paged providers, speculative decode, cancelled requests) —
the gate then NEVER skips, so confidence gating degrades to plain
always-verify instead of silently skipping on blind spots.

Calibration: the weights below were chosen so that a greedy decode whose
every token carries >= ~0.9 probability over a well-separated source scores
above the default ``VERIFY_CONFIDENCE_THRESHOLD`` (0.75), while random-init
or high-entropy decodes score near the mean token probability (tiny). They
are knobs, not constants of nature — the eval quality gate
(tests/test_eval.py::TestVerifyGate, sentio_tpu/eval/verify_gate.json) pins
gated-vs-always-verify verdict agreement so a calibration change that makes
garbage look confident fails tier-1.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

__all__ = [
    "confidence_score",
    "retrieval_support",
    "WEIGHT_MEAN",
    "WEIGHT_MIN",
    "WEIGHT_RETRIEVAL",
]

# contribution weights; sum to 1.0 so the score stays in [0, 1]
WEIGHT_MEAN = 0.6
WEIGHT_MIN = 0.2
WEIGHT_RETRIEVAL = 0.2


def retrieval_support(documents: Sequence) -> float:
    """[0, 1] — how clearly the top retrieved document separates from the
    rest. 1.0 means the top fused score dominates the runner-up outright;
    0.5 means a single document with no competition (weak evidence either
    way); 0.0 means no documents or a flat / inverted score profile.
    Works on any object with a ``score()`` method (models/document.py)."""
    scores = sorted((float(d.score()) for d in documents), reverse=True)
    if not scores:
        return 0.0
    if len(scores) == 1:
        return 0.5
    top, second = scores[0], scores[1]
    if top <= 0.0:
        return 0.0
    margin = (top - second) / (abs(top) + 1e-12)
    return 0.5 + 0.5 * max(min(margin, 1.0), 0.0)


def confidence_score(
    logprob_mean: Optional[float],
    logprob_min: Optional[float],
    documents: Sequence = (),
) -> Optional[float]:
    """Calibrated answer confidence in [0, 1], or ``None`` when there is no
    logprob signal to score (the gate must then run the verifier — absence
    of evidence is not confidence)."""
    if logprob_mean is None:
        return None
    mean_p = math.exp(min(float(logprob_mean), 0.0))
    min_p = (
        math.exp(min(float(logprob_min), 0.0))
        if logprob_min is not None else mean_p
    )
    score = (
        WEIGHT_MEAN * mean_p
        + WEIGHT_MIN * min_p
        + WEIGHT_RETRIEVAL * retrieval_support(documents)
    )
    return max(min(score, 1.0), 0.0)
