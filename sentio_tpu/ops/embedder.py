"""Embedding service: in-process bi-encoder on the device mesh.

Replaces the reference's remote Jina embeddings API
(/root/reference/src/core/embeddings/providers/jina.py:33) and reproduces its
service contract from the embedder base class (embeddings/base.py:23-423):
LFU+TTL embedding cache, request/hit/error stats, sync + async entry points,
``warm_up`` probe, lazy ``dimension``. Two providers, selected by config:

* ``tpu`` — the Flax-free JAX bi-encoder (models/transformer.py), tokenized
  host-side, batched and bucketed, jitted once per bucket shape.
* ``hash`` — deterministic seeded pseudo-vectors, the reference's offline
  mock mode (jina.py:141-159) kept as the no-hardware test backend.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import threading
import time
from typing import Optional, Sequence

import numpy as np

from sentio_tpu.config import EmbedderConfig, get_settings
from sentio_tpu.infra import faults

logger = logging.getLogger(__name__)


class EmbeddingError(Exception):
    pass


class EmbeddingCache:
    """LFU with TTL, thread-safe (reference: embeddings/base.py:23-106)."""

    def __init__(self, max_size: int = 10_000, ttl_s: float = 3600.0) -> None:
        self.max_size = max_size
        self.ttl_s = ttl_s
        self._store: dict[str, tuple[np.ndarray, float, int]] = {}  # key -> (vec, t, hits)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(text: str) -> str:
        return hashlib.sha256(text.encode()).hexdigest()

    def get(self, text: str) -> Optional[np.ndarray]:
        k = self.key(text)
        with self._lock:
            entry = self._store.get(k)
            if entry is None:
                self.misses += 1
                return None
            vec, t, hits = entry
            if self.ttl_s > 0 and time.perf_counter() - t > self.ttl_s:
                del self._store[k]
                self.misses += 1
                return None
            self._store[k] = (vec, t, hits + 1)
            self.hits += 1
            return vec

    def put(self, text: str, vec: np.ndarray) -> None:
        if self.max_size <= 0:  # caching disabled
            return
        k = self.key(text)
        with self._lock:
            if len(self._store) >= self.max_size and k not in self._store:
                # evict least-frequently-used
                victim = min(self._store.items(), key=lambda kv: kv[1][2])[0]
                del self._store[victim]
            self._store[k] = (vec, time.perf_counter(), 0)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "size": len(self._store),
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
            }


class BaseEmbedder:
    """Common service wrapper: cache, stats, sync/async, warm-up."""

    def __init__(self, config: Optional[EmbedderConfig] = None) -> None:
        self.config = config or get_settings().embedder
        self.cache = EmbeddingCache(self.config.cache_size, self.config.cache_ttl_s)
        self.stats = {"requests": 0, "texts": 0, "errors": 0, "time_s": 0.0}

    @property
    def dimension(self) -> int:
        return self.config.dim

    # -- provider hook -------------------------------------------------------

    def _embed_batch(self, texts: list[str]) -> np.ndarray:  # [B, dim] float32
        raise NotImplementedError

    # -- public API ----------------------------------------------------------

    def embed_many(self, texts: Sequence[str]) -> np.ndarray:
        faults.hit("embedder.batch")
        t0 = time.perf_counter()
        self.stats["requests"] += 1
        self.stats["texts"] += len(texts)
        out = np.zeros((len(texts), self.dimension), np.float32)
        missing: list[tuple[int, str]] = []
        for i, text in enumerate(texts):
            cached = self.cache.get(text)
            if cached is not None:
                out[i] = cached
            else:
                missing.append((i, text))
        try:
            for start in range(0, len(missing), self.config.batch_size):
                chunk = missing[start : start + self.config.batch_size]
                vecs = self._embed_batch([t for _, t in chunk])
                for (i, text), vec in zip(chunk, vecs):
                    out[i] = vec
                    self.cache.put(text, vec)
        except Exception:
            self.stats["errors"] += 1
            raise
        finally:
            self.stats["time_s"] += time.perf_counter() - t0
        return out

    def embed(self, text: str) -> np.ndarray:
        return self.embed_many([text])[0]

    async def embed_many_async(self, texts: Sequence[str]) -> np.ndarray:
        return await asyncio.get_running_loop().run_in_executor(
            None, self.embed_many, list(texts)
        )

    async def embed_async(self, text: str) -> np.ndarray:
        return (await self.embed_many_async([text]))[0]

    def warm_up(self) -> bool:
        """Probe with a trivial input (reference: base.py:387-416); also
        triggers jit compilation so the first real request doesn't pay it."""
        try:
            vec = self.embed("warm up probe")
            return vec.shape == (self.dimension,)
        except Exception:  # noqa: BLE001 — any probe failure means "unhealthy"
            return False

    def get_stats(self) -> dict:
        return {**self.stats, "cache": self.cache.stats()}


class HashEmbedder(BaseEmbedder):
    """Deterministic hash-seeded unit vectors — same trick as the reference's
    empty-API-key mock mode. Texts sharing content always embed identically,
    so retrieval tests are reproducible with zero hardware."""

    def _embed_batch(self, texts: list[str]) -> np.ndarray:
        out = np.zeros((len(texts), self.dimension), np.float32)
        for i, text in enumerate(texts):
            seed = int.from_bytes(hashlib.sha256(text.lower().encode()).digest()[:8], "little")
            rng = np.random.default_rng(seed)
            vec = rng.standard_normal(self.dimension).astype(np.float32)
            # mix in token-level signal so related texts correlate; sorted so
            # float summation order (and thus the vector) is identical across
            # processes regardless of PYTHONHASHSEED
            for tok in sorted(set(text.lower().split())):
                tseed = int.from_bytes(hashlib.md5(tok.encode()).digest()[:8], "little")
                trng = np.random.default_rng(tseed)
                vec += 4.0 * trng.standard_normal(self.dimension).astype(np.float32)
            out[i] = vec / max(np.linalg.norm(vec), 1e-9)
        return out


class TpuEmbedder(BaseEmbedder):
    """The real path: tokenize host-side, run the bi-encoder on device.

    Sequences bucket to powers of two (one compiled program per bucket);
    params live on the mesh (replicated by default — the encoder is small
    relative to HBM; flip to ENCODER_TP_RULES for TP).
    """

    BUCKETS = (16, 32, 64, 128, 256, 512)
    BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

    def __init__(
        self,
        config: Optional[EmbedderConfig] = None,
        params=None,
        model_config=None,
        tokenizer=None,
        mesh=None,
    ) -> None:
        super().__init__(config)
        import jax

        from sentio_tpu.models.tokenizer import ByteTokenizer
        from sentio_tpu.models.transformer import (
            EncoderConfig,
            encoder_forward,
            init_encoder,
            mean_pool,
        )

        if params is None and self.config.checkpoint_path:
            # real weights: a `cli convert encoder` checkpoint + HF tokenizer
            from sentio_tpu.runtime.weights import load_model

            params, model_config, ck_tok = load_model(
                self.config.checkpoint_path, expect_family="encoder",
                tokenizer_path=self.config.tokenizer_path,
            )
            tokenizer = tokenizer or ck_tok
        self.model_config = model_config or (
            EncoderConfig.tiny() if self.config.model_preset == "tiny" else EncoderConfig.base()
        )
        self.tokenizer = tokenizer or ByteTokenizer(self.model_config.vocab_size)
        if params is None:
            params = init_encoder(jax.random.PRNGKey(0), self.model_config)
        self.params = params
        self.mesh = mesh
        if mesh is not None:
            from sentio_tpu.parallel.sharding import ENCODER_TP_RULES, shard_params

            self.params = shard_params(params, mesh, ENCODER_TP_RULES)

        cfg = self.model_config
        # bidirectional flash kernel for the encoder pass — policy lives in
        # kernels.select_encoder_attn_fn (shared with the cross-encoder)
        from sentio_tpu.kernels import select_encoder_attn_fn

        attn_fn = select_encoder_attn_fn(mesh, cfg.n_heads)

        def fwd(p, ids, mask):
            return mean_pool(
                encoder_forward(p, cfg, ids, mask, attn_fn=attn_fn), mask
            )

        self._fwd = jax.jit(fwd)

        # built eagerly (no lazy-init race); the dispatcher thread itself
        # only starts on first submit
        self._query_batcher = None
        if self.config.coalesce:
            from sentio_tpu.parallel.batcher import ThreadBatcher

            def process(batch_texts: list[str]):
                out = self._embed_device_batch(batch_texts)
                # each caller gets its own [1, D] device slice (no download)
                return [out[i : i + 1] for i in range(len(batch_texts))]

            self._query_batcher = ThreadBatcher(
                process,
                max_size=self.config.coalesce_max,
                deadline_ms=self.config.coalesce_deadline_ms,
                name="embed-coalescer",
            )

    def close(self) -> None:
        """Stop the coalescer dispatcher thread (container cleanup)."""
        if self._query_batcher is not None:
            self._query_batcher.close()

    @property
    def dimension(self) -> int:
        return self.model_config.dim

    def get_stats(self) -> dict:
        stats = super().get_stats()
        if self._query_batcher is not None:
            stats["coalescer"] = self._query_batcher.stats.snapshot()
        return stats

    def _embed_batch(self, texts: list[str]) -> np.ndarray:
        import jax.numpy as jnp

        from sentio_tpu.models.tokenizer import batch_encode
        from sentio_tpu.parallel.batcher import bucket_size

        ids, mask = batch_encode(
            self.tokenizer, texts, max_len=min(self.config.max_tokens, self.model_config.max_len)
        )
        # pad seq AND batch to buckets so jit compiles once per bucket pair,
        # not once per (n_texts, longest_text) combination
        n = ids.shape[0]
        width = bucket_size(ids.shape[1], self.BUCKETS)
        rows = bucket_size(n, self.BATCH_BUCKETS)
        ids = np.pad(
            ids, ((0, rows - n), (0, width - ids.shape[1])),
            constant_values=self.tokenizer.pad_id,
        )
        mask = np.pad(mask, ((0, rows - n), (0, width - mask.shape[1])))
        out = self._fwd(self.params, jnp.asarray(ids), jnp.asarray(mask))
        return np.asarray(out, np.float32)[:n]

    def embed_device(self, texts: list[str]):
        """Embed → [n, D] array WITHOUT a blocking host download. The dense
        retrieval leg chains this straight into the index's top-k program so
        the query vector never makes a host round trip — on remote-attached
        devices each blocking transfer costs ~RTT, which dominated the
        retrieve leg before this path existed.

        Single-query calls (the /chat hot path — one worker thread per
        request) coalesce across threads through a deadline batcher so
        concurrent requests share ONE padded device batch; multi-text calls
        are already a batch and dispatch directly.

        Cache contract matches :meth:`embed_many`: full-hit batches return
        cached host vectors (no device work at all); misses compute on
        device and the cache is populated from a BACKGROUND thread so the
        fetch never blocks this request."""
        cached = [self.cache.get(t) for t in texts]
        if all(c is not None for c in cached):
            self.stats["cache_hits"] = self.stats.get("cache_hits", 0) + len(texts)
            return np.stack(cached).astype(np.float32)

        if len(texts) == 1 and self._query_batcher is not None:
            return self._query_batcher.submit(texts[0])
        return self._embed_device_batch(texts)

    def _embed_device_batch(self, texts: list[str]):
        import jax.numpy as jnp

        from sentio_tpu.models.tokenizer import batch_encode
        from sentio_tpu.parallel.batcher import bucket_size

        ids, mask = batch_encode(
            self.tokenizer, texts, max_len=min(self.config.max_tokens, self.model_config.max_len)
        )
        n = ids.shape[0]
        width = bucket_size(ids.shape[1], self.BUCKETS)
        rows = bucket_size(n, self.BATCH_BUCKETS)
        ids = np.pad(
            ids, ((0, rows - n), (0, width - ids.shape[1])),
            constant_values=self.tokenizer.pad_id,
        )
        mask = np.pad(mask, ((0, rows - n), (0, width - mask.shape[1])))
        out = self._fwd(self.params, jnp.asarray(ids), jnp.asarray(mask))[:n]

        if self.cache.max_size > 0:  # cache off → skip the device download

            def fill_cache() -> None:
                try:
                    host = np.asarray(out, np.float32)  # device fetch can fail
                    for text, vec in zip(texts, host):
                        self.cache.put(text, vec)
                except Exception as exc:  # best-effort, but never silent
                    logger.warning("embed_device background cache fill failed: %s", exc)

            threading.Thread(target=fill_cache, name="embedder-cache-fill",
                             daemon=True).start()
        return out


_PROVIDERS = {"hash": HashEmbedder, "tpu": TpuEmbedder}


def get_embedder(config: Optional[EmbedderConfig] = None, **kwargs) -> BaseEmbedder:
    """Provider registry (reference: embeddings/factory.py:55-120). Unknown
    providers fall back to ``hash`` like the reference falls back to jina."""
    config = config or get_settings().embedder
    cls = _PROVIDERS.get(config.provider, HashEmbedder)
    return cls(config, **kwargs) if cls is TpuEmbedder else cls(config)
