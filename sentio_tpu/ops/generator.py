"""LLMGenerator: citation-grounded answer generation over the TPU engine.

Parity with /root/reference/src/core/llm/generator.py:19-333 and
chat_adapter.py:29-94: numbered ``[n] Source … score`` context assembly with
an instruction footer, temperature-by-mode (fast/balanced/quality/creative =
0.0/0.3/0.2/0.7), sync + streaming paths, and a provider seam — the exact
swap point the reference used for OpenAI-compatible APIs — now dispatching
to the in-process :class:`GeneratorEngine`. An ``echo`` provider is the
deterministic offline fake (the reference's mock-mode test pattern).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Iterator, Optional, Protocol, Sequence

from sentio_tpu.config import GeneratorConfig, get_settings
from sentio_tpu.models.document import Document
from sentio_tpu.ops.prompts import PromptBuilder


class ChatProvider(Protocol):
    """``request_id`` is the flight-recorder trace id (serving layer's
    query_id); providers that have no engine-side telemetry ignore it. The
    generator only forwards it when set, so minimal third-party/test
    providers without the kwarg keep working untraced."""

    name: str

    def chat(
        self, prompt: str, max_new_tokens: int, temperature: float,
        request_id: Optional[str] = None,
    ) -> str: ...
    def stream(
        self, prompt: str, max_new_tokens: int, temperature: float,
        request_id: Optional[str] = None,
    ) -> Iterator[str]: ...


@dataclass
class EchoProvider:
    """Deterministic fake: answers by quoting the top source. Lets the whole
    pipeline (graph, API, CLI, tests) run with zero hardware and stable
    output, like the reference's hash-mock embedder did for embeddings."""

    name: str = "echo"

    def chat(self, prompt: str, max_new_tokens: int, temperature: float,
             request_id: Optional[str] = None) -> str:
        line = ""
        for cand in prompt.splitlines():
            if cand.strip().startswith("[1]"):
                line = cand.strip()
                break
        if line:
            return f"Based on the provided sources, the most relevant finding is: {line}"
        return "No sources were provided, so no grounded answer is available."

    def stream(self, prompt: str, max_new_tokens: int, temperature: float,
               request_id: Optional[str] = None) -> Iterator[str]:
        text = self.chat(prompt, max_new_tokens, temperature)
        for i in range(0, len(text), 16):
            yield text[i : i + 16]


@dataclass
class TpuProvider:
    """Dispatches to the in-process TPU runtime. With a ``service`` (the
    continuous-batching pump over the paged KV pool) attached, every chat
    call joins the SHARED decode batch — concurrent requests coalesce on
    device instead of serializing (closes the round-1 gap where
    runtime/paged.py was dead code). The contiguous ``engine`` remains the
    streaming path and the fallback when paged decode is disabled."""

    engine: object = None  # GeneratorEngine
    service: object = None  # PagedGenerationService (continuous batching)
    # SpeculativeDecoder: draft-accelerated decode on the contiguous path
    # (greedy calls bit-exact, sampled calls distribution-exact via
    # rejection-sampling acceptance)
    speculative: object = None
    name: str = "tpu"

    def _tenant_kwargs(self, tenant: Optional[str],
                       priority: Optional[str]) -> dict:
        """Tenant/priority kwargs, only when the attached service is the
        multi-replica tier (a bare PagedGenerationService takes neither)."""
        if not getattr(self.service, "supports_tenants", False):
            return {}
        out: dict = {}
        if tenant is not None:
            out["tenant"] = tenant
        if priority is not None:
            out["priority"] = priority
        return out

    @staticmethod
    def _fill_stats(stats: Optional[dict], result) -> None:
        """Copy a PagedResult's logprob accumulators into the caller's
        stats dict (the confidence gate's signal — ops/confidence.py)."""
        if stats is not None:
            stats.update(result.stats_dict())

    def _stream_takes(self, kwarg: str) -> bool:
        """Whether the attached service's ``generate_stream`` accepts
        ``kwarg`` — introspected ONCE per provider per kwarg, not per
        streamed request (the probe sits on the hot path)."""
        cache = getattr(self, "_stream_kwarg_ok", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_stream_kwarg_ok", cache)
        cached = cache.get(kwarg)
        if cached is None:
            import inspect

            try:
                cached = kwarg in inspect.signature(
                    self.service.generate_stream).parameters
            except (TypeError, ValueError):
                cached = False
            cache[kwarg] = cached
        return cached

    def _stream_takes_stats(self) -> bool:
        return self._stream_takes("stats_out")

    def chat(self, prompt: str, max_new_tokens: int, temperature: float,
             request_id: Optional[str] = None,
             deadline_ts: Optional[float] = None,
             tenant: Optional[str] = None,
             priority: Optional[str] = None,
             stats: Optional[dict] = None) -> str:
        if self.service is not None:
            try:
                result = self.service.generate(
                    prompt, max_new_tokens=max_new_tokens, temperature=temperature,
                    request_id=request_id, deadline_ts=deadline_ts,
                    **self._tenant_kwargs(tenant, priority),
                )
                if result.finish_reason != "error":
                    self._fill_stats(stats, result)
                    return result.text
            except Exception as exc:  # noqa: BLE001 — contiguous engine is the escape hatch
                if getattr(exc, "soft_fail_exempt", False):
                    # shed / expired deadline: retrying on the contiguous
                    # engine would serve a caller that gave up (or double
                    # the load the shed was protecting against) — fail fast
                    raise
                if self.engine is None:
                    raise
            if self.engine is None:
                raise RuntimeError("paged decode failed and no contiguous engine")
        if self.speculative is not None:
            # greedy calls are bit-exact, sampled calls distribution-exact
            # (rejection-sampling acceptance) — both legitimately served by
            # the draft-accelerated path
            return self.speculative.generate(
                [prompt], max_new_tokens=max_new_tokens, temperature=temperature
            )[0].text
        result = self.engine.generate(
            [prompt], max_new_tokens=max_new_tokens, temperature=temperature
        )[0]
        return result.text

    def stream(self, prompt: str, max_new_tokens: int, temperature: float,
               request_id: Optional[str] = None,
               deadline_ts: Optional[float] = None,
               tenant: Optional[str] = None,
               priority: Optional[str] = None,
               stats: Optional[dict] = None,
               resumable: Optional[bool] = None) -> Iterator[str]:
        if self.service is not None and hasattr(self.service, "generate_stream"):
            yielded_any = False
            stream_kwargs = self._tenant_kwargs(tenant, priority)
            if stats is not None and self._stream_takes_stats():
                # only our own service implementations take stats_out; a
                # test fake with the bare generate_stream signature keeps
                # working (the gate then sees no logprobs and never skips)
                stream_kwargs["stats_out"] = stats
            if resumable is False and self._stream_takes("resumable"):
                # per-request opt-out of resume-by-replay (PR 14's knob,
                # ReplicaSet.generate_stream): a mid-stream replica death
                # then keeps the typed mid-stream error. Only the replica
                # tier takes it; bare services have nothing to resume.
                stream_kwargs["resumable"] = False
            try:
                for piece in self.service.generate_stream(
                    prompt, max_new_tokens=max_new_tokens, temperature=temperature,
                    request_id=request_id, deadline_ts=deadline_ts,
                    **stream_kwargs,
                ):
                    yielded_any = True
                    yield piece
                return
            except Exception as exc:  # noqa: BLE001 — contiguous engine is the escape hatch
                # restarting after partial output would duplicate the
                # answer; typed shed/deadline errors must not be retried
                if (yielded_any or self.engine is None
                        or getattr(exc, "soft_fail_exempt", False)):
                    raise
        yield from self.engine.stream(
            prompt, max_new_tokens=max_new_tokens, temperature=temperature
        )


@dataclass
class OpenAIProvider:
    """OpenAI-compatible remote chat provider — the pluggable alternative the
    reference keeps as its primary path (/root/reference/src/core/llm/
    providers/openai.py:44-314: httpx client against ``{base_url}/chat/
    completions``, bearer auth, retry loop, SSE streaming). Here it is the
    FALLBACK seam: the default provider is the in-process TPU engine, and
    this adapter exists for split deployments (retrieval on the TPU host,
    generation on a remote endpoint) and for measuring the API-baseline
    configs in eval/. Zero-egress images point it at loopback mocks."""

    base_url: str = "http://127.0.0.1:8000/v1"
    api_key: str = ""
    model: str = "default"
    timeout_s: float = 60.0
    max_retries: int = 2
    name: str = "openai"
    # endpoint-reported (or locally counted) token usage of the last
    # successful chat(); empty before the first call
    last_usage: dict = field(default_factory=dict)
    # guards base_url switches + client/retired-client bookkeeping: chat()
    # runs on concurrent worker threads, and unguarded 404 fallbacks could
    # flap base_url back and forth or drop a pooled client unclosed
    _lock: threading.Lock = field(
        default_factory=threading.Lock, init=False, repr=False, compare=False
    )

    def _client(self):
        """One pooled httpx.Client per provider — reused across calls and
        retries (a client per request would pay a TCP/TLS handshake each).
        Double-checked under the lock so two racing first calls cannot each
        build a client and strand one unclosed."""
        client = getattr(self, "_client_cached", None)
        if client is None:
            with self._lock:
                client = getattr(self, "_client_cached", None)
                if client is None:
                    import httpx

                    headers = {"Content-Type": "application/json"}
                    if self.api_key:
                        headers["Authorization"] = f"Bearer {self.api_key}"
                    client = httpx.Client(
                        base_url=self.base_url.rstrip("/"),
                        timeout=self.timeout_s, headers=headers,
                    )
                    object.__setattr__(self, "_client_cached", client)
        return client

    def close(self) -> None:
        with self._lock:
            doomed = []
            client = getattr(self, "_client_cached", None)
            if client is not None:
                doomed.append(client)
                object.__setattr__(self, "_client_cached", None)
            doomed.extend(getattr(self, "_retired_clients", []))
            object.__setattr__(self, "_retired_clients", [])
        for old in doomed:
            old.close()

    def _payload(self, prompt: str, max_new_tokens: int, temperature: float) -> dict:
        return {
            "model": self.model,
            "messages": [{"role": "user", "content": prompt}],
            "max_tokens": max_new_tokens,
            "temperature": temperature,
        }

    def _alt_base(self) -> Optional[str]:
        """OpenRouter-style deployments vary between ``…/api/v1`` and
        ``…/v1`` (reference openai.py:124-144 there). A 404 on a base URL
        whose PATH contains ``/api`` gets ONE retry against the stripped
        base; a hit permanently switches the client. Only the path is
        rewritten — an ``api.`` hostname must survive untouched."""
        from urllib.parse import urlsplit, urlunsplit

        parts = urlsplit(self.base_url)
        if "/api/" in parts.path or parts.path.endswith("/api"):
            new_path = parts.path.replace("/api", "", 1)
            return urlunsplit(parts._replace(path=new_path))
        return None

    def _switch_base(self, new_base: str,
                     only_from: Optional[str] = None) -> bool:
        """Rebind the base URL WITHOUT closing the old client: concurrent
        serving threads may have requests in flight on it (closing would
        fail them mid-call). Superseded clients park until close().

        Compare-and-swap under the lock: with ``only_from`` set, the switch
        happens only while ``base_url`` still holds that value — a thread
        whose 404 raced another thread's already-completed fallback becomes
        a no-op instead of re-switching (or re-reverting) the URL out from
        under everyone. Returns whether THIS call performed the switch."""
        with self._lock:
            if only_from is not None and self.base_url != only_from:
                return False
            if self.base_url == new_base:
                return False
            old = getattr(self, "_client_cached", None)
            if old is not None:
                retired = getattr(self, "_retired_clients", None)
                if retired is None:
                    retired = []
                    object.__setattr__(self, "_retired_clients", retired)
                retired.append(old)
                object.__setattr__(self, "_client_cached", None)
            object.__setattr__(self, "base_url", new_base)
            return True

    def count_tokens(self, text: str) -> int:
        """Token estimate for budget math when the endpoint returns no
        ``usage`` block (reference openai.py:251-269 there). tiktoken when
        present; a words×4/3 estimate otherwise (not in the base image)."""
        try:
            import tiktoken  # noqa: PLC0415 — optional, absent in base image

            return len(tiktoken.encoding_for_model(self.model).encode(text))
        except Exception:  # noqa: BLE001 — any failure degrades to estimate
            return max(int(len(text.split()) * 4 / 3), 1)

    def _note_usage(self, body: dict, prompt: str, reply: str,
                    latency_s: float) -> None:
        """Publish token counts to /metrics — endpoint-reported ``usage``
        when present (a reported 0 is honored), counted locally otherwise."""
        from sentio_tpu.infra.metrics import get_metrics

        usage = body.get("usage") or {}
        completion = usage.get("completion_tokens")
        if completion is None:
            completion = self.count_tokens(reply)
        prompt_toks = usage.get("prompt_tokens")
        if prompt_toks is None:
            prompt_toks = self.count_tokens(prompt)
        object.__setattr__(self, "last_usage", {
            "prompt_tokens": int(prompt_toks),
            "completion_tokens": int(completion),
        })
        get_metrics().record_llm("remote_chat", latency_s, tokens=int(completion))

    def chat(self, prompt: str, max_new_tokens: int, temperature: float,
             request_id: Optional[str] = None) -> str:
        import random
        import time

        last_exc: Exception | None = None
        for attempt in range(self.max_retries + 1):
            try:
                t0 = time.perf_counter()
                resp = self._client().post(
                    "/chat/completions",
                    json=self._payload(prompt, max_new_tokens, temperature),
                )
                if resp.status_code == 404 and not str(
                    resp.request.url
                ).startswith(self.base_url.rstrip("/")):
                    # raced a concurrent thread's fallback switch: this 404
                    # came from the RETIRED base — re-issue on the current
                    # client instead of failing the call hard
                    resp = self._client().post(
                        "/chat/completions",
                        json=self._payload(prompt, max_new_tokens, temperature),
                    )
                alt = self._alt_base() if resp.status_code == 404 else None
                if alt:
                    old = self.base_url
                    switched = self._switch_base(alt, only_from=old)
                    try:
                        resp = self._client().post(
                            "/chat/completions",
                            json=self._payload(prompt, max_new_tokens, temperature),
                        )
                    except Exception:
                        # probe blew up before any status — the switch is
                        # unverified, keep the configured base (but only if
                        # WE switched: a concurrent thread's verified switch
                        # must not be reverted by our failed probe)
                        if switched:
                            self._switch_base(old, only_from=alt)
                        raise
                    if resp.status_code >= 400 and switched:
                        # the alternate is no better — undo the switch so a
                        # genuinely-404 deployment keeps its configured base
                        self._switch_base(old, only_from=alt)
                resp.raise_for_status()
                body = resp.json()
                reply = body["choices"][0]["message"]["content"]
                self._note_usage(body, prompt, reply, time.perf_counter() - t0)
                return reply
            except Exception as exc:  # noqa: BLE001 — retry transport/5xx/429
                status = getattr(getattr(exc, "response", None), "status_code", None)
                if status is not None and 400 <= status < 500 and status != 429:
                    raise  # auth/config errors don't heal with retries
                last_exc = exc
                if attempt < self.max_retries:
                    time.sleep(min(2.0**attempt, 4.0) * (0.5 + random.random() / 2))
        raise RuntimeError(f"openai provider failed after {self.max_retries + 1} attempts") from last_exc

    def stream(self, prompt: str, max_new_tokens: int, temperature: float,
               request_id: Optional[str] = None) -> Iterator[str]:
        """SSE stream (``data: {...}`` lines, ``[DONE]`` sentinel). Falls back
        to one non-streaming call if the endpoint rejects stream=True."""
        import json as _json

        payload = {**self._payload(prompt, max_new_tokens, temperature), "stream": True}
        saw_sse = False
        try:
            body_lines: list[str] = []
            with self._client().stream(
                "POST", "/chat/completions", json=payload
            ) as resp:
                resp.raise_for_status()
                for line in resp.iter_lines():
                    if not line.startswith("data:"):
                        body_lines.append(line)
                        continue
                    saw_sse = True
                    data = line[len("data:"):].strip()
                    if data == "[DONE]":
                        return
                    try:
                        delta = _json.loads(data)["choices"][0]["delta"]
                    except (KeyError, IndexError, ValueError):
                        continue
                    chunk = delta.get("content")
                    if chunk:
                        yield chunk
            if not saw_sse:
                # endpoint ignored stream=True and sent one JSON completion
                reply = _json.loads("\n".join(body_lines))
                yield reply["choices"][0]["message"]["content"]
        except Exception:  # noqa: BLE001 — endpoints without SSE support
            if saw_sse:
                # the stream broke mid-answer — surfacing a silently
                # truncated reply as complete would be worse than failing
                raise
            yield self.chat(prompt, max_new_tokens, temperature)

    @classmethod
    def from_config(cls, cfg: GeneratorConfig) -> "OpenAIProvider":
        return cls(
            base_url=cfg.api_base or cls.base_url,
            api_key=cfg.api_key,
            model=cfg.api_model or cls.model,
            timeout_s=cfg.api_timeout_s,
        )


_PROVIDERS: dict[str, type] = {}


def register_provider(name: str):
    """Decorator registry (reference: llm/providers/__init__.py:12-41)."""

    def deco(cls):
        _PROVIDERS[name] = cls
        return cls

    return deco


register_provider("echo")(EchoProvider)
register_provider("tpu")(TpuProvider)
register_provider("openai")(OpenAIProvider)


def get_provider(name: str, **kwargs):
    cls = _PROVIDERS.get(name)
    if cls is None:
        raise ValueError(f"unknown LLM provider {name!r}; known: {sorted(_PROVIDERS)}")
    return cls(**kwargs)


@dataclass
class LLMGenerator:
    provider: ChatProvider = field(default_factory=EchoProvider)
    config: GeneratorConfig = field(default_factory=lambda: get_settings().generator)
    prompts: PromptBuilder = field(default_factory=PromptBuilder)

    # ---------------------------------------------------------- context build

    def prepare_context(self, documents: Sequence[Document]) -> str:
        """Numbered, citation-ready context block (reference
        generator.py:193-254): '[n] Source: … (score …)' headers + text."""
        if not documents:
            return "(no context documents)"
        blocks = []
        for i, doc in enumerate(documents, start=1):
            source = doc.metadata.get("source") or doc.metadata.get("source_file") or doc.id
            score = doc.score()
            header = f"[{i}] Source: {source} (score {score:.3f})"
            blocks.append(f"{header}\n{doc.content.strip()}")
        return "\n\n".join(blocks)

    def build_prompt(self, query: str, documents: Sequence[Document]) -> str:
        instruction = self.prompts.load("profile")
        context = self.prepare_context(documents)
        return self.prompts.build("retrieve", instruction=instruction, context=context, query=query)

    # ------------------------------------------------------------- generation

    def _method_accepts(self, method: str, kwarg: str) -> bool:
        """Whether the provider's ``method`` takes ``kwarg`` — externally
        registered providers with older signatures must keep working
        (untraced / deadline-blind) instead of TypeError-ing into the
        degradation ladder on all traffic. Introspected once per
        (method, kwarg)."""
        cache = getattr(self, "_accepts_kwarg", None)
        if cache is None:
            cache = self._accepts_kwarg = {}
        key = (method, kwarg)
        accepts = cache.get(key)
        if accepts is None:
            import inspect

            try:
                params = inspect.signature(getattr(self.provider, method)).parameters
                accepts = kwarg in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
                )
            except (TypeError, ValueError):  # builtins/C callables: assume yes
                accepts = True
            cache[key] = accepts
        return accepts

    def _trace_kwargs(
        self, method: str, request_id: Optional[str],
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        stats: Optional[dict] = None,
        resumable: Optional[bool] = None,
    ) -> dict:
        """The optional per-request context kwargs (trace id, absolute
        deadline, WFQ tenant key + priority tier, confidence-stats sink,
        stream-resumption opt-out) the provider's method is able to
        receive. ``resumable`` is forwarded only on opt-OUT (False) —
        True is every layer's default, so omitting it keeps minimal
        test/third-party providers working."""
        out: dict = {}
        if request_id and self._method_accepts(method, "request_id"):
            out["request_id"] = request_id
        if deadline_ts is not None and self._method_accepts(method, "deadline_ts"):
            out["deadline_ts"] = deadline_ts
        if tenant is not None and self._method_accepts(method, "tenant"):
            out["tenant"] = tenant
        if priority is not None and self._method_accepts(method, "priority"):
            out["priority"] = priority
        if stats is not None and self._method_accepts(method, "stats"):
            out["stats"] = stats
        if resumable is False and self._method_accepts(method, "resumable"):
            out["resumable"] = False
        return out

    def generate(
        self,
        query: str,
        documents: Sequence[Document],
        mode: Optional[str] = None,
        temperature: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        request_id: Optional[str] = None,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        stats: Optional[dict] = None,
    ) -> str:
        prompt = self.build_prompt(query, documents)
        temp = temperature if temperature is not None else self.config.temperature(mode)
        return self.provider.chat(
            prompt,
            max_new_tokens=max_new_tokens or self.config.max_new_tokens,
            temperature=temp,
            **self._trace_kwargs("chat", request_id, deadline_ts,
                                 tenant, priority, stats),
        )

    def stream(
        self,
        query: str,
        documents: Sequence[Document],
        mode: Optional[str] = None,
        temperature: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        request_id: Optional[str] = None,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        stats: Optional[dict] = None,
        resumable: Optional[bool] = None,
    ) -> Iterator[str]:
        prompt = self.build_prompt(query, documents)
        temp = temperature if temperature is not None else self.config.temperature(mode)
        yield from self.provider.stream(
            prompt,
            max_new_tokens=max_new_tokens or self.config.max_new_tokens,
            temperature=temp,
            **self._trace_kwargs("stream", request_id, deadline_ts,
                                 tenant, priority, stats, resumable),
        )

    def chat_raw(self, prompt: str, max_new_tokens: int, temperature: float,
                 request_id: Optional[str] = None,
                 deadline_ts: Optional[float] = None,
                 tenant: Optional[str] = None,
                 priority: Optional[str] = None) -> str:
        """Direct provider access (verifier path — shares the weights). A
        ``request_id`` ties the call into the flight recorder, so the
        verify node's engine admission shows up on the same trace as the
        generate node's; ``tenant``/``priority`` charge the verify decode
        to the REQUESTING tenant's WFQ quota instead of the shared default
        (a tenant's verify traffic must not ride free and starve others)."""
        return self.provider.chat(
            prompt, max_new_tokens=max_new_tokens, temperature=temperature,
            **self._trace_kwargs("chat", request_id, deadline_ts,
                                 tenant, priority),
        )


def create_generator(
    settings=None,
    engine=None,
    service=None,
    speculative=None,
) -> LLMGenerator:
    """env→generator wiring (reference: llm/factory.py:14-69)."""
    settings = settings or get_settings()
    cfg = settings.generator
    if cfg.provider == "tpu" and engine is not None:
        provider = TpuProvider(engine=engine, service=service, speculative=speculative)
    elif cfg.provider == "tpu":
        # no engine supplied (tests, host-only dev) → deterministic echo
        provider = EchoProvider()
    elif cfg.provider == "openai":
        provider = OpenAIProvider.from_config(cfg)
    else:
        provider = get_provider(cfg.provider)
    return LLMGenerator(provider=provider, config=cfg)
