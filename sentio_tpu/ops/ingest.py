"""Document ingestion: load → chunk → embed → index.

Parity with /root/reference/src/core/ingest/ingest.py:20-529 (multi-format
readers :172-223, recursive directory loader :225-289, batched embedding
keyed by chunk id :291-334, store upsert :336-392, single-doc path for
``/embed`` :460-488, stats :62-67) — rebuilt around in-process TPU compute:
the embed step batches whole chunk lists through the bi-encoder in one
device dispatch per ``batch_size`` (the reference pays one HTTPS round trip
per ≤100-chunk batch), and "the store" is the in-HBM :class:`TpuDenseIndex`
plus the host-side BM25 postings — there is no external vector database in
the hot path.

Format support: txt/md/rst (raw), json/jsonl (text-field extraction),
yaml, html/htm (stdlib tag stripping), csv/tsv, docx (stdlib zipfile +
XML — no python-docx needed), pdf (gated: needs an extractor lib the base
image doesn't ship; a clear error tells the operator).
"""

from __future__ import annotations

import csv
import io
import json
import logging
import re
import threading
import time
import zipfile
from dataclasses import dataclass, field
from html.parser import HTMLParser
from pathlib import Path
from typing import Iterable, Optional, Sequence

import numpy as np

from sentio_tpu.config import Settings, get_settings
from sentio_tpu.models.document import Document

logger = logging.getLogger(__name__)

__all__ = [
    "IngestError",
    "IngestStats",
    "DocumentIngestor",
    "ingest_directory",
    "SUPPORTED_SUFFIXES",
]


class IngestError(Exception):
    pass


SUPPORTED_SUFFIXES = (
    ".txt", ".md", ".rst", ".json", ".jsonl", ".yaml", ".yml",
    ".html", ".htm", ".csv", ".tsv", ".docx", ".pdf",
)


class _TextExtractor(HTMLParser):
    """Collects visible text, skipping script/style (reference ingests HTML
    via its loader at ingest.py:196-204 there)."""

    _SKIP = {"script", "style", "noscript"}

    def __init__(self) -> None:
        super().__init__()
        self.parts: list[str] = []
        self._skip_depth = 0

    def handle_starttag(self, tag, attrs):
        if tag in self._SKIP:
            self._skip_depth += 1

    def handle_endtag(self, tag):
        if tag in self._SKIP and self._skip_depth:
            self._skip_depth -= 1

    def handle_data(self, data):
        if not self._skip_depth and data.strip():
            self.parts.append(data.strip())


def _read_html(raw: str) -> str:
    parser = _TextExtractor()
    parser.feed(raw)
    return "\n".join(parser.parts)


def _read_json(raw: str) -> str:
    """Flatten all string leaves — same spirit as the reference's JSON loader
    (ingest.py:186-195 there), which joins textual fields."""

    def walk(node) -> Iterable[str]:
        if isinstance(node, str):
            if node.strip():
                yield node.strip()
        elif isinstance(node, dict):
            for v in node.values():
                yield from walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                yield from walk(v)

    return "\n".join(walk(json.loads(raw)))


def _read_jsonl(raw: str) -> str:
    parts = []
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            parts.append(_read_json(line))
        except json.JSONDecodeError:
            parts.append(line)
    return "\n".join(parts)


def _read_yaml(raw: str) -> str:
    try:
        import yaml

        docs = list(yaml.safe_load_all(raw))
    except Exception:  # noqa: BLE001 — yaml missing or invalid: treat as plain text
        return raw

    def walk(node) -> Iterable[str]:
        if isinstance(node, str):
            if node.strip():
                yield node.strip()
        elif isinstance(node, dict):
            for k, v in node.items():
                yield from walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                yield from walk(v)

    return "\n".join(p for d in docs for p in walk(d))


def _read_csv(raw: str, delimiter: str = ",") -> str:
    rows = csv.reader(io.StringIO(raw), delimiter=delimiter)
    return "\n".join(" ".join(cell for cell in row if cell.strip()) for row in rows)


_DOCX_TAG = re.compile(r"<[^>]+>")


def _read_docx(path: Path) -> str:
    """DOCX is a zip of XML; paragraph text lives in ``word/document.xml``
    under ``<w:t>`` runs. Stdlib-only replacement for the reference's
    python-docx loader (ingest.py:205-214 there)."""
    try:
        with zipfile.ZipFile(path) as zf:
            xml = zf.read("word/document.xml").decode("utf-8", errors="replace")
    except (zipfile.BadZipFile, KeyError) as exc:
        raise IngestError(f"not a valid docx file: {path}") from exc
    paragraphs = []
    for para in re.split(r"</w:p>", xml):
        runs = re.findall(r"<w:t[^>]*>(.*?)</w:t>", para, flags=re.S)
        text = _DOCX_TAG.sub("", "".join(runs)).strip()
        if text:
            paragraphs.append(text)
    return "\n".join(paragraphs)


def _read_pdf(path: Path) -> str:
    try:
        import PyPDF2  # noqa: F401 — gated: not in the base image
    except ImportError as exc:
        raise IngestError(
            f"PDF ingestion for {path.name} needs PyPDF2 (not installed in "
            "this image); convert to text/markdown first"
        ) from exc
    reader = PyPDF2.PdfReader(str(path))
    return "\n".join(page.extract_text() or "" for page in reader.pages)


@dataclass
class IngestStats:
    """Mirrors the reference's stats dict (ingest.py:62-67 there)."""

    documents_loaded: int = 0
    chunks_created: int = 0
    chunks_embedded: int = 0
    chunks_stored: int = 0
    files_skipped: int = 0
    errors: list[str] = field(default_factory=list)
    elapsed_s: float = 0.0

    def to_dict(self) -> dict:
        return {
            "documents_loaded": self.documents_loaded,
            "chunks_created": self.chunks_created,
            "chunks_embedded": self.chunks_embedded,
            "chunks_stored": self.chunks_stored,
            "files_skipped": self.files_skipped,
            "errors": self.errors,
            "elapsed_s": round(self.elapsed_s, 3),
        }


class DocumentIngestor:
    """load → chunk → embed (batched device dispatch) → index.

    Components are injected so the serving container shares one embedder and
    one index across ingest + retrieval (the reference's shared-component
    init, ingest.py:125-170 there). ``sparse_index`` is rebuilt after each
    ingest batch — BM25 postings build at millions of tokens/s host-side, so
    rebuild beats incremental bookkeeping at NQ scale.
    """

    def __init__(
        self,
        chunker=None,
        embedder=None,
        dense_index=None,
        sparse_index=None,
        settings: Optional[Settings] = None,
    ) -> None:
        self.settings = settings or get_settings()
        self._chunker = chunker
        self._embedder = embedder
        self._dense_index = dense_index
        self._sparse_index = sparse_index
        self.stats = IngestStats()  # lifetime totals; per-call stats are returned
        # index mutation (dense add + sparse rebuild) is multi-step and not
        # atomic — concurrent /embed requests serialize here
        self._write_lock = threading.Lock()

    # ------------------------------------------------------------ components

    @property
    def chunker(self):
        if self._chunker is None:
            from sentio_tpu.ops.chunking import TextChunker

            self._chunker = TextChunker(config=self.settings.chunking)
        return self._chunker

    @property
    def embedder(self):
        if self._embedder is None:
            from sentio_tpu.ops.embedder import get_embedder

            self._embedder = get_embedder(self.settings.embedder)
        return self._embedder

    @property
    def dense_index(self):
        if self._dense_index is None:
            # through the registry so INDEX_BACKEND=qdrant ingests into the
            # same external store the serving pods retrieve from — a local
            # default here would silently ingest into a process-private index
            from sentio_tpu.ops.vector_store import get_vector_store

            self._dense_index = get_vector_store(
                self.settings.retrieval.index_backend,
                dim=self.embedder.dimension,
                settings=self.settings,
            )
        return self._dense_index

    # ----------------------------------------------------------------- load

    def load_file(self, path: str | Path) -> list[Document]:
        """One file → one Document (pre-chunking), with source metadata."""
        path = Path(path)
        if not path.is_file():
            raise IngestError(f"not a file: {path}")
        suffix = path.suffix.lower()
        if suffix == ".docx":
            text = _read_docx(path)
        elif suffix == ".pdf":
            text = _read_pdf(path)
        else:
            raw = path.read_text(encoding="utf-8", errors="replace")
            if suffix in (".html", ".htm"):
                text = _read_html(raw)
            elif suffix == ".json":
                try:
                    text = _read_json(raw)
                except json.JSONDecodeError:
                    text = raw
            elif suffix == ".jsonl":
                text = _read_jsonl(raw)
            elif suffix in (".yaml", ".yml"):
                text = _read_yaml(raw)
            elif suffix == ".csv":
                text = _read_csv(raw)
            elif suffix == ".tsv":
                text = _read_csv(raw, delimiter="\t")
            else:  # txt/md/rst and any other text-like file
                text = raw
        text = text.strip()
        if not text:
            return []
        return [
            Document(
                text=text,
                metadata={"source": str(path), "filename": path.name, "format": suffix.lstrip(".")},
            )
        ]

    def load_directory(
        self, path: str | Path, recursive: bool = True, suffixes: Optional[Sequence[str]] = None
    ) -> list[Document]:
        """Glob loader (reference: recursive ``**/*`` walk, ingest.py:225-289
        there). Unsupported/failed files are counted, not fatal."""
        path = Path(path)
        if not path.is_dir():
            raise IngestError(f"not a directory: {path}")
        allowed = tuple(suffixes) if suffixes else SUPPORTED_SUFFIXES
        pattern = "**/*" if recursive else "*"
        docs: list[Document] = []
        for file in sorted(path.glob(pattern)):
            if not file.is_file():
                continue
            if file.suffix.lower() not in allowed:
                self.stats.files_skipped += 1
                continue
            try:
                docs.extend(self.load_file(file))
            except (IngestError, OSError) as exc:
                logger.warning("skipping %s: %s", file, exc)
                self.stats.errors.append(f"{file.name}: {exc}")
                self.stats.files_skipped += 1
        return docs

    # ---------------------------------------------------------------- ingest

    def ingest_documents(self, documents: Sequence[Document]) -> IngestStats:
        """Chunk, embed (device-batched), and index a document list. Empty
        chunks are dropped before embedding (reference: ingest.py:291-334).
        Returns THIS call's stats; lifetime totals accumulate on ``.stats``."""
        t0 = time.perf_counter()
        call = IngestStats(documents_loaded=len(documents))

        chunks = self.chunker.split(list(documents))
        chunks = [c for c in chunks if c.text.strip()]
        call.chunks_created = len(chunks)
        if chunks:
            vecs = self.embedder.embed_many([c.text for c in chunks])
            vecs = np.asarray(vecs, np.float32)
            call.chunks_embedded = len(chunks)

            with self._write_lock:
                self.dense_index.add(chunks, vecs)
                if self._sparse_index is not None:
                    self._sparse_index.build(self.dense_index.documents())
            call.chunks_stored = len(chunks)
        call.elapsed_s = time.perf_counter() - t0
        self._accumulate(call)
        return call

    def _accumulate(self, call: IngestStats) -> None:
        s = self.stats
        s.documents_loaded += call.documents_loaded
        s.chunks_created += call.chunks_created
        s.chunks_embedded += call.chunks_embedded
        s.chunks_stored += call.chunks_stored
        s.elapsed_s += call.elapsed_s

    def ingest_document(self, text: str, metadata: Optional[dict] = None) -> IngestStats:
        """Single in-memory document — the ``POST /embed`` path (reference:
        ingest.py:460-488 there)."""
        doc = Document(text=text, metadata=dict(metadata or {}))
        return self.ingest_documents([doc])

    def ingest_path(self, path: str | Path, recursive: bool = True) -> IngestStats:
        path = Path(path)
        # loader failures land on the lifetime stats; snapshot around the load
        # so THIS call's stats carry its own errors/skips (CLI exit code and
        # /embed responses depend on per-call accuracy)
        err0, skip0 = len(self.stats.errors), self.stats.files_skipped
        docs = self.load_directory(path, recursive=recursive) if path.is_dir() else self.load_file(path)
        call = self.ingest_documents(docs)
        call.errors = self.stats.errors[err0:]
        call.files_skipped = self.stats.files_skipped - skip0
        return call

    def clear(self) -> int:
        """Drop everything from both indexes; returns prior doc count."""
        with self._write_lock:
            n = self.dense_index.size
            self.dense_index.clear()
            if self._sparse_index is not None:
                self._sparse_index.build([])
        return n


def ingest_directory(
    path: str | Path,
    settings: Optional[Settings] = None,
    ingestor: Optional[DocumentIngestor] = None,
    recursive: bool = True,
) -> IngestStats:
    """Convenience used by the CLI (reference: ingest.py:491-529 there)."""
    ingestor = ingestor or DocumentIngestor(settings=settings)
    return ingestor.ingest_path(path, recursive=recursive)
