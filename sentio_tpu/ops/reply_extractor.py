"""Robust JSON-from-LLM extraction.

Parity with /root/reference/src/core/llm/reply_extractor.py:17-80: models
wrap JSON in prose and markdown fences; extraction tries, in order, (1)
fenced ```json blocks, (2) the largest balanced ``{...}`` span, (3) a
trailing-comma/single-quote-tolerant relaxed parse. Never raises — a failed
extraction returns ``None`` payload with the error recorded, because the
verifier contract upstream degrades to ``warn`` rather than failing the
pipeline.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from typing import Any, Optional

_FENCE_RE = re.compile(r"```(?:json)?\s*(\{.*?\})\s*```", re.DOTALL)


@dataclass
class JsonExtractResult:
    payload: Optional[dict[str, Any]]
    raw_span: str = ""
    error: str = ""

    @property
    def ok(self) -> bool:
        return self.payload is not None


def _balanced_spans(text: str) -> list[str]:
    """All top-level balanced {...} spans, largest first, string-aware."""
    spans = []
    depth = 0
    start = -1
    in_str = False
    escape = False
    for i, ch in enumerate(text):
        if escape:
            escape = False
            continue
        if ch == "\\" and in_str:
            escape = True
            continue
        if ch == '"':
            in_str = not in_str
            continue
        if in_str:
            continue
        if ch == "{":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "}" and depth > 0:
            depth -= 1
            if depth == 0 and start >= 0:
                spans.append(text[start : i + 1])
    return sorted(spans, key=len, reverse=True)


def _relaxed_parse(span: str) -> Optional[dict]:
    """Tolerate trailing commas and single-quoted (python-repr-style) JSON."""
    fixed = re.sub(r",\s*([}\]])", r"\1", span)
    try:
        return json.loads(fixed)
    except json.JSONDecodeError:
        pass
    # single-quoted dicts are python literals: literal_eval handles quote
    # nesting correctly where naive regex swapping cannot
    import ast

    for candidate in (fixed, _bare_words_to_python(fixed)):
        try:
            obj = ast.literal_eval(candidate)
        except (ValueError, SyntaxError, MemoryError, RecursionError):
            continue
        if isinstance(obj, dict):
            return obj
    return None


def _bare_words_to_python(span: str) -> str:
    """Rewrite bare true/false/null to True/False/None OUTSIDE string
    literals only — 'the claim is true' inside a value must stay untouched."""
    out: list[str] = []
    i = 0
    quote: Optional[str] = None
    replacements = {"true": "True", "false": "False", "null": "None"}
    while i < len(span):
        ch = span[i]
        if quote is not None:
            out.append(ch)
            if ch == "\\" and i + 1 < len(span):
                out.append(span[i + 1])
                i += 2
                continue
            if ch == quote:
                quote = None
            i += 1
            continue
        if ch in ("'", '"'):
            quote = ch
            out.append(ch)
            i += 1
            continue
        matched = False
        for word, repl in replacements.items():
            end = i + len(word)
            if (
                span[i:end] == word
                and (i == 0 or not (span[i - 1].isalnum() or span[i - 1] == "_"))
                and (end >= len(span) or not (span[end].isalnum() or span[end] == "_"))
            ):
                out.append(repl)
                i = end
                matched = True
                break
        if not matched:
            out.append(ch)
            i += 1
    return "".join(out)


def extract_json_block(text: str) -> JsonExtractResult:
    if not text or not text.strip():
        return JsonExtractResult(None, error="empty reply")

    candidates: list[str] = []
    for m in _FENCE_RE.finditer(text):
        candidates.append(m.group(1))
    candidates.extend(_balanced_spans(text))

    last_err = "no JSON object found"
    for span in candidates:
        try:
            payload = json.loads(span)
        except json.JSONDecodeError as exc:
            payload = _relaxed_parse(span)
            if payload is None:
                last_err = f"JSON parse failed: {exc}"
                continue
        if isinstance(payload, dict):
            return JsonExtractResult(payload, raw_span=span)
        last_err = "top-level JSON was not an object"
    return JsonExtractResult(None, error=last_err)
