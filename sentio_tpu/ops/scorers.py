"""Scorer plugins: post-fusion relevance signals layered on retrieval.

Parity with /root/reference/src/core/retrievers/scorers.py:25-273 — keyword
overlap, recency decay, semantic similarity, and MMR diversification — with
the TPU-native difference called out in SURVEY.md §2.2: the reference
re-embeds every document with one HTTP call each (N+1 calls) and runs an
O(k²) Python cosine loop; here semantic + MMR ride ONE batched embed forward
pass and vectorized numpy cosine matrices (k ≤ ~100 post-fusion, so the
matrix math is host-trivial once embeddings are batched).

Each scorer maps (query, docs) → score per doc in [0, 1]; the hybrid
retriever mixes them into fused scores with per-scorer weights.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field
from typing import Optional, Protocol, Sequence

import numpy as np

from sentio_tpu.models.document import Document


class ScorerPlugin(Protocol):
    name: str
    weight: float

    def score(self, query: str, documents: Sequence[Document]) -> np.ndarray: ...


_EMBED_MEMO: dict = {"key": None, "value": None}


def _doc_embeddings(embedder, query: str, documents: Sequence[Document]):
    """One batched forward for query + all docs → (q_vec, doc_matrix).

    Memoizes the latest call so the semantic and MMR scorers (which run
    back-to-back over the same candidates in the default stack) share a
    single device dispatch instead of embedding everything twice."""
    key = (id(embedder), query, tuple(d.id for d in documents))
    if _EMBED_MEMO["key"] == key:
        return _EMBED_MEMO["value"]
    texts = [query] + [d.content for d in documents]
    vecs = embedder.embed_many(texts)
    result = (vecs[0], vecs[1:])
    _EMBED_MEMO["key"] = key
    _EMBED_MEMO["value"] = result
    return result


@dataclass
class KeywordMatchScorer:
    """Word-overlap fraction between query terms and document text."""

    weight: float = 0.8
    name: str = "keyword"

    def score(self, query: str, documents: Sequence[Document]) -> np.ndarray:
        q_terms = set(re.findall(r"\w+", query.lower()))
        out = np.zeros(len(documents), np.float32)
        if not q_terms:
            return out
        for i, doc in enumerate(documents):
            d_terms = set(re.findall(r"\w+", doc.content.lower()))
            out[i] = len(q_terms & d_terms) / len(q_terms)
        return out


@dataclass
class RecencyScorer:
    """Exponential decay on ``metadata['timestamp']`` (unix seconds); docs
    without a timestamp score the neutral 0.5 (reference behavior)."""

    weight: float = 0.2
    half_life_days: float = 30.0
    name: str = "recency"

    def score(self, query: str, documents: Sequence[Document]) -> np.ndarray:
        now = time.time()  # wall-clock: compared to doc epoch timestamps
        out = np.full(len(documents), 0.5, np.float32)
        half_life_s = self.half_life_days * 86_400.0
        for i, doc in enumerate(documents):
            ts = doc.metadata.get("timestamp")
            if ts is None:
                continue
            try:
                age = max(now - float(ts), 0.0)
            except (TypeError, ValueError):
                continue
            out[i] = float(0.5 ** (age / half_life_s))
        return out


@dataclass
class SemanticSimilarityScorer:
    """Cosine(query, doc) via one batched embed (embeddings are unit-norm),
    mapped from [-1, 1] to [0, 1]."""

    embedder: object = None
    weight: float = 0.5
    name: str = "semantic"

    def score(self, query: str, documents: Sequence[Document]) -> np.ndarray:
        if self.embedder is None or not documents:
            return np.zeros(len(documents), np.float32)
        q_vec, doc_mat = _doc_embeddings(self.embedder, query, documents)
        sims = doc_mat @ q_vec
        return ((sims + 1.0) / 2.0).astype(np.float32)


@dataclass
class MMRScorer:
    """Maximal Marginal Relevance: greedy λ·relevance − (1−λ)·redundancy.
    Returns a rank-based score (first-selected highest) rather than reordering
    in place, so it composes with the other scorers by weight."""

    embedder: object = None
    lambda_param: float = 0.7
    weight: float = 0.5
    name: str = "mmr"

    def score(self, query: str, documents: Sequence[Document]) -> np.ndarray:
        n = len(documents)
        if self.embedder is None or n == 0:
            return np.zeros(n, np.float32)
        q_vec, doc_mat = _doc_embeddings(self.embedder, query, documents)
        rel = doc_mat @ q_vec  # [n]
        sim = doc_mat @ doc_mat.T  # [n, n] — one matrix, not an O(k²) loop
        lam = self.lambda_param

        selected: list[int] = []
        remaining = set(range(n))
        while remaining:
            if not selected:
                best = int(np.argmax([rel[i] for i in sorted(remaining)]))
                best = sorted(remaining)[best]
            else:
                best, best_val = -1, -np.inf
                sel = np.asarray(selected)
                for i in remaining:
                    val = lam * rel[i] - (1.0 - lam) * float(sim[i, sel].max())
                    if val > best_val:
                        best, best_val = i, val
            selected.append(best)
            remaining.discard(best)
        out = np.zeros(n, np.float32)
        for rank, idx in enumerate(selected):
            out[idx] = 1.0 - rank / max(n, 1)
        return out


def default_scorer_stack(embedder, settings) -> list[ScorerPlugin]:
    """The reference's default plugin stack and weights 0.8/0.2/0.5
    (retrievers/factory.py:64-80 there), with MMR λ from config."""
    r = settings.retrieval
    return [
        KeywordMatchScorer(weight=r.keyword_scorer_weight),
        RecencyScorer(weight=r.recency_scorer_weight),
        SemanticSimilarityScorer(embedder=embedder, weight=r.mmr_scorer_weight),
        MMRScorer(embedder=embedder, lambda_param=r.mmr_lambda, weight=r.mmr_scorer_weight),
    ]
