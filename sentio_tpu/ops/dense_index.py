"""TpuDenseIndex — exact MIPS retrieval as a sharded matmul + top-k.

The reference delegates dense retrieval to an external Qdrant server (Rust
HNSW over HTTP, /root/reference/src/core/vector_store/qdrant_store.py:37).
TPU-native, the index is the corpus embedding matrix itself, row-sharded
across every mesh device and resident in HBM: a query batch is one
``[Q, D] @ [D, N_local]`` matmul per device (MXU work), a local top-k, and a
k-sized all-gather — exact search, no ANN recall loss, no server. At
NQ scale (millions of chunks × 1k dims) this is a few GB in bf16 spread over
the mesh, and a query costs ~N·D/mesh FLOPs — microseconds, not HTTP.

Host keeps the float32 master copy + Document store (the "collection");
device array rebuilds lazily after mutation with growth padding so appends
don't recompile every time.
"""

from __future__ import annotations

import json
from functools import partial
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

from sentio_tpu.models.document import Document


class DenseIndexError(Exception):
    pass


class TpuDenseIndex:
    """Exact top-k cosine/MIPS index on the device mesh.

    ``mesh=None`` runs the same code single-device (CPU tests, 1-chip dev).
    Embeddings are L2-normalized at add time, so inner product == cosine.
    """

    def __init__(self, dim: int, mesh=None, dtype: str = "bfloat16") -> None:
        self.dim = dim
        self.mesh = mesh
        self.dtype = dtype
        self._embeddings = np.zeros((0, dim), np.float32)  # host master
        self._documents: list[Document] = []
        self._id_to_row: dict[str, int] = {}
        self._alive = np.zeros(0, bool)
        self._device_state = None  # (padded device array, n_pad) — lazy

    # ------------------------------------------------------------------ crud

    @property
    def size(self) -> int:
        return int(self._alive.sum())

    def documents(self) -> list[Document]:
        """Live documents (the "collection scroll" the reference does against
        Qdrant to hydrate BM25, retrievers/factory.py:83-133 there)."""
        return [doc for doc, ok in zip(self._documents, self._alive) if ok]

    def add(self, documents: Sequence[Document], embeddings: np.ndarray) -> None:
        embeddings = np.asarray(embeddings, np.float32)
        if embeddings.ndim != 2 or embeddings.shape[1] != self.dim:
            raise DenseIndexError(
                f"expected embeddings [N, {self.dim}], got {embeddings.shape}"
            )
        if len(documents) != embeddings.shape[0]:
            raise DenseIndexError("documents/embeddings length mismatch")
        norms = np.linalg.norm(embeddings, axis=1, keepdims=True)
        embeddings = embeddings / np.maximum(norms, 1e-9)
        # duplicate ids within one batch: last write wins (otherwise the
        # earlier row would stay alive but unreachable through _id_to_row)
        last_by_id = {doc.id: i for i, doc in enumerate(documents)}
        if len(last_by_id) != len(documents):
            keep = sorted(last_by_id.values())
            documents = [documents[i] for i in keep]
            embeddings = embeddings[keep]
        for doc in documents:
            if doc.id in self._id_to_row:  # upsert: tombstone the old row
                self._alive[self._id_to_row[doc.id]] = False
        base = len(self._documents)
        self._embeddings = np.concatenate([self._embeddings, embeddings])
        self._alive = np.concatenate([self._alive, np.ones(len(documents), bool)])
        for off, doc in enumerate(documents):
            self._documents.append(doc)
            self._id_to_row[doc.id] = base + off
        self._device_state = None
        self._maybe_compact()

    def delete(self, ids: Sequence[str]) -> int:
        n = 0
        for doc_id in ids:
            row = self._id_to_row.pop(doc_id, None)
            if row is not None and self._alive[row]:
                self._alive[row] = False
                n += 1
        if n:
            self._device_state = None
            self._maybe_compact()
        return n

    def _maybe_compact(self, dead_fraction: float = 0.25) -> None:
        """Drop tombstoned rows once they pass ``dead_fraction`` of the table
        so churn (daily re-ingest upserts) can't grow host or HBM footprint
        unboundedly — queries never pay matmul FLOPs over mostly-dead rows."""
        total = len(self._documents)
        dead = total - int(self._alive.sum())
        if total == 0 or dead / total <= dead_fraction:
            return
        keep = np.flatnonzero(self._alive)
        self._embeddings = self._embeddings[keep]
        self._documents = [self._documents[i] for i in keep]
        self._alive = np.ones(len(keep), bool)
        self._id_to_row = {doc.id: i for i, doc in enumerate(self._documents)}
        self._device_state = None

    def clear(self) -> None:
        self._embeddings = np.zeros((0, self.dim), np.float32)
        self._documents = []
        self._id_to_row = {}
        self._alive = np.zeros(0, bool)
        self._device_state = None

    # ---------------------------------------------------------------- search

    def _n_shards(self) -> int:
        return int(np.prod(list(self.mesh.shape.values()))) if self.mesh is not None else 1

    def _ensure_device(self):
        """Upload [n_pad, D] corpus (dead rows zeroed → score 0 after the
        -inf masking margin; padded rows likewise) sharded over all axes."""
        if self._device_state is not None:
            return self._device_state
        import jax
        import jax.numpy as jnp

        shards = self._n_shards()
        n = len(self._documents)
        # grow in 25% steps (min 1 row per shard) so appends amortize uploads
        n_pad = max(shards, int(np.ceil(n * 1.25 / shards)) * shards)
        corpus = np.zeros((n_pad, self.dim), np.float32)
        if n:
            corpus[:n] = self._embeddings * self._alive[:, None]
        valid = np.zeros(n_pad, bool)
        valid[:n] = self._alive
        dt = jnp.dtype(self.dtype)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            row_spec = NamedSharding(self.mesh, P(tuple(self.mesh.axis_names), None))
            corpus_dev = jax.device_put(jnp.asarray(corpus, dt), row_spec)
            valid_dev = jax.device_put(
                jnp.asarray(valid), NamedSharding(self.mesh, P(tuple(self.mesh.axis_names)))
            )
        else:
            corpus_dev = jnp.asarray(corpus, dt)
            valid_dev = jnp.asarray(valid)
        self._device_state = (corpus_dev, valid_dev, n_pad)
        return self._device_state

    def search_batch(
        self, queries, top_k: int = 10
    ) -> list[list[tuple[Document, float]]]:
        """queries [Q, D] → per-query (Document, cosine score) descending.

        Accepts host numpy OR a device array (the fused retrieval path hands
        the embedder's output over without a host round trip — queries are
        L2-normalized on whichever side they already live)."""
        import jax
        import jax.numpy as jnp

        on_device = isinstance(queries, jax.Array)
        if not on_device:
            queries = np.asarray(queries, np.float32)
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise DenseIndexError(f"expected queries [Q, {self.dim}], got {queries.shape}")
        if self.size == 0:
            return [[] for _ in range(len(queries))]
        if on_device:
            qn = queries / jnp.maximum(
                jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-9
            )
        else:
            qn = queries / np.maximum(np.linalg.norm(queries, axis=1, keepdims=True), 1e-9)
            qn = jnp.asarray(qn)

        corpus_dev, valid_dev, n_pad = self._ensure_device()
        k = min(top_k, self.size)
        shards = self._n_shards()
        k_local = min(max(k, 1), n_pad // shards)
        k_out = min(k, shards * k_local)

        scores, rows = _topk_fn(self.mesh, self.dtype, k_local, k_out)(
            corpus_dev, valid_dev, qn
        )
        # one blocking fetch for both outputs, not two sequential ones
        scores, rows = jax.device_get((scores, rows))
        scores = np.asarray(scores, np.float32)

        out: list[list[tuple[Document, float]]] = []
        for qi in range(len(queries)):
            hits = []
            for s, r in zip(scores[qi], rows[qi]):
                if s <= -1e29 or len(hits) >= k:
                    break
                hits.append((self._documents[int(r)], float(s)))
            out.append(hits)
        return out

    def search(self, query: np.ndarray, top_k: int = 10) -> list[tuple[Document, float]]:
        return self.search_batch(query[None, :], top_k)[0]

    def retrieve(self, query_embedding: np.ndarray, top_k: int = 10) -> list[Document]:
        out = []
        for doc, score in self.search(query_embedding, top_k):
            meta = dict(doc.metadata)
            meta["score"] = score
            meta["retriever"] = "dense"
            out.append(Document(text=doc.text, metadata=meta, id=doc.id))
        return out

    # ------------------------------------------------------------ persistence

    def save(self, path: str | Path) -> None:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        keep = self._alive
        np.savez_compressed(path.with_suffix(".npz"), embeddings=self._embeddings[keep])
        docs = [self._documents[i].to_dict() for i in np.flatnonzero(keep)]
        path.with_suffix(".json").write_text(json.dumps({"dim": self.dim, "documents": docs}))

    @classmethod
    def load(cls, path: str | Path, mesh=None, dtype: str = "bfloat16") -> "TpuDenseIndex":
        path = Path(path)
        meta = json.loads(path.with_suffix(".json").read_text())
        index = cls(dim=int(meta["dim"]), mesh=mesh, dtype=dtype)
        embeddings = np.load(path.with_suffix(".npz"))["embeddings"]
        docs = [Document.from_dict(d) for d in meta["documents"]]
        if len(docs):
            index.add(docs, embeddings)
        return index


# --------------------------------------------------------------------------
# compiled search kernels, cached per (mesh, dtype, k_local)

_TOPK_CACHE: dict = {}


def _topk_fn(mesh, dtype: str, k_local: int, k_out: int):
    key = (id(mesh) if mesh is not None else None, dtype, k_local, k_out)
    fn = _TOPK_CACHE.get(key)
    if fn is None:
        fn = _build_topk(mesh, dtype, k_local, k_out)
        _TOPK_CACHE[key] = fn
    return fn


def _build_topk(mesh, dtype: str, k_local: int, k_out: int):
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)

    def local_scores(corpus, valid, q):
        s = jnp.einsum("qd,nd->qn", q.astype(dt), corpus).astype(jnp.float32)
        return jnp.where(valid[None, :], s, -jnp.inf)

    if mesh is None:

        @jax.jit
        def single(corpus, valid, q):
            s = local_scores(corpus, valid, q)
            return jax.lax.top_k(s, k_out)

        return single

    # jax moved shard_map out of experimental across the versions this tree
    # supports (same compat-shim pattern as the kernels' TPUCompilerParams
    # rename): 0.4.x only has jax.experimental.shard_map; newer releases
    # expose jax.shard_map and eventually drop the experimental alias.
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # experimental alias removed in newer jax
        from jax import shard_map
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def shard_fn(corpus, valid, q):
        # corpus/valid hold this device's rows; q replicated
        s = local_scores(corpus, valid, q)  # [Q, n_local]
        loc_s, loc_i = jax.lax.top_k(s, k_local)  # [Q, k_local]
        # local row index -> global row index
        first = jax.lax.axis_index(axes[0])
        idx = first
        for a in axes[1:]:
            # static mesh extent (jax.lax.axis_size only exists on newer jax)
            idx = idx * mesh.shape[a] + jax.lax.axis_index(a)
        n_local = corpus.shape[0]
        glob_i = loc_i + idx * n_local
        # gather candidates from every shard, then merge
        all_s = jax.lax.all_gather(loc_s, axes, axis=0, tiled=False)  # [S, Q, k]
        all_i = jax.lax.all_gather(glob_i, axes, axis=0, tiled=False)
        shards = all_s.shape[0]
        cat_s = jnp.moveaxis(all_s, 0, 1).reshape(-1, shards * k_local)
        cat_i = jnp.moveaxis(all_i, 0, 1).reshape(-1, shards * k_local)
        best_s, pos = jax.lax.top_k(cat_s, k_out)
        best_i = jnp.take_along_axis(cat_i, pos, axis=1)
        return best_s, best_i

    # the replication-check kwarg was renamed check_rep -> check_vma along
    # the way; pass whichever this jax understands (the check is disabled
    # either way: all_gather'd outputs are replicated by construction)
    import inspect

    check_kw = (
        "check_vma"
        if "check_vma" in inspect.signature(shard_map).parameters
        else "check_rep"
    )
    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes), P(None, None)),
        out_specs=(P(None, None), P(None, None)),
        **{check_kw: False},
    )
    return jax.jit(fn)
