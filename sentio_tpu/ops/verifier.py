"""AnswerVerifier: LLM self-audit of generated answers.

Parity with /root/reference/src/core/llm/answer_verifier.py:20-88: a
temperature-0, bounded-token audit call that returns a normalized
``{verdict: pass|warn|fail, citations_ok, notes[<=8], revised_answer?}``
verdict, NEVER raises (conservative ``warn`` on any failure), and shares the
generator's weights — on TPU the audit is just another forward pass on the
same sharded params, not a second remote model.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from sentio_tpu.config import GeneratorConfig, get_settings
from sentio_tpu.models.document import Document
from sentio_tpu.ops.generator import LLMGenerator
from sentio_tpu.ops.prompts import PromptBuilder
from sentio_tpu.ops.reply_extractor import extract_json_block

VALID_VERDICTS = ("pass", "warn", "fail")


@dataclass
class VerifyResult:
    verdict: str = "warn"
    citations_ok: bool = True
    notes: list[str] = field(default_factory=list)
    revised_answer: Optional[str] = None

    def to_dict(self) -> dict:
        out = {
            "verdict": self.verdict,
            "citations_ok": self.citations_ok,
            "notes": self.notes,
        }
        if self.revised_answer:
            out["revised_answer"] = self.revised_answer
        return out


@dataclass
class AnswerVerifier:
    generator: LLMGenerator
    config: GeneratorConfig = field(default_factory=lambda: get_settings().generator)
    prompts: PromptBuilder = field(default_factory=PromptBuilder)

    def verify(
        self,
        query: str,
        answer: str,
        documents: Sequence[Document],
        request_id: Optional[str] = None,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> VerifyResult:
        try:
            # the audit prompt EMBEDS the generate prompt verbatim as its
            # head (same instruction profile + context + question, in the
            # same bytes) — on the paged engine the radix prefix cache then
            # serves that whole span from the generate admission's KV pages
            # and this call prefills only the audit tail
            context = self.generator.prepare_context(documents)
            prompt = self.prompts.build(
                "verify",
                instruction=self.prompts.load("profile"),
                context=context,
                query=query,
                answer=answer,
            )
            # the caller's deadline bounds the audit decode too — an
            # expired caller's verification is cancelled like its
            # generation — and the audit admission is charged to the
            # caller's WFQ tenant (a flooding tenant's verify traffic
            # competes inside ITS quota, not against everyone)
            reply = self.generator.chat_raw(
                prompt,
                max_new_tokens=self.config.verifier_max_tokens,
                temperature=0.0,
                request_id=request_id,
                deadline_ts=deadline_ts,
                tenant=tenant,
                priority=priority,
            )
            return self._normalize(reply)
        except Exception as exc:  # noqa: BLE001 — the audit must never 500
            return VerifyResult(verdict="warn", notes=[f"verifier error: {exc}"])

    def _normalize(self, reply: str) -> VerifyResult:
        extracted = extract_json_block(reply)
        if not extracted.ok:
            return VerifyResult(verdict="warn", notes=[f"unparseable audit: {extracted.error}"])
        data = extracted.payload
        verdict = str(data.get("verdict", "warn")).lower()
        if verdict not in VALID_VERDICTS:
            verdict = "warn"
        notes_raw = data.get("notes", [])
        if isinstance(notes_raw, str):
            notes_raw = [notes_raw]
        notes = [str(n) for n in notes_raw][:8]
        revised = data.get("revised_answer")
        return VerifyResult(
            verdict=verdict,
            citations_ok=bool(data.get("citations_ok", True)),
            notes=notes,
            revised_answer=str(revised) if revised else None,
        )
