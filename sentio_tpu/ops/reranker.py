"""Rerankers: TPU cross-encoder scoring with the reference's fallback contract.

Parity with /root/reference/src/core/rerankers/: the ``Reranker`` interface
(base.py:85-131), the registry (``__init__.py:11-30``), and the Jina
reranker's degradation contract (jina_reranker.py:297-322) — on ANY failure
the original order is kept with decaying scores ``1.0 - 0.1*idx``. The
remote API call is replaced by one batched cross-encoder forward: all
(query, doc) pairs ride a single device dispatch (jina_reranker.py:120-154
became models/cross_encoder.py scoring).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from sentio_tpu.config import RerankConfig, get_settings
from sentio_tpu.infra import faults
from sentio_tpu.models.document import Document

logger = logging.getLogger(__name__)


@dataclass
class RerankingResult:
    documents: list[Document]
    scores: list[float]
    model: str
    fallback_used: bool = False


class Reranker:
    """rerank(query, docs, top_k) → RerankingResult; async executor wrap."""

    name = "base"

    def _score(self, query: str, documents: Sequence[Document]) -> np.ndarray:
        raise NotImplementedError

    def rerank(
        self, query: str, documents: Sequence[Document], top_k: Optional[int] = None
    ) -> RerankingResult:
        documents = list(documents)
        if not documents:
            return RerankingResult([], [], self.name)
        top_k = top_k if top_k is not None else len(documents)
        try:
            faults.hit("reranker.score")
            scores = np.asarray(self._score(query, documents), np.float32)
            if scores.shape != (len(documents),):
                raise ValueError(f"scorer returned shape {scores.shape}")
        except Exception:
            logger.exception("%s rerank failed; keeping original order", self.name)
            return self._default_ranking(documents, top_k)
        order = np.argsort(-scores, kind="stable")[:top_k]
        out_docs, out_scores = [], []
        for i in order:
            doc = documents[int(i)]
            meta = dict(doc.metadata)
            # drop the fused score: Document.score() prefers hybrid_score, and
            # a stale one would make downstream sort-by-score undo the rerank
            meta.pop("hybrid_score", None)
            meta["rerank_score"] = float(scores[int(i)])
            meta["score"] = float(scores[int(i)])
            out_docs.append(Document(text=doc.text, metadata=meta, id=doc.id))
            out_scores.append(float(scores[int(i)]))
        return RerankingResult(out_docs, out_scores, self.name)

    def _default_ranking(self, documents: list[Document], top_k: int) -> RerankingResult:
        """Original order, decaying scores 1.0 − 0.1·idx floored at 0.1."""
        docs, scores = [], []
        for i, doc in enumerate(documents[:top_k]):
            score = max(1.0 - 0.1 * i, 0.1)
            meta = dict(doc.metadata)
            meta.pop("hybrid_score", None)
            meta["rerank_score"] = score
            meta["score"] = score
            docs.append(Document(text=doc.text, metadata=meta, id=doc.id))
            scores.append(score)
        return RerankingResult(docs, scores, self.name, fallback_used=True)

    async def arerank(
        self, query: str, documents: Sequence[Document], top_k: Optional[int] = None
    ) -> RerankingResult:
        return await asyncio.get_running_loop().run_in_executor(
            None, self.rerank, query, list(documents), top_k
        )


class PassthroughReranker(Reranker):
    """Keeps retrieval order (scores preserved) — the USE_RERANKER=false path."""

    name = "passthrough"

    def _score(self, query: str, documents: Sequence[Document]) -> np.ndarray:
        return np.asarray([d.score(1.0 - 0.01 * i) for i, d in enumerate(documents)], np.float32)


class CrossEncoderReranker(Reranker):
    """Batched (query, doc) pair scoring on the device mesh."""

    name = "cross_encoder"

    def __init__(
        self,
        config: Optional[RerankConfig] = None,
        params=None,
        model_config=None,
        tokenizer=None,
        mesh=None,
    ) -> None:
        import jax

        from sentio_tpu.models.cross_encoder import cross_encoder_scores, init_cross_encoder
        from sentio_tpu.models.tokenizer import ByteTokenizer
        from sentio_tpu.models.transformer import EncoderConfig

        self.config = config or get_settings().rerank
        if params is None and self.config.checkpoint_path:
            # real weights: a `cli convert cross-encoder` checkpoint
            from sentio_tpu.runtime.weights import load_model

            params, model_config, ck_tok = load_model(
                self.config.checkpoint_path, expect_family="cross-encoder",
                tokenizer_path=self.config.tokenizer_path,
            )
            tokenizer = tokenizer or ck_tok
        self.model_config = model_config or EncoderConfig.tiny()
        self.tokenizer = tokenizer or ByteTokenizer(self.model_config.vocab_size)
        if params is None:
            params = init_cross_encoder(jax.random.PRNGKey(7), self.model_config)
        if mesh is not None:
            from sentio_tpu.parallel.sharding import ENCODER_TP_RULES, shard_params

            params = shard_params(params, mesh, ENCODER_TP_RULES)
        self.params = params
        cfg = self.model_config
        # bidirectional flash kernel for pair scoring — policy lives in
        # kernels.select_encoder_attn_fn (shared with the embedder)
        from sentio_tpu.kernels import select_encoder_attn_fn

        attn_fn = select_encoder_attn_fn(mesh, cfg.n_heads)

        def fwd(p, ids, mask, types):
            return cross_encoder_scores(p, cfg, ids, mask, types, attn_fn=attn_fn)

        self._fwd = jax.jit(fwd)

    def _score(self, query: str, documents: Sequence[Document]) -> np.ndarray:
        import jax.numpy as jnp

        from sentio_tpu.models.tokenizer import batch_encode_pairs
        from sentio_tpu.parallel.batcher import bucket_size

        max_len = min(self.config.max_pair_tokens, self.model_config.max_len)
        pairs = [(query, d.content) for d in documents]
        scores = np.zeros(len(pairs), np.float32)
        for start in range(0, len(pairs), self.config.batch_size):
            chunk = pairs[start : start + self.config.batch_size]
            ids, mask, types = batch_encode_pairs(self.tokenizer, chunk, max_len)
            rows = bucket_size(len(chunk), (1, 2, 4, 8, 16, 32))
            pad = rows - len(chunk)
            if pad:
                ids = np.pad(ids, ((0, pad), (0, 0)), constant_values=self.tokenizer.pad_id)
                mask = np.pad(mask, ((0, pad), (0, 0)))
                mask[len(chunk):, 0] = True  # keep softmax rows non-degenerate
                types = np.pad(types, ((0, pad), (0, 0)))
            out = self._fwd(self.params, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(types))
            scores[start : start + len(chunk)] = np.asarray(out)[: len(chunk)]
        return scores


_RERANKERS = {
    "cross_encoder": CrossEncoderReranker,
    "passthrough": PassthroughReranker,
}


def get_reranker(kind: Optional[str] = None, **kwargs) -> Reranker:
    kind = kind or get_settings().rerank.kind
    cls = _RERANKERS.get(kind)
    if cls is None:
        raise ValueError(f"unknown reranker {kind!r}; known: {sorted(_RERANKERS)}")
    return cls(**kwargs) if cls is CrossEncoderReranker else cls()
