"""Text chunking — own recursive splitter, no LangChain dependency.

Parity target: the reference's ``TextChunker`` wrapping LangChain's
``RecursiveCharacterTextSplitter`` (/root/reference/src/core/chunking/
text_splitter.py:23-196): strategies ``recursive`` and ``fixed``, size/overlap
knobs, ``parent_id`` preserved in chunk metadata, stats. Same separator
hierarchy (paragraph → line → sentence → word → char), greedy packing with
character overlap.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Iterable

from sentio_tpu.config import ChunkingConfig
from sentio_tpu.models.document import Document

_SEPARATORS = ["\n\n", "\n", ". ", " ", ""]
_SENTENCE_RE = re.compile(r"(?<=[.!?])\s+")


class ChunkingError(Exception):
    pass


def _split_on(text: str, separator: str) -> list[str]:
    """Split keeping the separator attached to the preceding piece so that
    re-joining chunks loses no characters."""
    if separator == "":
        return list(text)
    parts = text.split(separator)
    out = []
    for i, part in enumerate(parts):
        if i < len(parts) - 1:
            part = part + separator
        if part:
            out.append(part)
    return out


def _recursive_split(text: str, size: int, separators: list[str]) -> list[str]:
    """Break text into pieces each <= size, preferring coarse separators."""
    if len(text) <= size:
        return [text] if text else []
    sep, rest = separators[0], separators[1:]
    pieces = _split_on(text, sep)
    out: list[str] = []
    for piece in pieces:
        if len(piece) <= size:
            out.append(piece)
        elif rest:
            out.extend(_recursive_split(piece, size, rest))
        else:  # single char pieces can't exceed size; defensive
            out.extend(piece[i : i + size] for i in range(0, len(piece), size))
    return out


def _pack(pieces: Iterable[str], size: int, overlap: int) -> list[str]:
    """Greedily merge pieces into chunks of <= size chars with char overlap
    carried from the tail of the previous chunk."""
    chunks: list[str] = []
    current = ""
    for piece in pieces:
        if current and len(current) + len(piece) > size:
            chunks.append(current)
            carry = current[len(current) - overlap :] if overlap > 0 else ""
            # the carried overlap may not crowd out the incoming piece
            keep = max(0, size - len(piece))
            current = carry[len(carry) - keep :] if keep and carry else ""
        current += piece
        step = size - overlap  # > 0, validated by TextChunker
        while len(current) > size:  # a single piece longer than size (no finer separator)
            chunks.append(current[:size])
            current = current[step:]
    if current.strip():
        chunks.append(current)
    return [c.strip() for c in chunks if c.strip()]


@dataclass
class TextChunker:
    config: ChunkingConfig = field(default_factory=ChunkingConfig)
    _stats: dict = field(default_factory=lambda: {"documents": 0, "chunks": 0, "chars": 0})

    def __post_init__(self) -> None:
        if self.config.chunk_size <= 0:
            raise ChunkingError("chunk_size must be positive")
        if self.config.chunk_overlap < 0 or self.config.chunk_overlap >= self.config.chunk_size:
            raise ChunkingError("chunk_overlap must be in [0, chunk_size)")
        if self.config.strategy not in ("recursive", "fixed", "sentence"):
            raise ChunkingError(f"unknown strategy {self.config.strategy!r}")

    def split_text(self, text: str) -> list[str]:
        size, overlap = self.config.chunk_size, self.config.chunk_overlap
        if not text or not text.strip():
            return []
        if self.config.strategy == "fixed":
            step = size - overlap
            return [
                text[i : i + size].strip()
                for i in range(0, max(len(text) - overlap, 1), step)
                if text[i : i + size].strip()
            ]
        if self.config.strategy == "sentence":
            sentences = [s for s in _SENTENCE_RE.split(text) if s]
            pieces: list[str] = []
            for sent in sentences:  # sentences longer than size still need breaking
                pieces.extend(_recursive_split(sent, size, _SEPARATORS[1:]))
            return _pack(pieces, size, overlap)
        pieces = _recursive_split(text, size, _SEPARATORS)
        return _pack(pieces, size, overlap)

    def split(self, documents: list[Document]) -> list[Document]:
        out: list[Document] = []
        for doc in documents:
            texts = self.split_text(doc.content)
            for idx, chunk_text in enumerate(texts):
                meta = dict(doc.metadata)
                meta.update(
                    {
                        "parent_id": doc.id,
                        "chunk_index": idx,
                        "chunk_count": len(texts),
                        "chunking_strategy": self.config.strategy,
                    }
                )
                out.append(Document(text=chunk_text, metadata=meta, id=f"{doc.id}:{idx}"))
            self._stats["documents"] += 1
            self._stats["chunks"] += len(texts)
            self._stats["chars"] += len(doc.content)
        return out

    def get_stats(self) -> dict:
        stats = dict(self._stats)
        stats["avg_chunk_chars"] = (
            round(stats["chars"] / stats["chunks"], 1) if stats["chunks"] else 0.0
        )
        return stats
